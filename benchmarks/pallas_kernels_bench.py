"""Pallas kernel benchmarks vs the XLA-compiled baselines, on real TPU.

VERDICT r2 item 5 'done' criterion: kernel-level speedup numbers in
benchmarks/.  Measures, at Llama-8B-proxy shapes:

* flash attention fwd+bwd — Pallas kernels (fwd + the new dq/dkv backward
  kernels) vs XLA's fusion of the dense softmax attention, and vs the
  blockwise-jax backward that the Pallas backward replaces;
* fused residual+RMSNorm — one Pallas pass vs the XLA elementwise chain.

Run ON THE CHIP: python benchmarks/pallas_kernels_bench.py
(prints one JSON line; falls back to interpret off-TPU, which is only a
correctness smoke, not a measurement).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def _timeit(step_scalar, *args, iters=20):
    """step_scalar(carry, *args) -> scalar.  The timing loop runs INSIDE
    one jitted fori_loop (a data-dependent carry defeats hoisting), so a
    single dispatch amortizes the tunneled chip's RPC latency; np.asarray
    forces completion."""
    import jax
    from jax import lax

    @jax.jit
    def run(*a):
        def body(i, carry):
            return carry + step_scalar(carry, *a)
        return lax.fori_loop(0, iters, body, 0.0)

    np.asarray(run(*args))                        # compile + warm
    t0 = time.perf_counter()
    out = run(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / iters


def bench_flash(b=4, s=2048, h=16, hk=8, d=128, dtype="bfloat16"):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.attention import _sdpa_reference
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(0)
    dt = jnp.dtype(dtype)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dt)
    k = jnp.asarray(rng.standard_normal((b, s, hk, d)), dt)
    v = jnp.asarray(rng.standard_normal((b, s, hk, d)), dt)

    def train(attn):
        def loss(args):
            o = attn(*args)
            return jnp.mean(o.astype(jnp.float32) ** 2)

        def scalar_step(carry, q, k, v):
            # carry-dependent perturbation: keeps each loop iteration live
            q = q * (1 + carry * 1e-12).astype(q.dtype)
            g = jax.grad(loss)((q, k, v))
            return sum(jnp.sum(jnp.abs(x).astype(jnp.float32))
                       for x in g)
        return scalar_step

    # pinned variants/blocks: the comparison must measure the backward
    # IMPLEMENTATIONS, not whatever the autotuner happens to select
    pallas = train(lambda q, k, v: flash_attention(
        q, k, v, causal=True, interpret=not on_tpu, pallas_bwd=True,
        block_q=128, block_k=128))
    pallas_jaxbwd = train(lambda q, k, v: flash_attention(
        q, k, v, causal=True, interpret=not on_tpu, pallas_bwd=False,
        block_q=128, block_k=128))
    xla = train(lambda q, k, v: _sdpa_reference(
        q, jnp.repeat(k, h // hk, 2), jnp.repeat(v, h // hk, 2),
        is_causal=True))

    t_pallas = _timeit(pallas, q, k, v)
    t_jaxbwd = _timeit(pallas_jaxbwd, q, k, v)
    t_xla = _timeit(xla, q, k, v)
    return {"shape": f"b{b} s{s} h{h}/{hk} d{d} {dtype}",
            "pallas_ms": round(t_pallas * 1e3, 3),
            "pallas_fwd_jax_bwd_ms": round(t_jaxbwd * 1e3, 3),
            "xla_dense_ms": round(t_xla * 1e3, 3),
            "speedup_vs_xla": round(t_xla / t_pallas, 2),
            "bwd_kernel_speedup": round(t_jaxbwd / t_pallas, 2)}


def bench_rmsnorm(rows=8192, d=4096, dtype="bfloat16"):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.rmsnorm import fused_rmsnorm

    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(0)
    dt = jnp.dtype(dtype)
    x = jnp.asarray(rng.standard_normal((rows, d)), dt)
    r = jnp.asarray(rng.standard_normal((rows, d)), dt)
    w = jnp.asarray(rng.standard_normal((d,)), jnp.float32)

    def fused(carry, x, w, r):
        x = x * (1 + carry * 1e-12).astype(x.dtype)
        y, h = fused_rmsnorm(x, w, residual=r, interpret=not on_tpu)
        return jnp.sum(jnp.abs(y).astype(jnp.float32)) + \
            jnp.sum(jnp.abs(h).astype(jnp.float32))

    def xla(carry, x, w, r):
        x = x * (1 + carry * 1e-12).astype(x.dtype)
        hf = x.astype(jnp.float32) + r.astype(jnp.float32)
        inv = jax.lax.rsqrt(jnp.mean(hf * hf, -1, keepdims=True) + 1e-5)
        y, h = (hf * inv * w).astype(x.dtype), hf.astype(x.dtype)
        return jnp.sum(jnp.abs(y).astype(jnp.float32)) + \
            jnp.sum(jnp.abs(h).astype(jnp.float32))

    t_f = _timeit(fused, x, w, r)
    t_x = _timeit(xla, x, w, r)
    return {"shape": f"{rows}x{d} {dtype}",
            "fused_ms": round(t_f * 1e3, 3),
            "xla_ms": round(t_x * 1e3, 3),
            "speedup": round(t_x / t_f, 2)}


def main():
    import jax

    backend = jax.default_backend()
    out = {"backend": backend,
           "flash": bench_flash(),
           "rmsnorm": bench_rmsnorm()}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
