"""Compiled KV-cache decode throughput on the chip — the serving-side
number (reference role: the fused_multi_transformer inference path that
ERNIE serving runs on; here generation/__init__.py's compiled per-token
step over StaticCache).

Measures greedy decode tokens/sec at a Llama-proportioned single-chip
model (b=8, prompt 128, 512 new tokens, bf16).  Prints one JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import paddle_tpu as pp
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=1024,
            rope_theta=500000.0, dtype="bfloat16")
        batch, prompt_len, new_tokens = 8, 128, 512
    else:
        cfg = LlamaConfig.tiny()
        batch, prompt_len, new_tokens = 2, 8, 16

    pp.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size,
                       (batch, prompt_len)).astype(np.int32)

    def run(n):
        out = model.generate(ids, max_new_tokens=n, do_sample=False)
        np.asarray(out)

    half = new_tokens // 2
    run(new_tokens)           # compile + warm (both shapes)
    run(half)
    # prefill time cancels in the delta: pure per-token decode rate
    t0 = time.perf_counter()
    run(new_tokens)
    t_full = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(half)
    t_half = time.perf_counter() - t0
    decode_dt = max(t_full - t_half, 1e-9)
    tok_s = batch * (new_tokens - half) / decode_dt
    print(json.dumps({
        "metric": "decode_tokens_per_sec",
        "value": round(tok_s, 1), "unit": "tok/s",
        "detail": {"batch": batch, "prompt_len": prompt_len,
                   "new_tokens": new_tokens,
                   "per_seq_tok_s": round(tok_s / batch, 1),
                   "params": n_params,
                   "device": getattr(dev, "device_kind", dev.platform),
                   "wall_full_s": round(t_full, 3),
                   "wall_half_s": round(t_half, 3)}}), flush=True)

    # continuous batching + int8: a realistic request stream (mixed
    # prompt/response lengths) through the slot-reuse engine — the thing
    # that separates a serving engine from a fixed-batch loop
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    def stream_bench(int8: bool):
        import os as _os
        K = int(_os.environ.get("PT_SERVE_K", "16")) if on_tpu else 2
        eng = ContinuousBatchingEngine(
            model, slots=batch, max_len=prompt_len + new_tokens + K + 2,
            prefill_buckets=(32, 64, 128) if on_tpu else (8, 16),
            int8_weights=int8, steps_per_sync=K)
        rng2 = np.random.default_rng(7)
        n_req = 3 * batch
        lens = rng2.integers(prompt_len // 2, prompt_len + 1, n_req)
        news = rng2.integers(new_tokens // 2, new_tokens + 1, n_req)
        # warm every executable (both buckets + the decode step)
        eng.add_request(rng2.integers(0, cfg.vocab_size,
                                      (prompt_len // 2,)), 4)
        eng.add_request(rng2.integers(0, cfg.vocab_size,
                                      (prompt_len,)), 4)
        eng.run()
        t0 = time.perf_counter()
        for i in range(n_req):
            eng.add_request(
                rng2.integers(0, cfg.vocab_size, (int(lens[i]),)),
                int(news[i]))
        results = eng.run()
        dt = time.perf_counter() - t0
        total = sum(len(v[1]) for v in results.values())
        print(json.dumps({
            "metric": ("decode_continuous_int8_tokens_per_sec" if int8
                       else "decode_continuous_tokens_per_sec"),
            "value": round(total / dt, 1), "unit": "tok/s",
            "detail": {"slots": batch, "requests": n_req,
                       "generated_tokens": total,
                       "wall_s": round(dt, 3),
                       "steps_per_sync": K,
                       "int8_weights": int8}}), flush=True)

    stream_bench(int8=False)
    stream_bench(int8=True)


if __name__ == "__main__":
    main()
