"""ERNIE encoder pretraining MFU on the chip — the BASELINE ERNIE-4.5
config-matrix slot's encoder half (the decoder half is the MoE bench's
ERNIE-4.5-style heterogeneous-MoE program).

Full masked-LM train step (fwd + bwd + AdamW fp32-master) of an
ERNIE-3.0-base-proportioned encoder (L12 d768 h12, tied-embedding MLM
head) at b32 s512 bf16, 15% mask rate — the knowledge-masking
pretraining shape.  Prints one JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _timed_scalar(x, i):
    t0 = time.perf_counter()
    _ = float(x + i)
    return time.perf_counter() - t0


def main():
    import jax
    import paddle_tpu as pp
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.ernie import ErnieConfig, ErnieForMaskedLM
    from bench import _PEAK

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = ErnieConfig(
            vocab_size=40000, hidden_size=768, num_hidden_layers=12,
            num_attention_heads=12, intermediate_size=3072,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            max_position_embeddings=512, dtype="bfloat16")
        import os
        batch = int(os.environ.get("PT_ERNIE_BATCH", "32"))
        seq, iters, warmup = 512, 10, 3
    else:
        cfg = ErnieConfig.tiny()
        batch, seq, iters, warmup = 2, 32, 2, 1

    pp.seed(0)
    model = ErnieForMaskedLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    step = TrainStep(model, opt)
    n_params = sum(int(np.prod(a.shape)) for a in step.params.values())

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq))
    labels = np.where(rng.random((batch, seq)) < 0.15, ids, -100)
    batch_dict = {"input_ids": ids, "labels": labels}
    for _ in range(warmup):
        loss = step(batch_dict)
    # tunnel-proof sync: block_until_ready does not reliably wait through
    # the tunneled chip and this model is small enough that dispatch does
    # not throttle — end every window with a host transfer of the chained
    # loss and subtract the measured scalar round-trip
    _ = float(loss)
    t_xfer = min(_timed_scalar(loss, i) for i in range(3))
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(batch_dict)
        _ = float(loss)
        windows.append((time.perf_counter() - t0 - t_xfer) / iters)
    dt = min(windows)

    tokens = batch * seq
    flops_per_token = 6 * n_params + \
        12 * cfg.num_hidden_layers * seq * cfg.hidden_size
    kind = getattr(dev, "device_kind", "").lower()
    peak = next((v for k, v in sorted(_PEAK.items(),
                                      key=lambda kv: -len(kv[0]))
                 if k in kind), 197e12)
    mfu = flops_per_token * tokens / dt / peak
    print(json.dumps({
        "metric": "ernie_mlm_pretrain_mfu", "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "detail": {"params": n_params,
                   "tokens_per_sec_per_chip": round(tokens / dt, 1),
                   "step_time_s": round(dt, 4),
                   "step_time_mean_s": round(sum(windows) / len(windows),
                                             4),
                   "batch": batch, "seq": seq,
                   "device": getattr(dev, "device_kind", dev.platform),
                   "final_loss": float(loss)}}), flush=True)


if __name__ == "__main__":
    main()
