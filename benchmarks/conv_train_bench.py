"""Conv-family train MFU on the chip — the BASELINE PP-OCRv4 slot
(VERDICT r4 Missing #2 / Next #3).

Two measured sections:
  1. ResNet-50 classification train step (fwd + bwd + SGD-momentum,
     bf16 compute / fp32 master) at 224x224 — the conv-kernel substrate
     the reference lowers through cudnn (phi/kernels/gpudnn/
     conv_kernel.cu); here XLA lowers jax.lax.conv onto the MXU.
  2. A CRNN-style text recognizer (conv backbone -> BiLSTM -> CTC), the
     PP-OCRv4 recognition architecture class (SVTR/CRNN family).

FLOPs come from XLA's own cost analysis of the compiled step
(compiled.cost_analysis()['flops']) — exact for conv nets, no analytic
approximation.  Prints one JSON line per section.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def _measure(step, args, iters, warmup):
    """Tunnel-proof timing: block_until_ready does NOT reliably wait for
    remote execution through the tunneled chip, so each window ends with
    a host transfer of the (chained, donated) loss — which can't complete
    before every step in the window has.  The scalar round-trip cost is
    measured separately and subtracted; min of 3 windows."""
    state = args
    for _ in range(warmup):
        loss, state = step(*state)
    _ = float(loss)                       # real drain
    t_xfer = min(_timed_scalar(loss, i) for i in range(3))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, state = step(*state)
        _ = float(loss)
        best = min(best, (time.perf_counter() - t0 - t_xfer) / iters)
    return best, float(loss)


def _timed_scalar(x, i):
    t0 = time.perf_counter()
    _ = float(x + i)
    return time.perf_counter() - t0


def _flops_of(step, args):
    """XLA's flop count for one compiled step; None when the backend
    doesn't expose cost analysis."""
    try:
        compiled = step.lower(*args).compile()
        fa = compiled.cost_analysis()
        if isinstance(fa, list):
            fa = fa[0]
        return float(fa.get("flops", 0.0)) or None
    except Exception:
        return None


def _sgdm_step_factory(model, loss_of_output, lr=0.1):
    """jitted (params, mom, batch...) -> loss, (params, mom, batch...)
    with SGD-momentum on fp32 master weights."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.functional import functional_call

    def loss_fn(ps, *data):
        return loss_of_output(ps, *data)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(ps, mom, *data):
        l, g = jax.value_and_grad(loss_fn)(ps, *data)

        def upd(p, m, gr):
            m2 = 0.9 * m + gr.astype(jnp.float32)
            w = p.astype(jnp.float32) - lr * m2
            return w.astype(p.dtype), m2

        new = jax.tree.map(upd, ps, mom, g)
        ps2 = jax.tree.map(lambda x: x[0], new,
                           is_leaf=lambda x: isinstance(x, tuple))
        mom2 = jax.tree.map(lambda x: x[1], new,
                            is_leaf=lambda x: isinstance(x, tuple))
        return l, (ps2, mom2, *data)

    return step


def bench_resnet50(on_tpu, peak):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pp
    from paddle_tpu.core.dispatch import unwrap
    from paddle_tpu.core.functional import functional_call, params_of
    from paddle_tpu.vision.models import resnet50, resnet18

    pp.seed(0)
    if on_tpu:
        import os
        model, batch, size, iters, warmup = resnet50(num_classes=1000), \
            int(os.environ.get("PT_CONV_BATCH", "128")), 224, 30, 3
    else:
        model, batch, size, iters, warmup = resnet18(num_classes=10), \
            2, 32, 2, 1
    dt_ = jnp.bfloat16 if on_tpu else jnp.float32
    params = params_of(model)
    if on_tpu:
        params = jax.tree.map(lambda a: a.astype(dt_)
                              if a.dtype == jnp.float32 else a, params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 3, size, size)), dt_)
    y = jnp.asarray(rng.integers(0, 10, (batch,)), jnp.int32)

    def loss_of(ps, x, y):
        logits = unwrap(functional_call(model, ps, pp.Tensor(x)))
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(logp, y[:, None], axis=1).mean()

    mom = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    step = _sgdm_step_factory(model, loss_of)
    flops = _flops_of(step, (params, mom, x, y))
    dt, loss = _measure(step, (params, mom, x, y), iters, warmup)
    n_params = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(params))
    mfu = (flops / dt / peak) if flops else None
    print(json.dumps({
        "metric": "resnet50_train_mfu",
        "value": round(mfu, 4) if mfu else None,
        "unit": "fraction_of_peak",
        "detail": {"images_per_sec": round(batch / dt, 1),
                   "step_time_s": round(dt, 4),
                   "hlo_gflops_per_step": round(flops / 1e9, 1)
                   if flops else None,
                   "params": n_params, "batch": batch, "size": size,
                   "final_loss": loss}}), flush=True)


class _CRNN:
    """Conv backbone -> BiLSTM -> per-timestep charset logits (the
    PP-OCR CRNN recognizer shape), as one Layer so functional_call
    binds all params."""

    def __new__(cls, charset=96, hidden=256):
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.layer import Layer

        class CRNN(Layer):
            def __init__(self):
                super().__init__()
                self.net = nn.Sequential(
                    nn.Conv2D(3, 64, 3, stride=1, padding=1), nn.ReLU(),
                    nn.MaxPool2D(2, 2),
                    nn.Conv2D(64, 128, 3, stride=1, padding=1), nn.ReLU(),
                    nn.MaxPool2D(2, 2),
                    nn.Conv2D(128, 256, 3, stride=1, padding=1), nn.ReLU(),
                    nn.Conv2D(256, 256, 3, stride=(2, 1), padding=1),
                    nn.ReLU(),
                    nn.Conv2D(256, 512, 3, stride=1, padding=1), nn.ReLU(),
                    nn.Conv2D(512, 512, 3, stride=(2, 1), padding=1),
                    nn.ReLU(),
                    nn.Conv2D(512, 512, 2, stride=(2, 1), padding=0),
                    nn.ReLU(),
                )
                self.rnn = nn.LSTM(512, hidden, num_layers=2,
                                   direction="bidirectional")
                self.head = nn.Linear(2 * hidden, charset + 1)  # +1 blank

            def forward(self, x):
                """[b,3,H,W] -> log-probs [T, b, charset+1]."""
                import jax
                import jax.numpy as jnp
                import paddle_tpu as pp
                from paddle_tpu.core.dispatch import unwrap
                feat = unwrap(self.net(x))               # [b, C, 1, W']
                seq = feat[:, :, 0, :].transpose(0, 2, 1)  # [b, W', C]
                out, _ = self.rnn(pp.Tensor(seq))
                logits = unwrap(self.head(out))          # [b, W', K]
                return jax.nn.log_softmax(
                    logits.astype(jnp.float32), axis=-1).transpose(1, 0, 2)

        return CRNN()


def bench_crnn(on_tpu, peak):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pp
    from paddle_tpu.core.dispatch import unwrap
    from paddle_tpu.core.functional import functional_call, params_of
    from paddle_tpu.nn import functional as F

    pp.seed(1)
    charset = 96
    model = _CRNN(charset=charset, hidden=256 if on_tpu else 32)
    if on_tpu:
        batch, H, W, iters, warmup = 64, 32, 320, 20, 3
    else:
        batch, H, W, iters, warmup = 2, 32, 64, 2, 1
    label_len = 24 if on_tpu else 4

    dt_ = jnp.bfloat16 if on_tpu else jnp.float32
    params = params_of(model)
    if on_tpu:
        params = jax.tree.map(lambda a: a.astype(dt_)
                              if a.dtype == jnp.float32 else a, params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 3, H, W)), dt_)
    labels = jnp.asarray(rng.integers(1, charset, (batch, label_len)),
                         jnp.int32)

    def loss_of(ps, x, labels):
        logp = unwrap(functional_call(model, ps, pp.Tensor(x)))
        T = logp.shape[0]
        input_lengths = jnp.full((batch,), T, jnp.int32)
        label_lengths = jnp.full((batch,), label_len, jnp.int32)
        return unwrap(F.ctc_loss(logp, labels, input_lengths,
                                 label_lengths, blank=0,
                                 reduction="mean"))

    step = _sgdm_step_factory(model, loss_of, lr=0.05)

    mom = {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()}
    flops = _flops_of(step, (params, mom, x, labels))
    dt, loss = _measure(step, (params, mom, x, labels), iters, warmup)
    n_params = sum(int(np.prod(a.shape)) for a in params.values())
    mfu = (flops / dt / peak) if flops else None
    print(json.dumps({
        "metric": "crnn_ocr_train_mfu",
        "value": round(mfu, 4) if mfu else None,
        "unit": "fraction_of_peak",
        "detail": {"images_per_sec": round(batch / dt, 1),
                   "step_time_s": round(dt, 4),
                   "hlo_gflops_per_step": round(flops / 1e9, 1)
                   if flops else None,
                   "params": n_params, "batch": batch,
                   "input": [H, W], "charset": charset,
                   "final_loss": loss}}), flush=True)


def main():
    import jax
    from bench import _PEAK

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    kind = getattr(dev, "device_kind", "").lower()
    peak = next((v for k, v in sorted(_PEAK.items(),
                                      key=lambda kv: -len(kv[0]))
                 if k in kind), 197e12)
    bench_resnet50(on_tpu, peak)
    bench_crnn(on_tpu, peak)


if __name__ == "__main__":
    main()
