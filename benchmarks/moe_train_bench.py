"""MoE-LLM end-to-end train MFU on the chip (BASELINE DeepSeekMoE /
Qwen2-MoE family; VERDICT r3 #1a).

Full train step (fwd+bwd+AdamW) of a DeepSeekMoE-shaped decoder (shared
+ routed experts, top-k dense-einsum dispatch — the same program GSPMD
turns into all-to-alls on an ep mesh).  MFU counts ACTIVATED FLOPs
(6 * activated-params per token + attention), the standard MoE
accounting: idle experts do no math.

Prints one JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import paddle_tpu as pp
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import MoEConfig, MoEForCausalLM
    from bench import _PEAK

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = MoEConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            moe_intermediate_size=1024, num_hidden_layers=6,
            num_attention_heads=8, num_key_value_heads=8, num_experts=16,
            num_experts_per_tok=2, num_shared_experts=1,
            first_k_dense_replace=1, max_position_embeddings=2048,
            capacity_factor=1.25, dispatch_mode="index", dtype="bfloat16")
        batch, seq, iters, warmup = 4, 2048, 8, 2
    else:
        cfg = MoEConfig.tiny()
        batch, seq, iters, warmup = 2, 64, 2, 1

    pp.seed(0)
    model = MoEForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    step = TrainStep(model, opt)
    n_params = sum(int(np.prod(a.shape)) for a in step.params.values())
    # activated = total minus the (E - top_k) routed experts idle per token
    n_moe_layers = cfg.num_hidden_layers - cfg.first_k_dense_replace
    idle = n_moe_layers * (cfg.num_experts - cfg.num_experts_per_tok) \
        * 3 * cfg.hidden_size * cfg.moe_intermediate_size
    activated = n_params - idle

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    batch_dict = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    for _ in range(warmup):
        step(batch_dict)
    jax.block_until_ready(step.params)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(batch_dict)
    jax.block_until_ready(step.params)
    dt = (time.perf_counter() - t0) / iters

    tokens = batch * seq
    flops_per_token = 6 * activated + \
        12 * cfg.num_hidden_layers * seq * cfg.hidden_size
    kind = getattr(dev, "device_kind", "").lower()
    peak = next((v for k, v in sorted(_PEAK.items(),
                                      key=lambda kv: -len(kv[0]))
                 if k in kind), 459e12)
    mfu = flops_per_token * tokens / dt / peak
    print(json.dumps({
        "metric": "moe_pretrain_mfu", "value": round(mfu, 4),
        "unit": "fraction_of_peak_activated_flops",
        "detail": {"params_total": n_params, "params_activated": activated,
                   "experts": cfg.num_experts,
                   "top_k": cfg.num_experts_per_tok,
                   "tokens_per_sec_per_chip": round(tokens / dt, 1),
                   "step_time_s": round(dt, 4), "batch": batch, "seq": seq,
                   "device": getattr(dev, "device_kind", dev.platform),
                   "final_loss": float(loss)}}), flush=True)


if __name__ == "__main__":
    main()
