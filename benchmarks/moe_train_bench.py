"""MoE-LLM end-to-end train MFU on the chip (BASELINE DeepSeekMoE /
Qwen2-MoE family; VERDICT r3 #1a).

Full train step (fwd+bwd+AdamW) of a DeepSeekMoE-shaped decoder (shared
+ routed experts, top-k dense-einsum dispatch — the same program GSPMD
turns into all-to-alls on an ep mesh).  MFU counts ACTIVATED FLOPs
(6 * activated-params per token + attention), the standard MoE
accounting: idle experts do no math.

Prints one JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import os
    import jax
    import paddle_tpu as pp
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import MoEConfig, MoEForCausalLM
    from bench import _PEAK

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    # PT_MOE_DISPATCH picks the dispatch path (einsum | index | ragged |
    # all_to_all | all_to_all_index); the a2a modes run through shard_map
    # on a 1-device (ep,) mesh — the same program the multichip dryrun
    # compiles at ep=8
    mode = os.environ.get("PT_MOE_DISPATCH", "all_to_all_index")
    mesh = None
    if mode.startswith("all_to_all"):
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:1]), ("ep",))
    # ablation knobs: PT_MOE_BATCH sizes the batch; PT_MOE_DENSE=1 makes
    # every layer dense (isolates the non-MoE cost of the same trunk)
    dense_all = os.environ.get("PT_MOE_DENSE", "") == "1"
    which = os.environ.get("PT_MOE_CFG", "large")
    if on_tpu:
        if which == "large":
            # DeepSeekMoE-family dims (deepseek_moe_16b: d=2048, expert
            # width 1408) scaled to one 16G chip by depth/expert count
            cfg = MoEConfig(
                vocab_size=32000, hidden_size=2048,
                intermediate_size=5632, moe_intermediate_size=1408,
                num_hidden_layers=4, num_attention_heads=16,
                num_key_value_heads=16, num_experts=16,
                num_experts_per_tok=2, num_shared_experts=1,
                first_k_dense_replace=1, max_position_embeddings=2048,
                capacity_factor=1.25, dispatch_mode=mode, mesh=mesh,
                dtype="bfloat16")
        else:  # "small": round-4-comparable config
            cfg = MoEConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                moe_intermediate_size=1024, num_hidden_layers=6,
                num_attention_heads=8, num_key_value_heads=8,
                num_experts=16, num_experts_per_tok=2,
                num_shared_experts=1, first_k_dense_replace=1,
                max_position_embeddings=2048, capacity_factor=1.25,
                dispatch_mode=mode, mesh=mesh, dtype="bfloat16")
        if dense_all:
            cfg.first_k_dense_replace = cfg.num_hidden_layers
        batch = int(os.environ.get("PT_MOE_BATCH", "4"))
        seq, iters, warmup = 2048, 8, 2
    else:
        cfg = MoEConfig.tiny(dispatch_mode=mode, mesh=mesh)
        if dense_all:  # the ablation knobs apply off-chip too
            cfg.first_k_dense_replace = cfg.num_hidden_layers
        batch = int(os.environ.get("PT_MOE_BATCH", "2"))
        seq, iters, warmup = 64, 2, 1

    pp.seed(0)
    model = MoEForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    step = TrainStep(model, opt)
    n_params = sum(int(np.prod(a.shape)) for a in step.params.values())
    # activated = total minus the idle fraction of the ROUTED expert
    # params, measured from the actual [E, ...] expert arrays (a per-token
    # forward touches top_k of num_experts of them) — never from an
    # assumed expert architecture (an earlier 3-matrix SwiGLU assumption
    # overcounted idle by 1.5x against the 2-matrix ExpertFFN and
    # UNDER-reported MFU)
    expert_params = sum(int(np.prod(a.shape))
                        for name, a in step.params.items()
                        if ".experts." in name)
    idle = int(expert_params
               * (cfg.num_experts - cfg.num_experts_per_tok)
               / cfg.num_experts)
    activated = n_params - idle

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    batch_dict = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    for _ in range(warmup):
        step(batch_dict)
    jax.block_until_ready(step.params)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(batch_dict)
    jax.block_until_ready(step.params)
    dt = (time.perf_counter() - t0) / iters

    tokens = batch * seq
    flops_per_token = 6 * activated + \
        12 * cfg.num_hidden_layers * seq * cfg.hidden_size
    kind = getattr(dev, "device_kind", "").lower()
    peak = next((v for k, v in sorted(_PEAK.items(),
                                      key=lambda kv: -len(kv[0]))
                 if k in kind), 459e12)
    mfu = flops_per_token * tokens / dt / peak
    print(json.dumps({
        "metric": "moe_pretrain_mfu", "value": round(mfu, 4),
        "unit": "fraction_of_peak_activated_flops",
        "detail": {"params_total": n_params, "params_activated": activated,
                   "dispatch_mode": mode,
                   "experts": cfg.num_experts,
                   "top_k": cfg.num_experts_per_tok,
                   "tokens_per_sec_per_chip": round(tokens / dt, 1),
                   "step_time_s": round(dt, 4), "batch": batch, "seq": seq,
                   "device": getattr(dev, "device_kind", dev.platform),
                   "final_loss": float(loss)}}), flush=True)


if __name__ == "__main__":
    main()
