"""Llama long-context train MFU at seq 4096 / 8192 (BASELINE config
matrix + VERDICT r3 #1/#4).

At long sequence the attention term dominates and the Pallas flash
kernels must carry the step; this bench measures the FULL train step
(fwd+bwd+AdamW) per sequence length for BOTH backward implementations —
the Pallas dq/dkv kernels and the blockwise-jax recompute — and reports
which one wins in-model, alongside the autotuner's isolated choice.

Prints one JSON line per (seq, backward) plus a summary line per seq.
Run on the TPU chip (the driver's tunnel); falls back to a tiny CPU
smoke shape off-TPU.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _peak_flops(device) -> float:
    from bench import _PEAK
    kind = getattr(device, "device_kind", "").lower()
    for key, val in sorted(_PEAK.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return 459e12


def run_one(cfg, batch, seq, pallas_bwd, iters=8, warmup=2, remat=False,
            remat_policy=None):
    import jax
    import paddle_tpu as pp
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import LlamaForCausalLM

    pp.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    import os
    os.environ["PT_FLASH_PALLAS_BWD"] = str(int(pallas_bwd))
    step = TrainStep(model, opt, remat=remat, remat_policy=remat_policy)
    n_params = sum(int(np.prod(a.shape)) for a in step.params.values())
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    batch_dict = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    for _ in range(warmup):
        step(batch_dict)
    jax.block_until_ready(step.params)
    t0 = time.perf_counter()
    for _ in range(iters):
        step(batch_dict)
    jax.block_until_ready(step.params)
    dt = (time.perf_counter() - t0) / iters
    tokens = batch * seq
    flops_per_token = 6 * n_params + \
        12 * cfg.num_hidden_layers * seq * cfg.hidden_size
    dev = jax.devices()[0]
    mfu = flops_per_token * tokens / dt / _peak_flops(dev)
    return mfu, tokens / dt, dt


def _plans(on_tpu):
    if on_tpu:
        # same Llama-3-8B-proportioned single-chip model as bench.py;
        # long context: batch shrinks with seq so activations fit HBM
        base = dict(vocab_size=32000, hidden_size=2048,
                    intermediate_size=7168, num_hidden_layers=8,
                    num_attention_heads=16, num_key_value_heads=8,
                    rope_theta=500000.0, dtype="bfloat16")
        # s8192 b1 runs WITHOUT remat: flash attention keeps activations
        # O(seq*d) so the 584M model's fwd residuals fit the 16G chip at
        # b1, and dropping remat is worth +32% (0.242 -> 0.322 measured;
        # both checkpoint policies measured identical, so recompute —
        # not policy choice — was the cost; remat sweep via
        # PT_SEQ_REMAT/PT_SEQ_POLICY for larger-than-memory configs)
        return base, [
            dict(seq=4096, batch=2, remat=False, remat_policy=None),
            dict(seq=8192, batch=1, remat=False, remat_policy=None),
        ]
    base = dict(vocab_size=256, hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, dtype="float32")
    return base, [dict(seq=256, batch=2, remat=False, remat_policy=None)]


def _child(seq: int, pb: int):
    """One measurement per process: a fresh 584M model + full AdamW state
    twice in one process OOMs the 16G chip (freeing is async).

    PT_SEQ_BATCH / PT_SEQ_REMAT / PT_SEQ_POLICY override the plan for
    remat-policy sweeps (VERDICT r4 Next #2)."""
    import os
    import jax
    from paddle_tpu.models import LlamaConfig
    on_tpu = jax.devices()[0].platform == "tpu"
    base, plans = _plans(on_tpu)
    plan = next(p for p in plans if p["seq"] == seq)
    if os.environ.get("PT_SEQ_BATCH"):
        plan["batch"] = int(os.environ["PT_SEQ_BATCH"])
    if os.environ.get("PT_SEQ_REMAT"):
        plan["remat"] = os.environ["PT_SEQ_REMAT"] == "1"
    if os.environ.get("PT_SEQ_POLICY"):
        pol = os.environ["PT_SEQ_POLICY"]
        plan["remat_policy"] = None if pol == "none" else pol
    cfg = LlamaConfig(max_position_embeddings=seq, **base)
    mfu, tps, dt = run_one(cfg, plan["batch"], seq, bool(pb),
                           remat=plan["remat"],
                           remat_policy=plan["remat_policy"])
    print("RESULT " + json.dumps({
        "mfu": mfu, "tps": tps, "dt": dt, "batch": plan["batch"],
        "remat": plan["remat"]}), flush=True)


def main():
    import subprocess
    import sys
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    _, plans = _plans(on_tpu)
    for plan in plans:
        seq, per = plan["seq"], {}
        for pb in (True, False):
            proc = subprocess.run(
                [sys.executable, __file__, "--child", str(seq),
                 str(int(pb))],
                capture_output=True, text=True, timeout=3000)
            line = next((ln for ln in proc.stdout.splitlines()
                         if ln.startswith("RESULT ")), None)
            if line is None:
                print(json.dumps({
                    "metric": f"llama_s{seq}_mfu_"
                              f"{'pallas_bwd' if pb else 'blockwise_bwd'}",
                    "value": None, "error": proc.stderr[-500:]}),
                    flush=True)
                continue
            r = json.loads(line[len("RESULT "):])
            per[pb] = r["mfu"]
            print(json.dumps({
                "metric": f"llama_s{seq}_mfu_"
                          f"{'pallas_bwd' if pb else 'blockwise_bwd'}",
                "value": round(r["mfu"], 4), "unit": "fraction_of_peak",
                "detail": {"batch": r["batch"], "seq": seq,
                           "tokens_per_sec_per_chip": round(r["tps"], 1),
                           "step_time_s": round(r["dt"], 4),
                           "remat": r["remat"]}}), flush=True)
        if len(per) == 2:
            winner = "pallas" if per[True] >= per[False] else "blockwise"
            print(json.dumps({
                "metric": f"llama_s{seq}_mfu",
                "value": round(max(per.values()), 4),
                "unit": "fraction_of_peak",
                "detail": {"in_model_winner": winner,
                           "pallas_bwd_mfu": round(per[True], 4),
                           "blockwise_bwd_mfu": round(per[False], 4)}}),
                flush=True)


if __name__ == "__main__":
    import sys
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        _child(int(sys.argv[2]), int(sys.argv[3]))
    else:
        main()
