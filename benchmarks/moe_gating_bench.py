"""MoE top-k gating microbenchmark: vectorized vs k-pass loop.

VERDICT r2 weak #5: gating looped Python-side over k (k sequential argmax
passes building dense [T,E,C] one-hots).  The shipped ``top_k_gating`` is
now a single lax.top_k + one cumsum over the k-major flattening; this
bench times it against the old k-pass formulation (reconstructed below)
at DeepSeekMoE-like shapes (k=6, E=64) so the win is a tracked number.
(Semantics note: under capacity OVERFLOW the two differ slightly — the
loop recycled dropped slots between passes, the vectorized form uses
standard GShard position bookkeeping; identical when nothing overflows.)

Run: python benchmarks/moe_gating_bench.py   (CPU or TPU)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def _loop_gating(gate_logits, k, capacity):
    """The pre-vectorization k-pass formulation (baseline)."""
    import jax
    import jax.numpy as jnp

    tokens, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    combine = jnp.zeros((tokens, E, capacity), probs.dtype)
    dispatch = jnp.zeros((tokens, E, capacity), bool)
    fill = jnp.zeros((E,), jnp.int32)
    masked = probs
    for _ in range(k):
        choice = jnp.argmax(masked, axis=-1)
        onehot = jax.nn.one_hot(choice, E, dtype=probs.dtype)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
        pos = pos + fill[None, :] * onehot
        in_cap = (pos < capacity) & (onehot > 0)
        gate_val = (probs * onehot).sum(-1)
        cap_onehot = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32),
                                    capacity, dtype=probs.dtype)
        sel = in_cap.any(-1)
        combine = combine + (gate_val[:, None, None] * onehot[:, :, None]
                             * cap_onehot[:, None, :]
                             * sel[:, None, None])
        dispatch = dispatch | ((onehot[:, :, None]
                                * cap_onehot[:, None, :]) > 0) \
            & sel[:, None, None]
        fill = fill + (onehot * in_cap).sum(0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)
    denom = combine.sum(axis=(1, 2), keepdims=True)
    return jnp.where(denom > 0, combine / jnp.maximum(denom, 1e-9),
                     combine)


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.moe import top_k_gating

    results = {}
    for name, T, E, k in (("gshard-top2", 8192, 64, 2),
                          ("deepseek-top6", 8192, 64, 6)):
        C = max(1, 2 * k * T // E)
        logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))

        new = jax.jit(lambda lg: top_k_gating(lg, k=k, capacity=C)[0])
        old = jax.jit(lambda lg: _loop_gating(lg, k=k, capacity=C))

        def bench(fn):
            fn(logits).block_until_ready()   # compile
            t0 = time.perf_counter()
            for _ in range(20):
                out = fn(logits)
            out.block_until_ready()
            return (time.perf_counter() - t0) / 20

        t_new, t_old = bench(new), bench(old)
        results[name] = {"vectorized_ms": round(t_new * 1e3, 3),
                         "k_loop_ms": round(t_old * 1e3, 3),
                         "speedup": round(t_old / t_new, 2)}
    print(json.dumps(results))


if __name__ == "__main__":
    main()
