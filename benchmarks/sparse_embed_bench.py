"""Sparse vs dense embedding-gradient microbench at 128k vocab.

Measures one eager train step (forward + backward + Adam update) of a
[vocab, d] embedding over T looked-up tokens:

  dense : jax vjp scatter-add builds the full [vocab, d] grad, Adam
          touches every row (reference dense adam kernel)
  sparse: RowSparseGrad (rows/values) + lazy Adam — work and memory are
          O(T·d), the reference's selected_rows/adam lazy_mode path

Prints one JSON line per mode.  Runs on whatever the default jax backend
is (TPU under the driver; CPU with JAX_PLATFORMS=cpu).
"""

import json
import time

import numpy as np


def bench(vocab=131072, d=1024, tokens=8192, steps=10):
    import paddle_tpu as pp

    results = {}
    rng = np.random.default_rng(0)
    ids_np = rng.integers(0, vocab, (1, tokens)).astype("int32")

    for mode in ("dense", "sparse"):
        pp.seed(0)
        emb = pp.nn.Embedding(vocab, d, sparse=(mode == "sparse"))
        opt = pp.optimizer.Adam(learning_rate=1e-3,
                                lazy_mode=(mode == "sparse"),
                                parameters=emb.parameters())
        ids = pp.to_tensor(ids_np)

        def step():
            loss = (emb(ids) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        step()  # warmup (compile + state init)
        emb.weight._data.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(steps):
            step()
        emb.weight._data.block_until_ready()
        dt = (time.perf_counter() - t0) / steps
        results[mode] = dt
        print(json.dumps({
            "metric": f"embed_train_step_{mode}",
            "value": round(dt * 1e3, 3), "unit": "ms",
            "detail": {"vocab": vocab, "d": d, "tokens": tokens}}),
            flush=True)

    speedup = results["dense"] / results["sparse"]
    print(json.dumps({"metric": "sparse_embed_speedup",
                      "value": round(speedup, 2), "unit": "x_vs_dense",
                      "detail": {"vocab": vocab, "d": d,
                                 "tokens": tokens}}), flush=True)
    return results


if __name__ == "__main__":
    bench()
