"""MoE step decomposition on the chip: where does the time go?

Times, at the moe_train_bench shapes (T=8192 tokens, d=1024, E=16, k=2,
bf16, fwd+bwd), each piece of the MoE sublayer in isolation:
  1. expert FFN GEMMs alone on pre-built [E, C, d] buffers  (MXU floor)
  2. gating bookkeeping alone (logits -> indices/slots/weights)
  3. full routed block, per dispatch mode
  4. the dense shared-expert MLP at the same token count (reference point:
     what a no-routing FFN of the same activated width costs)

Timing discipline for the remote tunnel: repeated IDENTICAL dispatches can
be cache-answered and block_until_ready alone under-reports, so every
iteration's input depends on the previous iteration's scalar output — the
chain forces real sequential device execution; one block at the end.
Prints one JSON line.
"""

from __future__ import annotations

import json
import time


def chain_time(step_fn, x0, *rest, iters=20, warmup=2):
    """step_fn(x, *rest) -> scalar; iteration i's input is
    x0 + 1e-20 * scalar_{i-1}, forcing sequential execution."""
    import jax
    import jax.numpy as jnp
    s = jnp.zeros((), jnp.float32)
    for _ in range(warmup):
        s = step_fn(x0 + s.astype(x0.dtype) * 1e-20, *rest)
    jax.block_until_ready(s)
    t0 = time.perf_counter()
    for _ in range(iters):
        s = step_fn(x0 + s.astype(x0.dtype) * 1e-20, *rest)
    jax.block_until_ready(s)
    return (time.perf_counter() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.distributed.moe import (
        _expert_ffn, moe_forward_index, moe_forward_ragged,
        top_k_gating_indices)

    T, d, h, E, k = 8192, 1024, 1024, 16, 2
    cf = 1.25
    C = int(cf * k * T / E)          # 1280
    dt = jnp.bfloat16
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, d)), dt)
    gw = jnp.asarray(rng.normal(size=(d, E)) * 0.02, dt)
    w1 = jnp.asarray(rng.normal(size=(E, d, h)) * 0.02, dt)
    b1 = jnp.zeros((E, h), dt)
    w2 = jnp.asarray(rng.normal(size=(E, h, d)) * 0.02, dt)
    b2 = jnp.zeros((E, d), dt)
    buf = jnp.asarray(rng.normal(size=(E, C, d)), dt)

    def as_step(loss_fn, argnums):
        """fwd+bwd scalar step: loss + tiny*sum(grads) keeps the backward
        pass alive in the dependency chain."""
        vg = jax.value_and_grad(loss_fn, argnums=argnums)

        @jax.jit
        def step(x, *rest):
            v, gs = vg(x, *rest)
            return v + sum(g.astype(jnp.float32).sum() for g in gs) * 1e-12

        return step

    out = {}

    # 1. expert GEMMs alone
    def ffn_loss(buf, w1, b1, w2, b2):
        return _expert_ffn(buf, w1, b1, w2, b2,
                           jax.nn.gelu).astype(jnp.float32).sum()

    t = chain_time(as_step(ffn_loss, (1, 3)), buf, w1, b1, w2, b2)
    out["ffn_only_ms"] = t * 1e3
    ffn_flops = 3 * (2 * E * C * d * h * 2)   # fwd + 2x bwd, two GEMMs
    out["ffn_only_tflops"] = ffn_flops / t / 1e12

    # 2. gating bookkeeping alone
    def gate_loss(x, gw):
        topi, slot, w, keep, aux = top_k_gating_indices(
            (x @ gw).astype(jnp.float32), k=k, capacity=C)
        return w.sum() + aux

    out["gating_ms"] = chain_time(as_step(gate_loss, (1,)), x, gw) * 1e3

    # 3. full routed block per mode
    def block_index(x, gw, w1, b1, w2, b2):
        logits = (x @ gw).astype(jnp.float32)
        o, aux, _ = moe_forward_index(
            x, logits, lambda b: _expert_ffn(b, w1, b1, w2, b2, jax.nn.gelu),
            E=E, top_k=k, capacity=C)
        return o.astype(jnp.float32).sum() + aux

    def block_ragged(x, gw, w1, b1, w2, b2):
        logits = (x @ gw).astype(jnp.float32)
        o, aux, _ = moe_forward_ragged(x, logits, w1, b1, w2, b2,
                                       E=E, top_k=k)
        return o.astype(jnp.float32).sum() + aux

    for name, fn in [("index", block_index), ("ragged", block_ragged)]:
        out[f"block_{name}_ms"] = chain_time(
            as_step(fn, (1, 2, 4)), x, gw, w1, b1, w2, b2) * 1e3

    # 4. dense MLP reference at same activated width (k experts' worth)
    wd1 = jnp.asarray(rng.normal(size=(d, k * h)) * 0.02, dt)
    wd2 = jnp.asarray(rng.normal(size=(k * h, d)) * 0.02, dt)

    def dense_loss(x, wd1, wd2):
        return (jax.nn.gelu(x @ wd1) @ wd2).astype(jnp.float32).sum()

    out["dense_same_width_ms"] = chain_time(
        as_step(dense_loss, (1, 2)), x, wd1, wd2) * 1e3

    out["shapes"] = {"T": T, "d": d, "h": h, "E": E, "k": k, "C": C}
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
