"""Eager-mode dispatch microbenchmark.

VERDICT weak #6: the eager hot path (Tensor -> dispatch -> jax.vjp per op)
was unmeasured.  This prints per-op wall time for a chain of small ops in
three modes — eager tape, eager no-grad, and the jitted chain — so the
dispatch overhead is a tracked number, not folklore.  TrainStep remains
the supported hot path; eager is for interactivity.

Run: python benchmarks/eager_bench.py  (CPU by default; any backend works)
"""

from __future__ import annotations

import json
import time

import numpy as np


def _time(fn, iters=200, warmup=20):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    if hasattr(out, "_data"):
        out._data.block_until_ready()
    elif hasattr(out, "block_until_ready"):
        out.block_until_ready()
    return (time.perf_counter() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pp

    n_ops = 8
    x_np = np.random.default_rng(0).normal(size=(256, 256)).astype("f4")

    def chain_raw(v):
        for _ in range(n_ops):
            v = jnp.tanh(v * 1.01 + 0.1)
        return v

    # eager with tape
    def eager_grad():
        t = pp.to_tensor(x_np, stop_gradient=False)
        v = t
        for _ in range(n_ops):
            v = pp.tanh(v * 1.01 + 0.1)
        return v

    # eager without tape
    def eager_nograd():
        with pp.autograd.no_grad():
            v = pp.to_tensor(x_np)
            for _ in range(n_ops):
                v = pp.tanh(v * 1.01 + 0.1)
            return v

    jitted = jax.jit(chain_raw)
    x_dev = jnp.asarray(x_np)

    results = {
        # 3 dispatched ops per loop iteration (mul, add, tanh)
        "eager_tape_us_per_op": _time(eager_grad) / (3 * n_ops) * 1e6,
        "eager_nograd_us_per_op": _time(eager_nograd) / (3 * n_ops) * 1e6,
        "jit_chain_us_per_op": _time(lambda: jitted(x_dev)) / (3 * n_ops)
                               * 1e6,
    }
    results["tape_overhead_x"] = (results["eager_tape_us_per_op"]
                                  / results["jit_chain_us_per_op"])
    print(json.dumps({k: round(v, 2) for k, v in results.items()}))


if __name__ == "__main__":
    main()
