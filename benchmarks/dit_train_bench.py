"""DiT train MFU on the chip (BASELINE DiT / Stable-Diffusion-3 family;
VERDICT r3 #1b — the vision/diffusion config with no measured number).

Full train step (fwd+bwd+AdamW) of a DiT-L/2-proportioned model on
32x32x4 latents: patchify conv + 24 adaLN transformer blocks + unpatchify
— the PaddleMIX DiT recipe shape, sized for one 16G chip with full
optimizer state.  FLOPs = 6N per patch token + attention term.

Prints one JSON line.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pp
    from paddle_tpu.core.dispatch import unwrap
    from paddle_tpu.core.functional import functional_call, params_of
    from paddle_tpu.models import DiT, DiTConfig
    from bench import _PEAK

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = DiTConfig(input_size=32, patch_size=2, in_channels=4,
                        hidden_size=1024, depth=24, num_heads=16,
                        num_classes=1000, dtype="bfloat16")
        batch, iters, warmup = 32, 8, 2
    else:
        cfg = DiTConfig.tiny()
        batch, iters, warmup = 2, 2, 1

    pp.seed(0)
    model = DiT(cfg)
    params = params_of(model)
    n_params = sum(int(np.prod(a.shape)) for a in
                   jax.tree.leaves(params))

    rng = np.random.default_rng(0)
    dt_ = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    x = jnp.asarray(rng.normal(size=(batch, cfg.in_channels,
                                     cfg.input_size, cfg.input_size)), dt_)
    noise = jnp.asarray(rng.normal(size=x.shape), dt_)
    t = jnp.asarray(rng.integers(0, 1000, (batch,)), jnp.int32)
    y = jnp.asarray(rng.integers(0, cfg.num_classes, (batch,)), jnp.int32)

    def loss_fn(ps):
        out = functional_call(model, ps, pp.Tensor(x), pp.Tensor(t),
                              pp.Tensor(y))
        eps = unwrap(out)[:, :cfg.in_channels]
        return jnp.mean((eps.astype(jnp.float32)
                         - noise.astype(jnp.float32)) ** 2)

    # AdamW-style update inline (fp32 master + moments)
    def init_state(p):
        # explicit copy: fp32 leaves would otherwise ALIAS the param
        # buffer (astype is a no-op) and double-donate in step()
        return {"m": jnp.zeros_like(p, jnp.float32),
                "v": jnp.zeros_like(p, jnp.float32),
                "w": jnp.array(p, jnp.float32, copy=True)}

    state = jax.tree.map(init_state, params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(ps, st, i):
        l, g = jax.value_and_grad(loss_fn)(ps)

        def upd(gr, s):
            m = 0.9 * s["m"] + 0.1 * gr.astype(jnp.float32)
            v = 0.999 * s["v"] + 0.001 * jnp.square(gr.astype(jnp.float32))
            mh = m / (1 - 0.9 ** i)
            vh = v / (1 - 0.999 ** i)
            w = s["w"] - 1e-4 * (mh / (jnp.sqrt(vh) + 1e-8) + 0.01 * s["w"])
            return {"m": m, "v": v, "w": w}

        st = jax.tree.map(upd, g, st)
        ps = jax.tree.map(lambda p, s: s["w"].astype(p.dtype), ps, st)
        return l, ps, st

    i = jnp.asarray(1)
    for _ in range(warmup):
        loss, params, state = step(params, state, i)
        i = i + 1
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, params, state = step(params, state, i)
        i = i + 1
    jax.block_until_ready(params)
    dts = (time.perf_counter() - t0) / iters

    tokens = batch * cfg.num_patches
    flops_per_token = 6 * n_params + \
        12 * cfg.depth * cfg.num_patches * cfg.hidden_size
    kind = getattr(dev, "device_kind", "").lower()
    peak = next((v for k, v in sorted(_PEAK.items(),
                                      key=lambda kv: -len(kv[0]))
                 if k in kind), 459e12)
    mfu = flops_per_token * tokens / dts / peak
    print(json.dumps({
        "metric": "dit_train_mfu", "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "detail": {"params": n_params, "batch": batch,
                   "patch_tokens": cfg.num_patches,
                   "images_per_sec": round(batch / dts, 1),
                   "step_time_s": round(dts, 4),
                   "device": getattr(dev, "device_kind", dev.platform),
                   "final_loss": float(loss)}}), flush=True)


if __name__ == "__main__":
    main()
