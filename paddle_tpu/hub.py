"""paddle_tpu.hub — hubconf-based model loading.

Reference parity: ``paddle.hub`` (python/paddle/hapi/hub.py —
list/help/load over a repo's ``hubconf.py``; entrypoints are callables
whose docstrings are the help text).  Source scope here: ``'local'``
(a directory containing hubconf.py).  The github/gitee download sources
require network egress this environment does not have — they raise with
that explanation rather than half-working.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str, source: str):
    if source != "local":
        raise NotImplementedError(
            f"hub source {source!r}: only 'local' is supported (this "
            "environment has no network egress for github/gitee clones); "
            "clone the repo yourself and pass its path with source='local'")
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location(
        f"paddle_tpu_hubconf_{abs(hash(os.path.abspath(path)))}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)  # hubconf may import repo-local modules
    try:
        spec.loader.exec_module(mod)
    finally:
        try:
            sys.path.remove(repo_dir)
        except ValueError:
            pass
    return mod


def _find_spec(name: str):
    try:
        return importlib.util.find_spec(name)
    except ModuleNotFoundError:  # dotted name with an absent parent
        return None


def _entrypoints(mod) -> List[str]:
    deps = getattr(mod, "dependencies", [])
    missing = [d for d in deps if _find_spec(d) is None]
    if missing:
        raise RuntimeError(f"hubconf dependencies not installed: {missing}")
    return sorted(
        n for n, v in vars(mod).items()
        if callable(v) and not n.startswith("_")
        # only functions DEFINED in hubconf are entrypoints — helpers it
        # imports from repo-local modules are not part of the contract
        and getattr(v, "__module__", mod.__name__) == mod.__name__)


def list(repo_dir: str, source: str = "local", force_reload: bool = False):
    """Entrypoint names exported by the repo's hubconf
    (reference hub.py:175)."""
    return _entrypoints(_load_hubconf(repo_dir, source))


def _resolve(repo_dir: str, model: str, source: str):
    mod = _load_hubconf(repo_dir, source)
    eps = _entrypoints(mod)
    if model not in eps:
        raise ValueError(f"no entrypoint {model!r} in {repo_dir!r}; "
                         f"available: {eps}")
    return getattr(mod, model)


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:
    """The entrypoint's docstring (reference hub.py:223)."""
    return _resolve(repo_dir, model, source).__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    """Call the entrypoint with **kwargs and return its result
    (reference hub.py:268)."""
    return _resolve(repo_dir, model, source)(**kwargs)
