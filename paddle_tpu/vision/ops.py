"""Detection ops (parity: python/paddle/vision/ops.py — roi_align, nms,
box_coder helpers over phi detection kernels).

TPU-native: roi_align is expressed as vectorized bilinear gathers (XLA
fuses the interpolation); nms is an O(n^2) mask + lax.fori_loop greedy
sweep — static shapes, no dynamic work queues, compiler-schedulable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op

__all__ = ["roi_align", "nms", "box_area", "box_iou", "distribute_fpn_proposals"]


@eager_op
def box_area(boxes):
    """boxes [N,4] xyxy -> [N] areas."""
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def _iou_matrix(boxes1, boxes2):
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    a1 = (boxes1[:, 2] - boxes1[:, 0]) * (boxes1[:, 3] - boxes1[:, 1])
    a2 = (boxes2[:, 2] - boxes2[:, 0]) * (boxes2[:, 3] - boxes2[:, 1])
    return inter / jnp.maximum(a1[:, None] + a2[None, :] - inter, 1e-10)


@eager_op
def box_iou(boxes1, boxes2):
    """Pairwise IoU [N,M] of xyxy boxes."""
    return _iou_matrix(boxes1, boxes2)


@eager_op
def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy non-maximum suppression (reference vision/ops.py nms).

    Returns kept indices sorted by descending score.  When category_idxs
    is given, suppression only applies within a category (batched NMS via
    the coordinate-offset trick; ``categories`` is accepted for signature
    parity and unused).  Static-shape implementation: an O(n^2) IoU
    matrix and a fori_loop keep-mask sweep.

    Under jit tracing the result is fixed-size: kept indices first, then
    -1 padding (counts are data-dependent); mask with ``kept >= 0``
    before gathering.  Eagerly the padding is stripped.
    """
    n = boxes.shape[0]
    if scores is None:
        scores = jnp.arange(n, 0, -1, dtype=jnp.float32)
    work = boxes
    if category_idxs is not None:
        # offset boxes per category so cross-category IoU is always 0
        # (broadcasting the shift onto all 4 coords preserves geometry);
        # span covers the FULL coordinate extent so negative coords from
        # unclipped proposals can never re-overlap
        span = jnp.max(boxes) - jnp.min(boxes) + 1.0
        work = boxes + category_idxs.astype(boxes.dtype)[:, None] * span

    order = jnp.argsort(-scores)
    sorted_boxes = work[order]
    iou = _iou_matrix(sorted_boxes, sorted_boxes)

    def body(i, keep):
        # drop i when any higher-scored kept box overlaps it
        suppressed = jnp.sum(jnp.where(jnp.arange(n) < i,
                                       (iou[:, i] > iou_threshold) & keep,
                                       False)) > 0
        return keep.at[i].set(~suppressed & keep[i])

    keep = jax.lax.fori_loop(1, n, body, jnp.ones(n, bool))
    kept_sorted = jnp.nonzero(keep, size=n, fill_value=-1)[0]
    kept = jnp.where(kept_sorted >= 0, order[kept_sorted], -1)
    if top_k is not None:
        kept = kept[:top_k]  # static slice: valid eagerly and traced
    if not isinstance(keep, jax.core.Tracer):
        count = int(jnp.sum(keep))
        kept = kept[:min(count, top_k) if top_k is not None else count]
    return kept


@eager_op
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoI Align (reference vision/ops.py roi_align / phi roi_align
    kernel): bilinear-sample each RoI into output_size bins, averaged
    over sampling points.

    x: [N, C, H, W]; boxes: [R, 4] xyxy in input coords; boxes_num: [N]
    rois per image (prefix assignment, reference semantics).
    """
    if isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    if sampling_ratio >= 1:
        ratio = int(sampling_ratio)
    else:
        # reference semantics are adaptive ceil(roi_size/output) PER RoI,
        # which needs dynamic shapes; the static stand-in samples at the
        # densest rate any full-feature RoI would need (capped for cost)
        ratio = int(min(8, max(2, -(-H // out_h))))

    # image index of each roi from boxes_num prefix counts
    prefix = jnp.cumsum(boxes_num)
    img_idx = jnp.searchsorted(prefix, jnp.arange(R), side="right")

    off = 0.5 if aligned else 0.0
    x0 = boxes[:, 0] * spatial_scale - off
    y0 = boxes[:, 1] * spatial_scale - off
    x1 = boxes[:, 2] * spatial_scale - off
    y1 = boxes[:, 3] * spatial_scale - off
    roi_w = x1 - x0
    roi_h = y1 - y0
    if not aligned:
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    bin_w = roi_w / out_w
    bin_h = roi_h / out_h

    # sampling grid: [R, out, ratio] per axis
    iy = (jnp.arange(ratio) + 0.5) / ratio
    ys = (y0[:, None, None] + (jnp.arange(out_h)[None, :, None]
          + iy[None, None, :]) * bin_h[:, None, None])  # [R,out_h,ratio]
    xs = (x0[:, None, None] + (jnp.arange(out_w)[None, :, None]
          + iy[None, None, :]) * bin_w[:, None, None])  # [R,out_w,ratio]

    def bilinear(feat, yy, xx):
        """feat [C,H,W]; yy/xx [...]: bilinear values [C, ...]."""
        yy = jnp.clip(yy, 0.0, H - 1.0)
        xx = jnp.clip(xx, 0.0, W - 1.0)
        y_lo = jnp.floor(yy).astype(jnp.int32)
        x_lo = jnp.floor(xx).astype(jnp.int32)
        y_hi = jnp.clip(y_lo + 1, 0, H - 1)
        x_hi = jnp.clip(x_lo + 1, 0, W - 1)
        ly = yy - y_lo
        lx = xx - x_lo
        v00 = feat[:, y_lo, x_lo]
        v01 = feat[:, y_lo, x_hi]
        v10 = feat[:, y_hi, x_lo]
        v11 = feat[:, y_hi, x_hi]
        return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
                + v10 * ly * (1 - lx) + v11 * ly * lx)

    def one_roi(r):
        feat = x[img_idx[r]]                       # [C,H,W]
        yy = ys[r][:, None, :, None]               # [out_h,1,ratio,1]
        xx = xs[r][None, :, None, :]               # [1,out_w,1,ratio]
        grid_y = jnp.broadcast_to(yy, (out_h, out_w, ratio, ratio))
        grid_x = jnp.broadcast_to(xx, (out_h, out_w, ratio, ratio))
        vals = bilinear(feat, grid_y, grid_x)      # [C,out_h,out_w,r,r]
        return vals.mean(axis=(-1, -2))            # [C,out_h,out_w]

    return jax.vmap(one_roi)(jnp.arange(R))
