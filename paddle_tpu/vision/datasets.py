"""Built-in vision datasets (reference: python/paddle/vision/datasets/ —
MNIST mnist.py, FashionMNIST, Cifar10/100 cifar.py, DatasetFolder
folder.py).

This environment has no network egress, so each dataset works in two
modes: pass the on-disk file(s) a user already has (same file formats as
the reference: IDX for MNIST, the python-pickle batches for CIFAR), or
construct with ``backend="synthetic"`` for a deterministic, procedurally
generated stand-in with the right shapes/classes — what the in-repo hapi
examples and tests run on.  ``download=True`` raises a clear error
instead of silently failing.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, Optional

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: download=True is not available in this environment "
        f"(no network egress) — pass the dataset files explicitly, or "
        f"use backend='synthetic' for a deterministic stand-in")


class _SyntheticImageClasses(Dataset):
    """Deterministic procedurally generated (image, label) pairs: each
    class is a distinct frequency/phase pattern plus seeded noise, so
    models can actually overfit it in tests."""

    def __init__(self, n, shape, num_classes, transform=None, seed=0):
        self.n = int(n)
        self.shape = shape
        self.num_classes = num_classes
        self.transform = transform
        self._rng_seed = seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        rng = np.random.default_rng(self._rng_seed * 100003 + idx)
        label = idx % self.num_classes
        c, h, w = self.shape
        yy, xx = np.mgrid[0:h, 0:w]
        freq = 1 + label
        base = np.sin(2 * np.pi * freq * xx / w + label) * \
            np.cos(2 * np.pi * freq * yy / h)
        img = (base[None] + 0.1 * rng.standard_normal((c, h, w)))
        img = img.astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)


class MNIST(_SyntheticImageClasses):
    """paddle.vision.datasets.MNIST parity: ``mode`` train/test, optional
    ``image_path``/``label_path`` pointing at the standard IDX files
    (gzipped or raw), else the synthetic backend."""

    NUM_CLASSES = 10
    SHAPE = (1, 28, 28)

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend: str = "auto"):
        if download and not (image_path and label_path):
            _no_download(type(self).__name__)
        n = 2000 if mode == "train" else 400
        super().__init__(n, self.SHAPE, self.NUM_CLASSES, transform,
                         seed=0 if mode == "train" else 1)
        self.mode = mode
        self._images = self._labels = None
        if image_path and label_path:
            self._images = self._read_idx(image_path, dims=3)
            self._labels = self._read_idx(label_path, dims=1)
            self.n = len(self._labels)

    @staticmethod
    def _read_idx(path, dims):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            data = f.read()
        magic, = struct.unpack(">I", data[:4])
        nd = magic & 0xFF
        if nd != dims:
            raise ValueError(f"{path}: IDX ndim {nd} != expected {dims}")
        shape = struct.unpack(">" + "I" * nd, data[4:4 + 4 * nd])
        arr = np.frombuffer(data, np.uint8, offset=4 + 4 * nd)
        return arr.reshape(shape)

    def __getitem__(self, idx):
        if self._images is None:
            return super().__getitem__(idx)
        img = (self._images[idx].astype(np.float32) / 255.0)[None]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self._labels[idx])


class FashionMNIST(MNIST):
    """Same IDX formats and shapes as MNIST (reference fashionmnistated
    under the same loader), different synthetic seed."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._rng_seed += 17


class Cifar10(_SyntheticImageClasses):
    """paddle.vision.datasets.Cifar10 parity: ``data_file`` takes the
    python-version CIFAR batch file(s) directory or a single pickle;
    synthetic backend otherwise."""

    NUM_CLASSES = 10
    SHAPE = (3, 32, 32)

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend: str = "auto"):
        if download and not data_file:
            _no_download(type(self).__name__)
        n = 2000 if mode == "train" else 400
        super().__init__(n, self.SHAPE, self.NUM_CLASSES, transform,
                         seed=2 if mode == "train" else 3)
        self.mode = mode
        self._data = self._labels = None
        if data_file:
            files = [data_file]
            if os.path.isdir(data_file):
                pref = "data_batch" if mode == "train" else "test_batch"
                files = sorted(os.path.join(data_file, f)
                               for f in os.listdir(data_file)
                               if f.startswith(pref))
            xs, ys = [], []
            for f in files:
                with open(f, "rb") as fh:
                    d = pickle.load(fh, encoding="bytes")
                xs.append(np.asarray(d[b"data"], np.uint8))
                ys.extend(d.get(b"labels", d.get(b"fine_labels")))
            self._data = np.concatenate(xs).reshape(-1, 3, 32, 32)
            self._labels = np.asarray(ys, np.int64)
            self.n = len(self._labels)

    def __getitem__(self, idx):
        if self._data is None:
            return super().__getitem__(idx)
        img = self._data[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]


class Cifar100(Cifar10):
    NUM_CLASSES = 100

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.num_classes = 100


class DatasetFolder(Dataset):
    """class-per-subdirectory image folder (reference folder.py); loader
    defaults to numpy .npy files so no image codec is required."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=(".npy",), transform: Optional[Callable] = None):
        self.root = root
        self.loader = loader or np.load
        self.transform = transform
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples = []
        for c in self.classes:
            cdir = os.path.join(root, c)
            for f in sorted(os.listdir(cdir)):
                if f.endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, f),
                                         self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)
