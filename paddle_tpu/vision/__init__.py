"""paddle_tpu.vision — models + transforms (reference:
python/paddle/vision/)."""

from paddle_tpu.vision import datasets  # noqa: F401
from paddle_tpu.vision import models  # noqa: F401
from paddle_tpu.vision import ops  # noqa: F401
from paddle_tpu.vision import transforms  # noqa: F401

__all__ = ["datasets", "models", "ops", "transforms",
           "set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend: str):
    """Reference paddle.vision.set_image_backend: select the decode
    backend for image datasets ('pil' or 'cv2'; both decode to the same
    numpy HWC arrays the transforms consume)."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"image backend must be 'pil' or 'cv2', "
                         f"got {backend!r}")
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path: str):
    """Load an image file to a numpy HWC array using the selected
    backend (reference paddle.vision.image_load)."""
    import numpy as np
    if _image_backend == "cv2":
        try:
            import cv2
        except ImportError as e:
            raise RuntimeError("cv2 backend selected but OpenCV is not "
                               "installed") from e
        img = cv2.imread(path)
        if img is None:
            raise FileNotFoundError(
                f"cv2 could not read image file {path!r}")
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    from PIL import Image
    return np.asarray(Image.open(path).convert("RGB"))
