"""paddle_tpu.vision — models + transforms (reference:
python/paddle/vision/)."""

from paddle_tpu.vision import datasets  # noqa: F401
from paddle_tpu.vision import models  # noqa: F401
from paddle_tpu.vision import ops  # noqa: F401
from paddle_tpu.vision import transforms  # noqa: F401

__all__ = ["datasets", "models", "ops", "transforms"]
