"""vision.transforms — numpy-side image transforms (reference:
python/paddle/vision/transforms/).  Host-side preprocessing feeding the
DataLoader; device work stays in the model."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose"]


class Compose:
    def __init__(self, transforms: List):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32) / 255.0
        return np.transpose(arr, (2, 0, 1))


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class Normalize:
    def __init__(self, mean: Sequence[float], std: Sequence[float],
                 data_format="CHW"):
        shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
        self.mean = np.asarray(mean, np.float32).reshape(shape)
        self.std = np.asarray(std, np.float32).reshape(shape)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


def _resize_np(arr: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize HWC via jax.image (no PIL dependency)."""
    import jax.image
    import jax.numpy as jnp
    out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                           (h, w) + arr.shape[2:], method="bilinear")
    return np.asarray(out).astype(arr.dtype)


class Resize:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        return _resize_np(arr, self.size[0], self.size[1])


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, pad=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.pad = pad

    def __call__(self, img):
        arr = np.asarray(img)
        if self.pad:
            arr = np.pad(arr, ((self.pad,) * 2, (self.pad,) * 2)
                         + ((0, 0),) * (arr.ndim - 2), mode="constant")
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(1, h - th + 1))
        j = np.random.randint(0, max(1, w - tw + 1))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)
