from paddle_tpu.vision.models.resnet import (  # noqa: F401
    BasicBlock, BottleneckBlock, ResNet, resnet18, resnet34, resnet50,
    resnet101, resnet152)
from paddle_tpu.vision.models.zoo import (  # noqa: F401
    AlexNet, LeNet, MobileNetV2, SqueezeNet, VGG, mobilenet_v2,
    squeezenet1_0, squeezenet1_1, vgg11, vgg13, vgg16, vgg19)

__all__ = ["ResNet", "BasicBlock", "BottleneckBlock", "resnet18",
           "resnet34", "resnet50", "resnet101", "resnet152",
           "AlexNet", "LeNet", "MobileNetV2", "SqueezeNet", "VGG",
           "mobilenet_v2", "squeezenet1_0", "squeezenet1_1",
           "vgg11", "vgg13", "vgg16", "vgg19"]
