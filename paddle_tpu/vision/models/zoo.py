"""Classic CNN zoo: LeNet, AlexNet, VGG, MobileNetV2, SqueezeNet.

Reference parity: python/paddle/vision/models/{lenet,alexnet,vgg,
mobilenetv2,squeezenet}.py (the architectures are standard; code is an
independent implementation over paddle_tpu.nn).  All NCHW, bf16-ready;
convolutions map straight onto the MXU via XLA's conv lowering.
"""

from __future__ import annotations

from paddle_tpu.nn.common_layers import Dropout, Linear, ReLU, Sequential
from paddle_tpu.nn.conv_layers import Conv2D
from paddle_tpu.nn.norm_layers import BatchNorm2D
from paddle_tpu.nn.pooling_layers import (AdaptiveAvgPool2D, AvgPool2D,
                                          MaxPool2D)
from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.ops import manipulation as M

__all__ = ["LeNet", "AlexNet", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
           "MobileNetV2", "mobilenet_v2", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1"]


class LeNet(Layer):
    """reference vision/models/lenet.py (28x28 inputs)."""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        self.fc = Sequential(
            Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = M.flatten(x, 1)
        return self.fc(x)


class AlexNet(Layer):
    """reference vision/models/alexnet.py."""

    def __init__(self, num_classes: int = 1000, dropout: float = 0.5):
        super().__init__()
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, 2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(3, 2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(3, 2))
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        self.classifier = Sequential(
            Dropout(dropout), Linear(256 * 36, 4096), ReLU(),
            Dropout(dropout), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(M.flatten(x, 1))


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    """reference vision/models/vgg.py."""

    def __init__(self, features, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        self.classifier = Sequential(
            Linear(512 * 49, 4096), ReLU(), Dropout(0.5),
            Linear(4096, 4096), ReLU(), Dropout(0.5),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        return self.classifier(M.flatten(x, 1))


def _vgg_features(cfg, batch_norm=False):
    layers = []
    cin = 3
    for v in _VGG_CFGS[cfg]:
        if v == "M":
            layers.append(MaxPool2D(2, 2))
        else:
            layers.append(Conv2D(cin, v, 3, padding=1))
            if batch_norm:
                layers.append(BatchNorm2D(v))
            layers.append(ReLU())
            cin = v
    return Sequential(*layers)


def vgg11(batch_norm=False, **kw):
    return VGG(_vgg_features("A", batch_norm), **kw)


def vgg13(batch_norm=False, **kw):
    return VGG(_vgg_features("B", batch_norm), **kw)


def vgg16(batch_norm=False, **kw):
    return VGG(_vgg_features("D", batch_norm), **kw)


def vgg19(batch_norm=False, **kw):
    return VGG(_vgg_features("E", batch_norm), **kw)


class _InvertedResidual(Layer):
    def __init__(self, cin, cout, stride, expand_ratio):
        super().__init__()
        hidden = int(round(cin * expand_ratio))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand_ratio != 1:
            layers += [Conv2D(cin, hidden, 1, bias_attr=False),
                       BatchNorm2D(hidden), ReLU()]
        layers += [
            Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                   groups=hidden, bias_attr=False),
            BatchNorm2D(hidden), ReLU(),
            Conv2D(hidden, cout, 1, bias_attr=False), BatchNorm2D(cout)]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    """reference vision/models/mobilenetv2.py (inverted residuals)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        cfg = [
            # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        cin = max(8, int(32 * scale))
        features = [Conv2D(3, cin, 3, stride=2, padding=1,
                           bias_attr=False), BatchNorm2D(cin), ReLU()]
        for t, c, n, s in cfg:
            cout = max(8, int(c * scale))
            for i in range(n):
                features.append(_InvertedResidual(
                    cin, cout, s if i == 0 else 1, t))
                cin = cout
        self.last_channel = max(1280, int(1280 * scale))
        features += [Conv2D(cin, self.last_channel, 1, bias_attr=False),
                     BatchNorm2D(self.last_channel), ReLU()]
        self.features = Sequential(*features)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        self.classifier = Sequential(Dropout(0.2),
                                     Linear(self.last_channel,
                                            num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        return self.classifier(M.flatten(x, 1))


def mobilenet_v2(scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


class _Fire(Layer):
    def __init__(self, cin, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Conv2D(cin, squeeze, 1)
        self.expand1 = Conv2D(squeeze, e1, 1)
        self.expand3 = Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = F.relu(self.squeeze(x))
        return M.concat([F.relu(self.expand1(s)),
                         F.relu(self.expand3(s))], axis=1)


class SqueezeNet(Layer):
    """reference vision/models/squeezenet.py."""

    def __init__(self, version: str = "1.0", num_classes: int = 1000):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError(f"unsupported SqueezeNet version {version!r}; "
                             "expected '1.0' or '1.1'")
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = Sequential(
            Dropout(0.5), Conv2D(512, num_classes, 1), ReLU(),
            AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        x = self.classifier(self.features(x))
        return M.flatten(x, 1)


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)
