"""ResNet family (reference: python/paddle/vision/models/resnet.py).

The conv/vision model in the benchmark matrix (PP-OCRv4-class backbones are
ResNet-ish conv stacks).  Convs lower straight to XLA's conv-general which
tiles onto the MXU; BN in training mode keeps running stats as buffers.
NCHW layout (paddle convention).
"""

from __future__ import annotations

from typing import List, Optional, Type, Union

from paddle_tpu.nn.common_layers import Linear, Sequential
from paddle_tpu.nn.conv_layers import Conv2D
from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn.norm_layers import BatchNorm2D
from paddle_tpu.nn.pooling_layers import AdaptiveAvgPool2D, MaxPool2D

__all__ = ["ResNet", "BasicBlock", "BottleneckBlock", "resnet18",
           "resnet34", "resnet50", "resnet101", "resnet152"]


def _conv3x3(cin, cout, stride=1):
    return Conv2D(cin, cout, 3, stride=stride, padding=1, bias_attr=False)


def _conv1x1(cin, cout, stride=1):
    return Conv2D(cin, cout, 1, stride=stride, bias_attr=False)


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        from paddle_tpu.nn import functional as F
        self.conv1 = _conv3x3(inplanes, planes, stride)
        self.bn1 = BatchNorm2D(planes)
        self.conv2 = _conv3x3(planes, planes)
        self.bn2 = BatchNorm2D(planes)
        self.downsample = downsample
        self._relu = F.relu

    def forward(self, x):
        identity = x
        out = self._relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self._relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        from paddle_tpu.nn import functional as F
        self.conv1 = _conv1x1(inplanes, planes)
        self.bn1 = BatchNorm2D(planes)
        self.conv2 = _conv3x3(planes, planes, stride)
        self.bn2 = BatchNorm2D(planes)
        self.conv3 = _conv1x1(planes, planes * self.expansion)
        self.bn3 = BatchNorm2D(planes * self.expansion)
        self.downsample = downsample
        self._relu = F.relu

    def forward(self, x):
        identity = x
        out = self._relu(self.bn1(self.conv1(x)))
        out = self._relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self._relu(out + identity)


class ResNet(Layer):
    def __init__(self, block: Type[Union[BasicBlock, BottleneckBlock]],
                 depth_layers: List[int], num_classes: int = 1000,
                 with_pool: bool = True, in_channels: int = 3):
        super().__init__()
        self.inplanes = 64
        self.conv1 = Conv2D(in_channels, 64, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(64)
        self.maxpool = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_layers[0])
        self.layer2 = self._make_layer(block, 128, depth_layers[1], 2)
        self.layer3 = self._make_layer(block, 256, depth_layers[2], 2)
        self.layer4 = self._make_layer(block, 512, depth_layers[3], 2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                _conv1x1(self.inplanes, planes * block.expansion, stride),
                BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes))
        return Sequential(*layers)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        from paddle_tpu.ops import manipulation as M
        x = F.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = M.flatten(x, start_axis=1)
            x = self.fc(x)
        return x

    @staticmethod
    def partition_specs(config=None, dp_axis="dp", tp_axis="tp",
                        fsdp_axis=None):
        """Conv nets are DP/FSDP-parallel: convs replicate (or fsdp-shard
        the output-channel dim); the fc head column-shards on tp."""
        from jax.sharding import PartitionSpec as P
        return {
            "fc.weight": P(fsdp_axis, tp_axis),
            "fc.bias": P(tp_axis),
            ".weight": P(fsdp_axis) if fsdp_axis else P(),
        }

    @staticmethod
    def spec_for(name, rules):
        from paddle_tpu.models.llama import LlamaForCausalLM
        return LlamaForCausalLM.spec_for(name, rules)


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes, **kw)
