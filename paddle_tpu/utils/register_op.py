"""Out-of-tree custom-op registration — the TPU-native custom-op story.

Reference parity: ``PD_BUILD_OP`` (paddle/phi/api/ext/op_meta_info.h:874 —
name + kernel fn + infer-meta + optional grad kernel registered into the
global OpMetaInfoMap) and the JIT build toolchain
(python/paddle/utils/cpp_extension/cpp_extension.py).  On TPU the "kernel"
is a pure-jax or Pallas callable, the infer-meta is jax abstract eval, and
the build step is XLA's — so registration reduces to wiring the callable
into the framework's three integration points:

  1. the dual-mode dispatcher (``eager_op``): the op works on Tensors with
     tape autograd AND on raw arrays under jit;
  2. the ``OP_INFO`` schema registry (sharding hint for GSPMD consumers,
     arg/attr signature, custom_vjp flag) — same record the generated ops
     carry;
  3. the OpTest harness: a registered numpy oracle + example inputs make
     the op auto-testable with ``check_registered_op`` (output parity in
     eager/jit/functional modes, gradients vs finite differences) — the
     reference's OpTest-over-custom-op flow (test_custom_relu_op_setup.py).

A worked Pallas-kernel registration lives in
tests/test_register_op.py::test_pallas_custom_op.
"""

from __future__ import annotations

import inspect
from typing import Callable, Dict, Optional

__all__ = ["register_op", "get_registered_op", "registered_ops",
           "unregister_op", "check_registered_op"]

# name -> record; separate from OP_INFO so the oracle/example factories
# (test-only payload) don't leak into the schema registry
_CUSTOM_OPS: Dict[str, dict] = {}


def register_op(name: str, impl: Callable, *,
                vjp: Optional[tuple] = None,
                sharding: str = "elementwise",
                oracle: Optional[Callable] = None,
                example_inputs: Optional[Callable] = None,
                attrs: Optional[Dict] = None,
                namespace=None) -> Callable:
    """Register an out-of-tree op and return its dual-mode callable.

    Args:
        name: op name; must not collide with an existing OP_INFO entry.
        impl: pure function over raw jax arrays (jnp ops or a Pallas
            ``pallas_call``).  Positional array args + keyword attrs.
        vjp: optional ``(fwd, bwd)`` pair wired via ``jax.custom_vjp`` —
            ``fwd(*args, **attrs) -> (out, residuals)``,
            ``bwd(residuals, cotangent) -> tuple(d_args)``.  The reference's
            grad-kernel slot in PD_BUILD_OP.
        sharding: GSPMD hint recorded in OP_INFO ('elementwise',
            'contraction', 'reduction', ... — same vocabulary as ops.yaml).
        oracle: numpy reference implementation (enables the OpTest harness).
        example_inputs: zero-arg callable returning {arg_name: np.ndarray}
            used by ``check_registered_op``.
        attrs: default attr dict recorded in the schema.
        namespace: optional module/object to also ``setattr(name, op)`` on
            (e.g. ``paddle_tpu.incubate``).

    Returns:
        The wrapped op: accepts Tensors (eager, tape-recorded) or raw
        arrays (jit/functional), like every built-in op.
    """
    from paddle_tpu.core.dispatch import eager_op
    from paddle_tpu.ops.generated_math import OP_INFO

    if name in OP_INFO or name in _CUSTOM_OPS:
        raise ValueError(f"op '{name}' is already registered")

    try:
        params = list(inspect.signature(impl).parameters.values())
    except (TypeError, ValueError):  # builtins / partials without signature
        params = []
    arg_names = [p.name for p in params
                 if p.default is inspect.Parameter.empty]
    attr_names = [p.name for p in params
                  if p.default is not inspect.Parameter.empty]

    core = impl
    if vjp is not None:
        if attr_names:
            # jax.custom_vjp's nondiff handling would prepend attrs to
            # bwd's arguments, silently breaking the documented
            # bwd(residuals, cotangent) contract — demand closures instead
            raise ValueError(
                f"op '{name}': vjp ops must take array arguments only "
                f"(found attr params {attr_names}); close over attrs in "
                "impl/fwd/bwd (functools.partial) instead")
        import jax
        fwd, bwd = vjp
        core = jax.custom_vjp(impl)
        core.defvjp(fwd, bwd)

    wrapped = eager_op(core, name=name)

    OP_INFO[name] = {"args": arg_names, "attrs": dict(attrs or {}),
                     "sharding": sharding, "custom_vjp": vjp is not None,
                     "custom": True}
    _CUSTOM_OPS[name] = {"op": wrapped, "impl": impl, "oracle": oracle,
                         "example_inputs": example_inputs,
                         "attrs": dict(attrs or {})}
    if namespace is not None:
        setattr(namespace, name, wrapped)
    return wrapped


def get_registered_op(name: str) -> Callable:
    return _CUSTOM_OPS[name]["op"]


def registered_ops():
    return sorted(_CUSTOM_OPS)


def unregister_op(name: str):
    """Remove a registration (tests; the reference map is append-only).
    Only custom entries are removable — built-in schema rows are safe."""
    from paddle_tpu.ops.generated_math import OP_INFO
    if _CUSTOM_OPS.pop(name, None) is not None:
        OP_INFO.pop(name, None)


def check_registered_op(name: str, grad: bool = True,
                        rtol=None, atol=None, grad_rtol=None):
    """Run the OpTest harness on a registered op: output parity against
    its numpy oracle in eager/jit/functional modes, plus tape- and
    jax.grad-vs-finite-difference checks when the op is differentiable.

    The auto-test the reference gives PD_BUILD_OP ops via OpTest
    (test/custom_op/test_custom_relu_op_setup.py pattern)."""
    rec = _CUSTOM_OPS[name]
    if rec["oracle"] is None or rec["example_inputs"] is None:
        raise ValueError(
            f"op '{name}' was registered without oracle/example_inputs; "
            "pass both to make it harness-testable")
    from paddle_tpu.testing import op_case
    case = op_case(rec["op"], rec["oracle"], rec["example_inputs"](),
                   attrs=rec["attrs"], rtol=rtol, atol=atol,
                   grad_rtol=grad_rtol)
    case.run(grad=grad)
