"""paddle_tpu.utils — native-extension loading + misc helpers.

Reference parity: ``paddle.utils`` incl. ``cpp_extension`` (the JIT
build-and-load toolchain for custom C++ ops,
python/paddle/utils/cpp_extension/).  Here the native surface is the
C++ runtime components under csrc/ (TCPStore rendezvous, datafeed),
built with make+g++ and bound via ctypes (no pybind11 in this image).
"""

from paddle_tpu.utils.cpp_extension import load_native  # noqa: F401
from paddle_tpu.utils.register_op import (  # noqa: F401
    check_registered_op, get_registered_op, register_op, registered_ops,
    unregister_op)

__all__ = ["load_native", "register_op", "get_registered_op",
           "registered_ops", "unregister_op", "check_registered_op"]


def try_import(name: str):
    try:
        import importlib
        return importlib.import_module(name)
    except ImportError:
        return None
