"""Build + load the native C++ components.

Reference parity: ``paddle.utils.cpp_extension`` (cpp_extension.py — JIT
nvcc/ninja build of custom ops, loaded via dlopen).  TPU-side there is no
device code to compile; the native pieces are host runtime (csrc/): built
with `make`, loaded with ctypes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_LIBDIR = os.path.join(_REPO, "paddle_tpu", "lib")
_CSRC = os.path.join(_REPO, "csrc")
_lock = threading.Lock()
_cache = {}


class NativeBuildError(RuntimeError):
    pass


def _build():
    res = subprocess.run(["make", "-C", _CSRC, "-j"],
                         capture_output=True, text=True)
    if res.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{res.stdout}\n{res.stderr}")


def load_native(name: str, build_if_missing: bool = True,
                required_symbol: Optional[str] = None
                ) -> Optional[ctypes.CDLL]:
    """Load libpt_<name>.so, building csrc/ on first use.  A stale build
    missing `required_symbol` (the source gained a C API since the .so was
    last built) triggers a rebuild instead of an AttributeError later."""
    with _lock:
        if name in _cache:
            return _cache[name]
        path = os.path.join(_LIBDIR, f"libpt_{name}.so")
        if not os.path.exists(path):
            if not build_if_missing:
                return None
            _build()
        elif required_symbol is not None and build_if_missing:
            probe = ctypes.CDLL(path)
            if not hasattr(probe, required_symbol):
                del probe
                _build()
        if not os.path.exists(path):
            # optional component whose build prerequisites are absent
            # (e.g. the predictor needs the PJRT C API header); cache the
            # miss so the make subprocess isn't re-run on every probe
            _cache[name] = None
            return None
        lib = ctypes.CDLL(path)
        _cache[name] = lib
        return lib
