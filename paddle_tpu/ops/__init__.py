"""Pure-op modules. Each op is a single jax-level function that is
Tensor/tape-aware when handed eager Tensors (see core/dispatch.py)."""

from paddle_tpu.ops import (creation, linalg, logic, manipulation, math,  # noqa: F401
                            random, search, stat)
