"""Elementwise & reduction math ops (parity: python/paddle/tensor/math.py).

The bulk of this surface is GENERATED from the op schema
(ops/gen/ops.yaml -> ops/generated_math.py; reference:
paddle/phi/api/yaml/ops.yaml + its generator pipeline, SURVEY Appendix A).
Only ops with genuinely bespoke control flow stay hand-written here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op
from paddle_tpu.ops.generated_math import *  # noqa: F401,F403
from paddle_tpu.ops.generated_math import remainder, __all__ as _gen_all

# paddle-parity aliases
mod = remainder
floor_mod = remainder


@eager_op
def rsqrt_(x):  # convenience pure form
    return jax.lax.rsqrt(x)


@eager_op(name="multiplex")
def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)  # [n, batch, ...]
    idx = jnp.reshape(index, (-1,))
    return stacked[idx, jnp.arange(idx.shape[0])]


@eager_op(name="renorm")
def renorm(x, p, axis, max_norm):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    out = flat * factor[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


__all__ = [n for n in _gen_all if n != "OP_INFO"] + [
    "mod", "floor_mod", "rsqrt_", "multiplex", "renorm",
    "cumulative_trapezoid", "histogram_bin_edges"]


@eager_op
def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    """Cumulative trapezoidal integral (reference tensor/math.py
    cumulative_trapezoid): one fewer element along `axis` than y."""
    y0 = jax.lax.slice_in_dim(y, 0, y.shape[axis] - 1, axis=axis)
    y1 = jax.lax.slice_in_dim(y, 1, y.shape[axis], axis=axis)
    if x is not None:
        x0 = jax.lax.slice_in_dim(x, 0, x.shape[axis] - 1, axis=axis)
        x1 = jax.lax.slice_in_dim(x, 1, x.shape[axis], axis=axis)
        seg = (x1 - x0) * (y0 + y1) / 2.0
    else:
        seg = (dx if dx is not None else 1.0) * (y0 + y1) / 2.0
    return jnp.cumsum(seg, axis=axis)


@eager_op
def histogram_bin_edges(input, bins=100, min=0, max=0):
    """Bin edges matching paddle.histogram's binning (reference
    tensor/math.py histogram_bin_edges)."""
    lo, hi = min, max
    if lo == 0 and hi == 0:
        lo, hi = jnp.min(input), jnp.max(input)
    return jnp.linspace(lo, hi, bins + 1)
