"""Elementwise & reduction math ops (parity: python/paddle/tensor/math.py in
the reference, which wraps _C_ops; here each op is one pure jax function that
is tape-aware for eager Tensors and transparent under jit)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op

# ---------------------------------------------------------------- binary
add = eager_op(name="add")(lambda x, y: jnp.add(x, y))
subtract = eager_op(name="subtract")(lambda x, y: jnp.subtract(x, y))
multiply = eager_op(name="multiply")(lambda x, y: jnp.multiply(x, y))
divide = eager_op(name="divide")(lambda x, y: jnp.true_divide(x, y))
floor_divide = eager_op(name="floor_divide")(lambda x, y: jnp.floor_divide(x, y))
remainder = eager_op(name="remainder")(lambda x, y: jnp.remainder(x, y))
mod = remainder
floor_mod = remainder
pow = eager_op(name="pow")(lambda x, y: jnp.power(x, y))
maximum = eager_op(name="maximum")(lambda x, y: jnp.maximum(x, y))
minimum = eager_op(name="minimum")(lambda x, y: jnp.minimum(x, y))
fmax = eager_op(name="fmax")(lambda x, y: jnp.fmax(x, y))
fmin = eager_op(name="fmin")(lambda x, y: jnp.fmin(x, y))
atan2 = eager_op(name="atan2")(lambda x, y: jnp.arctan2(x, y))
heaviside = eager_op(name="heaviside")(lambda x, y: jnp.heaviside(x, y))
gcd = eager_op(name="gcd")(lambda x, y: jnp.gcd(x, y))
lcm = eager_op(name="lcm")(lambda x, y: jnp.lcm(x, y))
hypot = eager_op(name="hypot")(lambda x, y: jnp.hypot(x, y))
logaddexp = eager_op(name="logaddexp")(lambda x, y: jnp.logaddexp(x, y))
copysign = eager_op(name="copysign")(lambda x, y: jnp.copysign(x, y))
nextafter = eager_op(name="nextafter")(lambda x, y: jnp.nextafter(x, y))
ldexp = eager_op(name="ldexp")(lambda x, y: jnp.ldexp(x, y))
inner = eager_op(name="inner")(lambda x, y: jnp.inner(x, y))
outer = eager_op(name="outer")(lambda x, y: jnp.outer(x, y))
kron = eager_op(name="kron")(lambda x, y: jnp.kron(x, y))


@eager_op
def lerp(x, y, weight):
    return x + weight * (y - x)


# ---------------------------------------------------------------- unary
exp = eager_op(name="exp")(jnp.exp)
expm1 = eager_op(name="expm1")(jnp.expm1)
log = eager_op(name="log")(jnp.log)
log2 = eager_op(name="log2")(jnp.log2)
log10 = eager_op(name="log10")(jnp.log10)
log1p = eager_op(name="log1p")(jnp.log1p)
sqrt = eager_op(name="sqrt")(jnp.sqrt)
rsqrt = eager_op(name="rsqrt")(lambda x: jax.lax.rsqrt(x))
abs = eager_op(name="abs")(jnp.abs)
ceil = eager_op(name="ceil")(jnp.ceil)
floor = eager_op(name="floor")(jnp.floor)
round = eager_op(name="round")(jnp.round)
trunc = eager_op(name="trunc")(jnp.trunc)
frac = eager_op(name="frac")(lambda x: x - jnp.trunc(x))
sign = eager_op(name="sign")(jnp.sign)
sin = eager_op(name="sin")(jnp.sin)
cos = eager_op(name="cos")(jnp.cos)
tan = eager_op(name="tan")(jnp.tan)
asin = eager_op(name="asin")(jnp.arcsin)
acos = eager_op(name="acos")(jnp.arccos)
atan = eager_op(name="atan")(jnp.arctan)
sinh = eager_op(name="sinh")(jnp.sinh)
cosh = eager_op(name="cosh")(jnp.cosh)
tanh = eager_op(name="tanh")(jnp.tanh)
asinh = eager_op(name="asinh")(jnp.arcsinh)
acosh = eager_op(name="acosh")(jnp.arccosh)
atanh = eager_op(name="atanh")(jnp.arctanh)
reciprocal = eager_op(name="reciprocal")(lambda x: 1.0 / x)
square = eager_op(name="square")(jnp.square)
erf = eager_op(name="erf")(jax.scipy.special.erf)
erfinv = eager_op(name="erfinv")(jax.scipy.special.erfinv)
lgamma = eager_op(name="lgamma")(jax.scipy.special.gammaln)
digamma = eager_op(name="digamma")(jax.scipy.special.digamma)
polygamma = eager_op(name="polygamma")(
    lambda x, n: jax.scipy.special.polygamma(n, x))
i0 = eager_op(name="i0")(jax.scipy.special.i0)
i0e = eager_op(name="i0e")(jax.scipy.special.i0e)
i1 = eager_op(name="i1")(jax.scipy.special.i1)
i1e = eager_op(name="i1e")(jax.scipy.special.i1e)
neg = eager_op(name="neg")(jnp.negative)
deg2rad = eager_op(name="deg2rad")(jnp.deg2rad)
rad2deg = eager_op(name="rad2deg")(jnp.rad2deg)
angle = eager_op(name="angle")(jnp.angle)
conj = eager_op(name="conj")(jnp.conj)
real = eager_op(name="real")(jnp.real)
imag = eager_op(name="imag")(jnp.imag)
isnan = eager_op(name="isnan")(jnp.isnan)
isinf = eager_op(name="isinf")(jnp.isinf)
isfinite = eager_op(name="isfinite")(jnp.isfinite)
sigmoid = eager_op(name="sigmoid")(jax.nn.sigmoid)
logit = eager_op(name="logit")(
    lambda x, eps=None: jax.scipy.special.logit(
        x if eps is None else jnp.clip(x, eps, 1 - eps)))


@eager_op
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@eager_op
def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


@eager_op
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


@eager_op
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@eager_op
def rsqrt_(x):  # convenience pure form
    return jax.lax.rsqrt(x)


# ------------------------------------------------------------- reductions
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@eager_op(name="sum")
def sum(x, axis=None, dtype=None, keepdim=False):
    from paddle_tpu.core.dtypes import to_jax
    return jnp.sum(x, axis=_axis(axis), dtype=to_jax(dtype), keepdims=keepdim)


@eager_op(name="mean")
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@eager_op(name="max")
def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@eager_op(name="min")
def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@eager_op(name="amax")
def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@eager_op(name="amin")
def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@eager_op(name="prod")
def prod(x, axis=None, keepdim=False, dtype=None):
    from paddle_tpu.core.dtypes import to_jax
    return jnp.prod(x, axis=_axis(axis), keepdims=keepdim, dtype=to_jax(dtype))


@eager_op(name="logsumexp")
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@eager_op(name="cumsum")
def cumsum(x, axis=None, dtype=None):
    from paddle_tpu.core.dtypes import to_jax
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jnp.cumsum(x, axis=int(axis), dtype=to_jax(dtype))


@eager_op(name="cumprod")
def cumprod(x, dim=None, dtype=None):
    from paddle_tpu.core.dtypes import to_jax
    if dim is None:
        x = jnp.reshape(x, (-1,))
        dim = 0
    return jnp.cumprod(x, axis=int(dim), dtype=to_jax(dtype))


@eager_op(name="logcumsumexp")
def logcumsumexp(x, axis=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=int(axis))


@eager_op(name="count_nonzero")
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


@eager_op(name="nansum")
def nansum(x, axis=None, dtype=None, keepdim=False):
    from paddle_tpu.core.dtypes import to_jax
    return jnp.nansum(x, axis=_axis(axis), dtype=to_jax(dtype), keepdims=keepdim)


@eager_op(name="nanmean")
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@eager_op(name="diff")
def diff(x, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@eager_op(name="trace")
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@eager_op(name="diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@eager_op(name="addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


@eager_op(name="increment")
def increment(x, value=1.0):
    return x + value


@eager_op(name="multiplex")
def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)  # [n, batch, ...]
    idx = jnp.reshape(index, (-1,))
    return stacked[idx, jnp.arange(idx.shape[0])]


@eager_op(name="renorm")
def renorm(x, p, axis, max_norm):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    out = flat * factor[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


# Public surface: only ops defined in this module (tape-aware wrappers carry
# __wrapped_pure__; plain helpers must be defined here, not imported).
__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
