"""Elementwise & reduction math ops (parity: python/paddle/tensor/math.py).

The bulk of this surface is GENERATED from the op schema
(ops/gen/ops.yaml -> ops/generated_math.py; reference:
paddle/phi/api/yaml/ops.yaml + its generator pipeline, SURVEY Appendix A).
Only ops with genuinely bespoke control flow stay hand-written here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op
from paddle_tpu.ops.generated_math import *  # noqa: F401,F403
from paddle_tpu.ops.generated_math import remainder, __all__ as _gen_all

# paddle-parity aliases
mod = remainder
floor_mod = remainder


@eager_op
def rsqrt_(x):  # convenience pure form
    return jax.lax.rsqrt(x)


@eager_op(name="multiplex")
def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)  # [n, batch, ...]
    idx = jnp.reshape(index, (-1,))
    return stacked[idx, jnp.arange(idx.shape[0])]


@eager_op(name="renorm")
def renorm(x, p, axis, max_norm):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    out = flat * factor[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


__all__ = [n for n in _gen_all if n != "OP_INFO"] + [
    "mod", "floor_mod", "rsqrt_", "multiplex", "renorm"]
