"""Weight-only quantized matmul — int8/fp8 Pallas kernel, fused dequant.

The serving tentpole (ROADMAP item 4): decode is bandwidth-bound, and
bf16 weights are most of the bytes a decode step reads.  Weight-only
quantization stores every large 2-D weight as int8 (symmetric,
per-output-channel scale) or ``float8_e4m3fn`` and reads HALF (bf16) /
a QUARTER (fp32) of the weight bytes per matmul.  The kernel keeps the
fused-block discipline: the quantized weight block is DMA'd once,
up-converted in VMEM registers, multiplied on the MXU with an fp32
accumulator, and the per-channel scale multiply lands on that fp32
accumulator before the single cast to the io dtype — the dequantized
weight never exists in HBM.

Grid ``(token_blocks, out_blocks)``; K is unblocked (a ``[K, block_n]``
int8 weight tile at serving hidden sizes is well under VMEM), so each
grid step is one clean MXU contraction and the blocked result is
bitwise the unblocked one — which is why :func:`quant_matmul_reference`
(the jnp scale-multiply fallback, same op order) doubles as the
correctness oracle in interpret-mode tests.

Tile candidates are one more autotune axis (TVM-style, PAPERS.md):
``autotune.quant_block_sizes`` enumerates/benches ``(block_t,
block_n)`` through the persistent v2 cache, and the offline sweep CLI
(``python -m paddle_tpu.ops.pallas.autotune --sweep``) covers the
bench shapes for both wdtypes.

Routing is trace-time (``quant_matmul`` picks kernel vs fallback and
records ``paddle_tpu_quant_kernel_path_total{kernel,path}``), so
serving BENCH trajectories can attribute wins to the exact
implementation.  ``PADDLE_TPU_QUANT_MATMUL=0`` forces the fallback.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["quant_matmul", "quant_matmul_pallas", "quant_matmul_reference",
           "quant_matmul_eligible", "quant_matmul_env", "record_path",
           "weight_dtype", "QUANT_WEIGHT_DTYPES"]


def weight_dtype(mode: str):
    """The storage dtype of a quant mode: ``int8`` or ``fp8``
    (``float8_e4m3fn`` via ml_dtypes — jax's extended dtypes)."""
    if mode == "int8":
        return jnp.dtype(jnp.int8)
    if mode == "fp8":
        import ml_dtypes
        return jnp.dtype(ml_dtypes.float8_e4m3fn)
    raise ValueError(f"unknown quant mode {mode!r}; expected int8|fp8")


QUANT_WEIGHT_DTYPES = ("int8", "fp8")


def quant_matmul_env():
    """``PADDLE_TPU_QUANT_MATMUL``: 0 forces the jnp fallback, 1 forces
    the Pallas kernel (still TPU-only), unset → auto."""
    raw = os.environ.get("PADDLE_TPU_QUANT_MATMUL")
    if raw is None:
        return None
    return raw.strip().lower() in ("1", "true", "yes", "on")


def quant_matmul_eligible(t: int, k: int, n: int, x_dtype) -> bool:
    """Trace-time routing: TPU backend, lane-aligned K and N, token axis
    tiling the io dtype's sublane minimum (decode at tiny batch falls
    back — the fallback is bitwise-equivalent anyway)."""
    env = quant_matmul_env()
    if env is False:
        return False
    if jax.default_backend() != "tpu" or not _HAVE_TPU_PL:
        return False
    s = str(jnp.dtype(x_dtype))
    q = 16 if ("bfloat16" in s or "float16" in s) else 8
    return t >= q and t % q == 0 and k % 128 == 0 and n % 128 == 0


def record_path(kernel: str, path: str):
    """Trace-time implementation counter — the quant analog of the
    fused-block / paged-attention path counters."""
    try:
        from paddle_tpu.observability import default_registry
        default_registry().counter(
            "paddle_tpu_quant_kernel_path_total",
            "quantized-kernel implementation chosen at trace time",
            labelnames=("kernel", "path")).labels(
            kernel=kernel, path=path).inc()
    except Exception:  # pragma: no cover - telemetry must never trace-fail
        pass


def _default_quant_blocks(t: int, n: int, xdtype=None):
    """Heuristic (block_t, block_n) when the autotune cache is cold.
    Always valid: falls back to degenerate blocks when a dim doesn't
    tile (interpret-mode tests at odd shapes).  ``xdtype`` (the io/
    activation dtype) restricts the row block to its sublane quantum
    (bf16/fp16 tiles pack 16 rows) so the choice Mosaic sees is never
    sublane-padded."""
    quantum = 16 if xdtype is not None and \
        ("bfloat16" in str(xdtype) or "float16" in str(xdtype)) else 8
    bt = None
    for c in (256, 128, 64, 32, 16, 8):   # quantum-aligned first
        if c % quantum == 0 and t >= c and t % c == 0:
            bt = c
            break
    if bt is None:                        # degenerate shapes: old ladder
        bt = 1
        for c in (256, 128, 64, 32, 16, 8):
            if t >= c and t % c == 0:
                bt = c
                break
    bn = n
    for c in (512, 256, 128):
        if n % c == 0:
            bn = c
            break
    return (bt, bn)


def _quant_kernel(x_ref, w_ref, s_ref, o_ref):
    """One (token, out) tile: up-convert the quantized weight block in
    VMEM, contract on the MXU with an fp32 accumulator, and fold the
    per-output-channel scale into that accumulator before the single
    cast to the io dtype."""
    x = x_ref[:]
    w = w_ref[:].astype(x.dtype)                  # dequant, in-register
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[:] = (acc * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def quant_matmul_pallas(x2d, qw, scale, *, block_t=None, block_n=None,
                        interpret=None, autotune=True):
    """``x2d [T, K] @ dequant(qw [K, N], scale [N]) -> [T, N]`` via the
    Pallas kernel.  ``scale`` is the per-output-channel multiplier."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t, k = x2d.shape
    kk, n = qw.shape
    assert k == kk, (x2d.shape, qw.shape)
    if block_t is None or block_n is None:
        if autotune:
            from paddle_tpu.ops.pallas.autotune import quant_block_sizes
            bt, bn = quant_block_sizes(t, k, n, str(qw.dtype),
                                       str(x2d.dtype))
        else:
            bt, bn = _default_quant_blocks(t, n)
        block_t = block_t or bt
        block_n = block_n or bn
    if t % block_t or n % block_n:
        block_t, block_n = _default_quant_blocks(t, n)
    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"))
    return pl.pallas_call(
        _quant_kernel,
        grid=(t // block_t, n // block_n),
        in_specs=[
            pl.BlockSpec((block_t, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_t, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((t, n), x2d.dtype),
        interpret=interpret,
        **params,
    )(x2d, qw, scale.reshape(1, n))


def quant_matmul_reference(x2d, qw, scale):
    """The jnp scale-multiply fallback AND correctness oracle: identical
    op order to the kernel (up-convert to io dtype, fp32 MXU
    accumulation, per-channel scale on the accumulator, one final
    cast), so the two paths agree to blocked-vs-unblocked noise — zero
    at these shapes, since K is unblocked in the kernel."""
    w = qw.astype(x2d.dtype)
    acc = jax.lax.dot_general(
        x2d, w, (((x2d.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * scale.reshape(-1).astype(jnp.float32)).astype(x2d.dtype)


def quant_matmul(x, qw, scale, *, mode: str = "int8", interpret=None):
    """Weight-only quantized matmul with trace-time routing.

    ``x``: ``[..., K]`` activations (any leading dims); ``qw``:
    ``[K, N]`` int8 / float8_e4m3fn; ``scale``: ``[N]`` (or ``[1, N]``)
    fp32 per-output-channel dequant scale.  Returns ``[..., N]`` in
    ``x.dtype``.  Routes to the Pallas kernel when eligible; the jnp
    fallback is numerically identical.
    """
    lead = x.shape[:-1]
    k = x.shape[-1]
    n = qw.shape[1]
    t = 1
    for d in lead:
        t *= int(d)
    kernel = f"matmul_{mode}"
    use_pallas = quant_matmul_eligible(t, int(k), int(n), x.dtype) \
        if interpret is None else True
    record_path(kernel, "pallas" if use_pallas else "fallback")
    if not use_pallas:
        return quant_matmul_reference(x, qw, scale)
    x2d = x.reshape(t, k)
    out = quant_matmul_pallas(x2d, qw, scale, interpret=interpret)
    return out.reshape(lead + (n,))


# ---------------------------------------------------------------------------
# static verification (analysis/kernel_verify)


def verify_static(t, k, n, wdtype="int8", xdtype="bfloat16",
                  block_t=None, block_n=None):
    """Static Mosaic-legality findings for the weight-only quantized
    matmul at this shape/config — includes the scale-operand shape
    agreement check (``scale`` lanes must track the weight tile)."""
    from paddle_tpu.analysis import kernel_verify as kv
    wdtype, xdtype = str(wdtype), str(xdtype)
    if block_t is None or block_n is None:
        bt_d, bn_d = _default_quant_blocks(t, n, xdtype)
        block_t = block_t or bt_d
        block_n = block_n or bn_d
    bt, bn = int(block_t), int(block_n)
    spec = kv.KernelSpec(
        name="quant_matmul", grid=(t // bt if bt else 0,
                                   n // bn if bn else 0),
        args=[
            kv.ArgSpec("x", (t, k), (bt, k), lambda i, j: (i, 0), xdtype),
            kv.ArgSpec("qw", (k, n), (k, bn), lambda i, j: (0, j),
                       wdtype),
            kv.ArgSpec("scale", (1, n), (1, bn), lambda i, j: (0, j),
                       "float32"),
            kv.ArgSpec("o", (t, n), (bt, bn), lambda i, j: (i, j),
                       xdtype, is_output=True),
        ],
        dimension_semantics=("parallel", "parallel"),
        needs_fp32_acc=True, acc_inline=True,
        scale_pairs=[("scale", "qw")],
        where=f"quant_matmul[t={t} k={k} n={n} {wdtype}/{xdtype} "
              f"bt={bt} bn={bn}]")
    return kv.verify_kernel(spec)
