"""Flash attention — Pallas TPU kernel.

Replaces the reference's CUDA flash-attn integration
(python/paddle/nn/functional/flash_attention.py → _C_ops.flash_attn,
kernels under paddle/phi/kernels/gpu/flash_attn_*) with a TPU-native
blockwise kernel:

* forward: online-softmax over K/V blocks streamed HBM→VMEM by the grid
  pipeline; scores/accumulators live in VMEM scratch in fp32; the MXU does
  the two matmuls per block.  Saves per-row logsumexp for the backward.
* backward: blockwise recompute from the saved logsumexp (flash-attention-2
  style) expressed in JAX and left to XLA to fuse — dQ/dK/dV each come from
  one scan over blocks, so backward memory is O(seq·block), not O(seq²).

Layout: [batch, seq, heads, head_dim] (paddle convention) at the API;
kernels see [batch*heads, seq, head_dim].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, block_q, block_k, scale, causal,
                seq_len):
    """Grid: (batch*heads, num_q_blocks, num_k_blocks); the k axis is the
    innermost (sequential) dim, so VMEM scratch carries the online-softmax
    state across k blocks."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0]                                   # [bq, d]
        k = k_ref[0]                                   # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_ref[:]                              # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                         # [bq, bk]
        correction = jnp.exp(m_prev - m_new)           # [bq, 1]
        l_new = correction * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = m_new
        l_ref[:] = l_new

    if causal:
        # whole block above the diagonal → nothing to do
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:] + jnp.log(safe_l))[:, 0]


def _fwd_pallas(q, k, v, *, scale, causal, block_q, block_k,
                interpret=False):
    """q,k,v: [bh, s, d] → (out [bh, s, d], lse [bh, s])."""
    bh, s, d = q.shape
    nq = pl.cdiv(s, block_q)
    nk = pl.cdiv(s, block_k)

    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
        causal=causal, seq_len=s)

    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
    ]

    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)


# -- backward: blockwise recompute in JAX (flash-attn-2 equations) -----------

def _bwd_blockwise(res, g, *, scale, causal, block_k):
    """Memory-efficient backward: scan over K/V blocks; recompute P from
    q,k and the saved logsumexp.  All matmuls MXU-shaped; XLA fuses the
    elementwise chain."""
    q, k, v, out, lse = res           # q,k,v,out [bh,s,d]; lse [bh,s]
    bh, s, d = q.shape
    g = g.astype(jnp.float32)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    of = out.astype(jnp.float32)

    # delta_i = sum_d(dO * O) — rowwise (flash-attn-2 eq. 4)
    delta = jnp.sum(g * of, axis=-1)                   # [bh, s]

    nk = s // block_k
    kb = kf.reshape(bh, nk, block_k, d)
    vb = vf.reshape(bh, nk, block_k, d)

    q_pos = jnp.arange(s)

    def one_block(j):
        kj = kb[:, j]                                  # [bh, bk, d]
        vj = vb[:, j]
        sij = jnp.einsum("bqd,bkd->bqk", qf, kj) * scale
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            sij = jnp.where(mask[None], sij, _NEG_INF)
        pij = jnp.exp(sij - lse[:, :, None])           # [bh, q, bk]
        dv_j = jnp.einsum("bqk,bqd->bkd", pij, g)
        dp = jnp.einsum("bqd,bkd->bqk", g, vj)
        ds = pij * (dp - delta[:, :, None]) * scale
        dq_contrib = jnp.einsum("bqk,bkd->bqd", ds, kj)
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_contrib, dk_j, dv_j

    def scan_body(dq_acc, j):
        dq_c, dk_j, dv_j = one_block(j)
        return dq_acc + dq_c, (dk_j, dv_j)

    dq, (dks, dvs) = jax.lax.scan(scan_body, jnp.zeros_like(qf),
                                  jnp.arange(nk))
    dk = jnp.moveaxis(dks, 0, 1).reshape(bh, s, d)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(bh, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd_pallas(q, k, v, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    return _bwd_blockwise(res, g, scale=scale, causal=causal,
                          block_k=block_k)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = None):
    """q,k,v: [batch, seq, heads, head_dim] (paddle layout).  Requires seq
    divisible by the block sizes (callers pad; the model stack keeps seq a
    multiple of 128 for MXU efficiency anyway)."""
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq {s} must be divisible by block sizes "
                         f"({block_q},{block_k})")

    # GQA/MQA: broadcast kv heads to q heads
    hk = k.shape[2]
    if hk != h:
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)

    out = _flash_core(to_bh(q), to_bh(k), to_bh(v), float(scale),
                      bool(causal), block_q, block_k, bool(interpret))
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)
