"""Flash attention — Pallas TPU kernel.

Replaces the reference's CUDA flash-attn integration
(python/paddle/nn/functional/flash_attention.py → _C_ops.flash_attn,
kernels under paddle/phi/kernels/gpu/flash_attn_*) with a TPU-native
blockwise kernel:

* forward: online-softmax over K/V blocks streamed HBM→VMEM by the grid
  pipeline; scores/accumulators live in VMEM scratch in fp32; the MXU does
  the two matmuls per block.  Saves per-row logsumexp for the backward.
  GQA/MQA is handled in the grid itself: the K/V BlockSpec index map sends
  q-head h to kv-head h // (hq // hk), so KV tiles are fetched once per
  group instead of materializing repeated heads in HBM.
* backward: blockwise recompute from the saved logsumexp (flash-attention-2
  style) expressed in JAX with grouped-GQA einsums and left to XLA to fuse —
  dQ/dK/dV each come from one scan over blocks, so backward memory is
  O(seq·block), not O(seq²), and dK/dV sum over the query group without
  ever materializing repeated KV.

Mosaic legality notes (the round-1 kernel broke here): every output block's
last two dims must be (divisible by 8, divisible by 128) or equal to the
array dims.  The logsumexp is therefore emitted as [b, h, nq, 1, block_q]
— block (1,1,1,1,block_q) is legal because the trailing two dims equal the
array's — and reshaped to [b, h, s] outside the kernel.

Layout: [batch, seq, heads, head_dim] (paddle convention) at the API;
kernels see [batch, heads, seq, head_dim].
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["flash_attention", "flash_bwd_env"]


def flash_bwd_env():
    """Backward-implementation override from the environment:
    ``PADDLE_TPU_FLASH_BWD=1`` forces the Pallas dq/dkv kernels, ``0``
    the blockwise-jax recompute; unset → None (autotuner / call site
    decides).  ``PT_FLASH_PALLAS_BWD`` is honored as a legacy alias."""
    raw = os.environ.get("PADDLE_TPU_FLASH_BWD",
                         os.environ.get("PT_FLASH_PALLAS_BWD"))
    if raw is None:
        return None
    return raw.strip().lower() in ("1", "true", "yes", "on")


def _bwd_path_counter():
    from paddle_tpu.observability import default_registry
    return default_registry().counter(
        "paddle_tpu_flash_bwd_path_total",
        "flash-attention backward implementation chosen at trace time",
        labelnames=("path",))

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, block_q, block_k, scale, causal):
    """Grid: (batch, q_heads, num_q_blocks, num_k_blocks); the k axis is the
    innermost (sequential) dim, so VMEM scratch carries the online-softmax
    state across k blocks."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0, 0]                                # [bq, d]
        k = k_ref[0, 0]                                # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_ref[:]                              # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                         # [bq, bk]
        correction = jnp.exp(m_prev - m_new)           # [bq, 1]
        l_new = correction * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = m_new
        l_ref[:] = l_new

    if causal:
        # whole block above the diagonal → nothing to do
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse = m_ref[:] + jnp.log(safe_l)               # [bq, 1]
        lse_ref[0, 0, 0] = lse.reshape(1, block_q)


def _fwd_pallas(q, k, v, *, scale, causal, block_q, block_k,
                interpret=False):
    """q: [b, hq, s, d]; k,v: [b, hk, s, d] → (out [b, hq, s, d],
    lse [b, hq, s] fp32)."""
    b, hq, s, d = q.shape
    hk = k.shape[1]
    rep = hq // hk
    nq = pl.cdiv(s, block_q)
    nk = pl.cdiv(s, block_k)

    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
        causal=causal)

    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
    ]

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))

    out, lse5 = pl.pallas_call(
        kernel,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, i, j: (b_, h // rep, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, i, j: (b_, h // rep, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, 1, 1, block_q),
                         lambda b_, h, i, j: (b_, h, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, nq, 1, block_q), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
        **params,
    )(q, k, v)
    return out, lse5.reshape(b, hq, s)


# -- backward: Pallas kernels (flash-attn-2 equations) -----------------------
#
# Both kernels work in TRANSPOSED score space (s_T[k, q] instead of
# s[q, k]): the per-ROW softmax statistics (lse, delta) then enter as
# [1, block_q] row vectors that broadcast over the k dimension with no
# in-kernel transpose/relayout, and every contraction is a dot_general the
# MXU handles directly.

def _bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, block_q, block_k, scale, causal):
    """Grid: (b, hq, nq, nk); k inner — dq accumulates across k blocks."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0, 0]                                 # [bq, d]
        k = k_ref[0, 0]                                 # [bk, d]
        v = v_ref[0, 0]
        g = g_ref[0, 0]
        lse_row = lse_ref[0, 0, 0]                      # [1, bq]
        delta_row = delta_ref[0, 0, 0]                  # [1, bq]

        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bk, bq]
        if causal:
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            s_t = jnp.where(q_pos >= k_pos, s_t, _NEG_INF)
        p_t = jnp.exp(s_t - lse_row)                    # [bk, bq]
        dp_t = jax.lax.dot_general(
            v, g, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bk, bq]
        ds_t = p_t * (dp_t - delta_row) * scale
        # dq[q, d] = sum_k ds_T[k, q] * k[k, d]
        acc_ref[:] += jax.lax.dot_general(
            ds_t, k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _flush():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k,
                    nq, rep, scale, causal):
    """Grid: (b, hk, nk, rep*nq); inner axis walks every (group head,
    q block) pair — dk/dv accumulate over the whole query group, so
    repeated KV heads are never materialized (GQA)."""
    kj = pl.program_id(2)
    t = pl.program_id(3)
    nt = pl.num_programs(3)
    qi = t % nq

    @pl.when(t == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0, 0]                                 # [bq, d]
        k = k_ref[0, 0]                                 # [bk, d]
        v = v_ref[0, 0]
        g = g_ref[0, 0]
        lse_row = lse_ref[0, 0, 0]                      # [1, bq]
        delta_row = delta_ref[0, 0, 0]

        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bk, bq]
        if causal:
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            s_t = jnp.where(q_pos >= k_pos, s_t, _NEG_INF)
        p_t = jnp.exp(s_t - lse_row)
        # dv[k, d] = sum_q p_T[k, q] * g[q, d]
        dv_acc[:] += jax.lax.dot_general(
            p_t.astype(jnp.float32), g.astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp_t = jax.lax.dot_general(
            v, g, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds_t = p_t * (dp_t - delta_row) * scale
        # dk[k, d] = sum_q ds_T[k, q] * q[q, d]
        dk_acc[:] += jax.lax.dot_general(
            ds_t, q.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(t == nt - 1)
    def _flush():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas(res, g, *, scale, causal, block_q, block_k, interpret):
    """Pallas flash backward: dq from one kernel (k inner), dk/dv from a
    second (query-group inner).  lse/delta ride as [b,hq,nq,1,bq] so each
    q block's statistics arrive as a [1, bq] row vector."""
    q, k, v, out, lse = res      # q,out [b,hq,s,d]; k,v [b,hk,s,d]
    b, hq, s, d = q.shape
    hk = k.shape[1]
    rep = hq // hk
    nq = pl.cdiv(s, block_q)
    nk = pl.cdiv(s, block_k)

    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                              # [b, hq, s]
    lse5 = lse.reshape(b, hq, nq, 1, block_q)
    delta5 = delta.reshape(b, hq, nq, 1, block_q)

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal),
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, i, j: (b_, h // rep, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, i, j: (b_, h // rep, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, 1, 1, block_q),
                         lambda b_, h, i, j: (b_, h, i, 0, 0)),
            pl.BlockSpec((1, 1, 1, 1, block_q),
                         lambda b_, h, i, j: (b_, h, i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h, i, j: (b_, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(q, k, v, g, lse5, delta5)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, nq=nq, rep=rep, scale=scale,
                          causal=causal),
        grid=(b, hk, nk, rep * nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, g_, j, t: (b_, g_ * rep + t // nq,
                                               t % nq, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, g_, j, t: (b_, g_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, g_, j, t: (b_, g_, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, g_, j, t: (b_, g_ * rep + t // nq,
                                               t % nq, 0)),
            pl.BlockSpec((1, 1, 1, 1, block_q),
                         lambda b_, g_, j, t: (b_, g_ * rep + t // nq,
                                               t % nq, 0, 0)),
            pl.BlockSpec((1, 1, 1, 1, block_q),
                         lambda b_, g_, j, t: (b_, g_ * rep + t // nq,
                                               t % nq, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, g_, j, t: (b_, g_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, g_, j, t: (b_, g_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hk, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, hk, s, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(q, k, v, g, lse5, delta5)
    return dq, dk, dv


# -- backward: blockwise recompute in JAX (flash-attn-2 equations) -----------

def _bwd_blockwise(res, g, *, scale, causal, block_k):
    """Memory-efficient backward: scan over K/V blocks; recompute P from
    q,k and the saved logsumexp.  Grouped-GQA einsums keep KV at hk heads;
    dK/dV sum over the query group (r axis) inside the contraction.  All
    matmuls MXU-shaped; XLA fuses the elementwise chain."""
    q, k, v, out, lse = res      # q,out [b,hq,s,d]; k,v [b,hk,s,d]
    b, hq, s, d = q.shape
    hk = k.shape[1]
    rep = hq // hk
    g = g.astype(jnp.float32)
    qf = q.astype(jnp.float32).reshape(b, hk, rep, s, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    of = out.astype(jnp.float32)
    gg = g.reshape(b, hk, rep, s, d)
    lse_g = lse.reshape(b, hk, rep, s)

    # delta_i = sum_d(dO * O) — rowwise (flash-attn-2 eq. 4)
    delta = jnp.sum(g * of, axis=-1).reshape(b, hk, rep, s)

    nk = s // block_k
    kb = kf.reshape(b, hk, nk, block_k, d)
    vb = vf.reshape(b, hk, nk, block_k, d)

    q_pos = jnp.arange(s)

    def one_block(j):
        kj = kb[:, :, j]                               # [b, hk, bk, d]
        vj = vb[:, :, j]
        sij = jnp.einsum("bgrqd,bgkd->bgrqk", qf, kj) * scale
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            sij = jnp.where(mask[None, None, None], sij, _NEG_INF)
        pij = jnp.exp(sij - lse_g[..., None])          # [b,g,r,q,bk]
        dv_j = jnp.einsum("bgrqk,bgrqd->bgkd", pij, gg)
        dp = jnp.einsum("bgrqd,bgkd->bgrqk", gg, vj)
        ds = pij * (dp - delta[..., None]) * scale
        dq_contrib = jnp.einsum("bgrqk,bgkd->bgrqd", ds, kj)
        dk_j = jnp.einsum("bgrqk,bgrqd->bgkd", ds, qf)
        return dq_contrib, dk_j, dv_j

    def scan_body(dq_acc, j):
        dq_c, dk_j, dv_j = one_block(j)
        return dq_acc + dq_c, (dk_j, dv_j)

    dq, (dks, dvs) = jax.lax.scan(scan_body, jnp.zeros_like(qf),
                                  jnp.arange(nk))
    dq = dq.reshape(b, hq, s, d)
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, hk, s, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, hk, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_core(q, k, v, scale, causal, block_q, block_k, interpret,
                pallas_bwd):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        interpret, pallas_bwd)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               pallas_bwd):
    out, lse = _fwd_pallas(q, k, v, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, pallas_bwd,
               res, g):
    if pallas_bwd:
        return _bwd_pallas(res, g, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)
    return _bwd_blockwise(res, g, scale=scale, causal=causal,
                          block_k=block_k)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = None, block_k: int = None,
                    interpret: bool = None, pallas_bwd: bool = None,
                    autotune: bool = None):
    """q: [batch, seq, heads, head_dim]; k,v: [batch, seq, kv_heads,
    head_dim] (paddle layout).  Requires seq divisible by the block sizes
    (callers pad; the model stack keeps seq a multiple of 128 for MXU
    efficiency anyway) and heads % kv_heads == 0.

    block_q/block_k — and the backward implementation, when
    ``pallas_bwd`` is left None — default to the autotuner's cached
    choice on TPU (measured once per shape, persisted — reference analog:
    phi/kernels/autotune/auto_tune_base.h); elsewhere min(128, s) blocks
    and the Pallas backward.  ``pallas_bwd=False`` forces the
    blockwise-jax backward, True the Pallas dq/dkv kernels."""
    b, s, h, d = q.shape
    hk = k.shape[2]
    if h % hk:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hk}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if autotune is None:
        autotune = not interpret
    if pallas_bwd is None:
        pallas_bwd = flash_bwd_env()
    if block_q is None or block_k is None or pallas_bwd is None:
        if autotune and not interpret:
            from paddle_tpu.ops.pallas.autotune import flash_block_sizes
            bq_t, bk_t, pb_t = flash_block_sizes(
                b, s, h, hk, d, str(q.dtype), bool(causal),
                pallas_bwd=pallas_bwd)
            block_q = block_q or bq_t
            block_k = block_k or bk_t
            if pallas_bwd is None:
                pallas_bwd = pb_t
        else:
            block_q = block_q or min(128, s)
            block_k = block_k or min(128, s)
            if pallas_bwd is None:
                pallas_bwd = True
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq {s} must be divisible by block sizes "
                         f"({block_q},{block_k})")

    # trace-time telemetry: which backward this compile will run
    _bwd_path_counter().labels(
        path="pallas" if pallas_bwd else "blockwise").inc()

    def to_bhsd(x):
        return jnp.swapaxes(x, 1, 2)

    out = _flash_core(to_bhsd(q), to_bhsd(k), to_bhsd(v), float(scale),
                      bool(causal), block_q, block_k, bool(interpret),
                      bool(pallas_bwd))
    return jnp.swapaxes(out, 1, 2)


# ---------------------------------------------------------------------------
# static verification (analysis/kernel_verify) — the fwd / bwd-dq /
# bwd-dkv pallas_calls described as KernelSpecs, same grids and index
# maps the real calls install.


def _fwd_verify_spec(b, s, h, hk, d, bq, bk, dtype):
    from paddle_tpu.analysis import kernel_verify as kv
    rep = h // hk
    nq, nk = s // bq, s // bk
    q4 = (b, h, s, d)
    kv4 = (b, hk, s, d)
    return kv.KernelSpec(
        name="flash_fwd", grid=(b, h, nq, nk),
        args=[
            kv.ArgSpec("q", q4, (1, 1, bq, d),
                       lambda b_, h_, i, j: (b_, h_, i, 0), dtype),
            kv.ArgSpec("k", kv4, (1, 1, bk, d),
                       lambda b_, h_, i, j: (b_, h_ // rep, j, 0), dtype),
            kv.ArgSpec("v", kv4, (1, 1, bk, d),
                       lambda b_, h_, i, j: (b_, h_ // rep, j, 0), dtype),
            kv.ArgSpec("o", q4, (1, 1, bq, d),
                       lambda b_, h_, i, j: (b_, h_, i, 0), dtype,
                       is_output=True),
            kv.ArgSpec("lse", (b, h, nq, 1, bq), (1, 1, 1, 1, bq),
                       lambda b_, h_, i, j: (b_, h_, i, 0, 0), "float32",
                       is_output=True),
        ],
        scratch=[kv.ScratchSpec("acc", (bq, d), "float32"),
                 kv.ScratchSpec("m", (bq, 1), "float32"),
                 kv.ScratchSpec("l", (bq, 1), "float32")],
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"),
        needs_fp32_acc=True,
        where=f"flash_fwd[b={b} s={s} h={h}/{hk} d={d} bq={bq} bk={bk} "
              f"{dtype}]")


def _bwd_dq_verify_spec(b, s, h, hk, d, bq, bk, dtype):
    from paddle_tpu.analysis import kernel_verify as kv
    rep = h // hk
    nq, nk = s // bq, s // bk
    q4, kv4, stat5 = (b, h, s, d), (b, hk, s, d), (b, h, nq, 1, bq)
    qmap = lambda b_, h_, i, j: (b_, h_, i, 0)
    kmap = lambda b_, h_, i, j: (b_, h_ // rep, j, 0)
    smap = lambda b_, h_, i, j: (b_, h_, i, 0, 0)
    return kv.KernelSpec(
        name="flash_bwd_dq", grid=(b, h, nq, nk),
        args=[
            kv.ArgSpec("q", q4, (1, 1, bq, d), qmap, dtype),
            kv.ArgSpec("k", kv4, (1, 1, bk, d), kmap, dtype),
            kv.ArgSpec("v", kv4, (1, 1, bk, d), kmap, dtype),
            kv.ArgSpec("g", q4, (1, 1, bq, d), qmap, dtype),
            kv.ArgSpec("lse", stat5, (1, 1, 1, 1, bq), smap, "float32"),
            kv.ArgSpec("delta", stat5, (1, 1, 1, 1, bq), smap, "float32"),
            kv.ArgSpec("dq", q4, (1, 1, bq, d), qmap, dtype,
                       is_output=True),
        ],
        scratch=[kv.ScratchSpec("acc", (bq, d), "float32")],
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"),
        needs_fp32_acc=True,
        where=f"flash_bwd_dq[b={b} s={s} h={h}/{hk} d={d} bq={bq} "
              f"bk={bk} {dtype}]")


def _bwd_dkv_verify_spec(b, s, h, hk, d, bq, bk, dtype):
    from paddle_tpu.analysis import kernel_verify as kv
    rep = h // hk
    nq, nk = s // bq, s // bk
    q4, kv4, stat5 = (b, h, s, d), (b, hk, s, d), (b, h, nq, 1, bq)
    qmap = lambda b_, g_, j, t: (b_, g_ * rep + t // nq, t % nq, 0)
    kmap = lambda b_, g_, j, t: (b_, g_, j, 0)
    smap = lambda b_, g_, j, t: (b_, g_ * rep + t // nq, t % nq, 0, 0)
    return kv.KernelSpec(
        name="flash_bwd_dkv", grid=(b, hk, nk, rep * nq),
        args=[
            kv.ArgSpec("q", q4, (1, 1, bq, d), qmap, dtype),
            kv.ArgSpec("k", kv4, (1, 1, bk, d), kmap, dtype),
            kv.ArgSpec("v", kv4, (1, 1, bk, d), kmap, dtype),
            kv.ArgSpec("g", q4, (1, 1, bq, d), qmap, dtype),
            kv.ArgSpec("lse", stat5, (1, 1, 1, 1, bq), smap, "float32"),
            kv.ArgSpec("delta", stat5, (1, 1, 1, 1, bq), smap, "float32"),
            kv.ArgSpec("dk", kv4, (1, 1, bk, d), kmap, dtype,
                       is_output=True),
            kv.ArgSpec("dv", kv4, (1, 1, bk, d), kmap, dtype,
                       is_output=True),
        ],
        scratch=[kv.ScratchSpec("dk_acc", (bk, d), "float32"),
                 kv.ScratchSpec("dv_acc", (bk, d), "float32")],
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"),
        needs_fp32_acc=True,
        where=f"flash_bwd_dkv[b={b} s={s} h={h}/{hk} d={d} bq={bq} "
              f"bk={bk} {dtype}]")


def verify_static(b, s, h, hk, d, dtype="bfloat16", causal=True,
                  block_q=None, block_k=None, parts=("fwd", "bwd")):
    """Static Mosaic-legality findings for the flash kernels at this
    shape/config.  ``parts`` selects fwd and/or the two Pallas backward
    kernels; defaults mirror :func:`flash_attention`'s non-autotuned
    block choice (min(128, s))."""
    from paddle_tpu.analysis import kernel_verify as kv
    del causal  # masking happens in-kernel; the layout is causal-agnostic
    dtype = str(dtype)
    bq = min(int(block_q or min(128, s)), s)
    bk = min(int(block_k or min(128, s)), s)
    diags = []
    if "fwd" in parts:
        diags += kv.verify_kernel(_fwd_verify_spec(b, s, h, hk, d, bq, bk,
                                                   dtype))
    if "bwd" in parts:
        diags += kv.verify_kernel(_bwd_dq_verify_spec(b, s, h, hk, d, bq,
                                                      bk, dtype))
        diags += kv.verify_kernel(_bwd_dkv_verify_spec(b, s, h, hk, d, bq,
                                                       bk, dtype))
    return diags
