"""Kernel block-size autotuner — persistent, versioned cache + offline sweep.

Reference parity: ``phi/kernels/autotune/auto_tune_base.h`` +
``cache_base.h`` — the reference times kernel variants at first
invocation and caches the winner per shape key.  TPU-native version:
candidates are Pallas block-size configurations; each is compiled and
timed ONCE on the real chip at first use of a shape (this works even
when the op is hit inside a ``jit`` trace — the measurement runs
concrete side inputs, not tracers), and the winner persists to a
versioned on-disk JSON cache so later processes skip the sweep
entirely.

Two ways entries get into the cache:

* **lazy** — first use of a shape on-chip measures candidates and
  persists the winner (the original behaviour);
* **offline sweep** — ``python -m paddle_tpu.ops.pallas.autotune
  --sweep`` enumerates the candidate grid for every kernel (flash
  attention, fused CE, fused rmsnorm+QKV, fused MLP) over the bench
  shapes, TVM-style (PAPERS.md), and writes the winners in one go.
  ``--dry-run`` skips timing (heuristic winners) but exercises the full
  persistence round-trip — the CI gate for machines without a chip.
  The checked-in ``benchmarks/autotune_tpu_v5.json`` is loaded as a
  read-only seed layer so cold starts and fresh clones get tuned sizes
  without ever re-timing.

Cache format (schema ``version`` bumps invalidate silently — old or
corrupt/truncated files fall back to heuristic defaults, never raise)::

    {"version": 2,
     "entries": {"<op>|<shape-key>@<backend>": [block, sizes, ...]}}

Keys carry the dtype AND the backend (``tpu:<device_kind>`` vs
``cpu-interpret``), so a CPU test run can never poison the TPU entry
for the same shape.

Env knobs:
  PADDLE_TPU_AUTOTUNE=0           disable (use the heuristic default)
  PADDLE_TPU_AUTOTUNE_CACHE=path  cache file (default
                                  ~/.cache/paddle_tpu_autotune.json)
  PADDLE_TPU_AUTOTUNE_SEED=path   shipped seed cache override ("0"
                                  disables the seed layer)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, Dict, Sequence, Tuple

__all__ = ["autotune", "flash_block_sizes", "ce_block_sizes",
           "qkv_block_sizes", "mlp_block_sizes", "quant_block_sizes",
           "decoder_block_sizes", "cache_path", "seed_path",
           "backend_tag", "cached_entries", "clear_cache", "reload",
           "CACHE_VERSION", "main"]

CACHE_VERSION = 2

_mem_cache: Dict[str, object] = {}
_loaded = False


# -- persistence -------------------------------------------------------------

def cache_path() -> str:
    return os.environ.get(
        "PADDLE_TPU_AUTOTUNE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "paddle_tpu_autotune.json"))


def seed_path() -> str:
    """The checked-in cache shipped with the repo (read-only base
    layer); "" disables."""
    env = os.environ.get("PADDLE_TPU_AUTOTUNE_SEED")
    if env is not None:
        return "" if env in ("0", "") else env
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(here, "..", "..", "..", "benchmarks",
                        "autotune_tpu_v5.json")


def _parse(path: str):
    """Entries of a cache file, or None when the file is missing,
    truncated, corrupt or of a different schema version — silent
    invalidation, the caller falls back to heuristics/benching."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except Exception:
        return None
    if not isinstance(raw, dict) or raw.get("version") != CACHE_VERSION:
        return None
    entries = raw.get("entries")
    return entries if isinstance(entries, dict) else None


def _load():
    global _loaded
    if _loaded:
        return
    _loaded = True
    sp = seed_path()
    if sp:
        seed = _parse(sp)
        if seed:
            _mem_cache.update(seed)
    user = _parse(cache_path())
    if user:
        _mem_cache.update(user)         # user cache overrides the seed


def _save(path: str = None):
    path = path or cache_path()
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # merge-then-atomic-replace: concurrent processes benching
        # different shapes must not clobber each other or expose a
        # half-written file to readers
        merged = dict(_parse(path) or {})
        merged.update(_mem_cache)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": CACHE_VERSION, "entries": merged},
                      f, indent=0, sort_keys=True)
        os.replace(tmp, path)
    except Exception:
        pass  # read-only fs: in-memory cache still works


def clear_cache():
    global _loaded
    _mem_cache.clear()
    _loaded = True
    try:
        os.remove(cache_path())
    except OSError:
        pass


def reload():
    """Forget the in-memory state so the next lookup re-reads the cache
    file(s) — for tests that swap PADDLE_TPU_AUTOTUNE_CACHE."""
    global _loaded
    _mem_cache.clear()
    _loaded = False


def cached_entries() -> Dict[str, object]:
    _load()
    return dict(_mem_cache)


# -- keys --------------------------------------------------------------------

def backend_tag(interpret: bool = None) -> str:
    """The backend component of every cache key: a TPU entry is keyed by
    the device kind; anything else (including interpret-mode kernels on
    a TPU host) is ``cpu-interpret`` — disjoint namespaces, so CPU test
    runs can never poison a chip's tuned entry."""
    try:
        import jax
        dev = jax.devices()[0]
        if not interpret and dev.platform == "tpu":
            return f"tpu:{getattr(dev, 'device_kind', '?')}" \
                .replace(" ", "_")
    except Exception:
        pass
    return "cpu-interpret"


# -- core --------------------------------------------------------------------

def _cache_counter():
    from paddle_tpu.observability import default_registry
    return default_registry().counter(
        "paddle_tpu_autotune_cache_total",
        "autotune persistent-cache lookups by outcome",
        labelnames=("op", "result"))


def enabled() -> bool:
    if os.environ.get("PADDLE_TPU_AUTOTUNE", "1") == "0":
        return False
    # multi-controller runs must compile IDENTICAL programs on every
    # process; per-host timing sweeps could disagree (noise) and deadlock
    # the first collective — use the deterministic default there
    try:
        import jax
        if jax.process_count() > 1:
            return False
    except Exception:
        pass
    return True


def _verify_prune(op: str, shape: tuple, cands: list):
    """Drop candidates the static verifier proves Mosaic-illegal before
    any of them is benchmarked (TVM-style legality-before-search).
    Returns (kept, n_pruned); never empties the set and never raises —
    a broken verifier must not cost a sweep."""
    try:
        from paddle_tpu.analysis.kernel_verify import prune_candidates
        return prune_candidates(op, shape, cands)
    except Exception:   # pragma: no cover - verifier bugs must not bench-fail
        return list(cands), 0


def autotune(op_name: str, key: str, candidates: Sequence,
             bench: Callable[[object], float], default):
    """Return the cached winner for (op_name, key), measuring once.

    bench(candidate) -> seconds (lower is better); raise/inf to
    disqualify a candidate.  Falls back to ``default`` when disabled or
    when every candidate fails."""
    full_key = f"{op_name}|{key}"
    _load()
    if full_key in _mem_cache:
        try:
            _cache_counter().labels(op=op_name, result="hit").inc()
        except Exception:
            pass
        got = _mem_cache[full_key]
        return tuple(got) if isinstance(got, list) else got
    if not enabled():
        return default
    try:
        _cache_counter().labels(op=op_name, result="miss").inc()
    except Exception:
        pass

    best, best_t = None, float("inf")
    for c in candidates:
        try:
            t = bench(c)
        except Exception:
            continue
        if t < best_t:
            best, best_t = c, t
    if best is None:
        best = default
    else:
        _feed_calibration(op_name, key, best_t)
    _mem_cache[full_key] = list(best) if isinstance(best, tuple) else best
    _save()
    return best


def _feed_calibration(op_name: str, key: str, measured_s: float):
    """Measurement-ledger feeder (PADDLE_TPU_CALIBRATION=1): the
    winner's benched seconds land in the calibration ledger under the
    kernel's own content-addressed key — the autotune sweep is one of
    the three measurement sources the calibrated cost model reads."""
    try:
        from paddle_tpu.observability import calibration
        if not calibration.enabled():
            return
        # the autotune key already embeds its backend tag; strip it and
        # let the ledger key carry the process fingerprint instead
        shape_part = key.rsplit("@", 1)[0]
        calibration.ledger().record(
            f"autotune:{op_name}", shape_part, measured_s=measured_s,
            provenance="autotune")
    except Exception:
        pass


def _put(op_name: str, key: str, value):
    """Record a winner without benching (offline sweep writer)."""
    _load()
    _mem_cache[f"{op_name}|{key}"] = \
        list(value) if isinstance(value, tuple) else value


# -- flash attention ---------------------------------------------------------

def _flash_candidates(s: int, d: int, dtype: str,
                      pallas_bwd=None) -> list:
    """(block_q, block_k, pallas_bwd) candidates: block sizes bounded by
    the VMEM working set, crossed with the two backward implementations
    (Pallas dq/dkv kernels vs the blockwise-jax recompute) — the variant
    choice is part of the tuning space, reference auto_tune_base style.
    A caller-pinned ``pallas_bwd`` constrains that dimension (no point
    benching a variant the call site will never use)."""
    blocks = []
    sizes = (128, 256) if s < 4096 else (128, 256, 512)
    for bq in sizes:
        for bk in sizes:
            if bq > s or bk > s or s % bq or s % bk:
                continue
            itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
            vmem = (2 * (bq + 2 * bk) * d * itemsize   # double-buffered io
                    + bq * bk * 4                      # score tile
                    + 2 * bq * d * 4)                  # fp32 accumulators
            if vmem < 10 * (1 << 20):
                blocks.append((bq, bk))
    blocks = blocks or [(min(128, s), min(128, s))]
    pbs = (True, False) if pallas_bwd is None else (bool(pallas_bwd),)
    return [(bq, bk, pb) for bq, bk in blocks for pb in pbs]


def flash_key(b, s, h, hk, d, dtype, causal, pallas_bwd=None,
              backend=None, interpret=None):
    pb_tag = "x" if pallas_bwd is None else str(int(bool(pallas_bwd)))
    return (f"b{b}s{s}h{h}k{hk}d{d}{dtype}c{int(causal)}pb{pb_tag}"
            f"@{backend or backend_tag(interpret)}")


def flash_block_sizes(b: int, s: int, h: int, hk: int, d: int,
                      dtype: str, causal: bool,
                      pallas_bwd=None) -> Tuple[int, int, bool]:
    """Measured (block_q, block_k, pallas_bwd) for this shape (the last
    entry echoes ``pallas_bwd`` when the caller pinned it)."""
    default = (min(128, s), min(128, s),
               True if pallas_bwd is None else bool(pallas_bwd))
    cands = _flash_candidates(s, d, dtype, pallas_bwd)
    cands, _ = _verify_prune("flash", (b, s, h, hk, d, dtype, causal),
                             cands)
    if len(cands) == 1:
        return tuple(cands[0])
    key = flash_key(b, s, h, hk, d, dtype, causal, pallas_bwd)

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.flash_attention import flash_attention

        bq, bk, pb = blocks
        iters = 8
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), dt)
        k = jnp.asarray(rng.standard_normal((b, s, hk, d)), dt)
        v = jnp.asarray(rng.standard_normal((b, s, hk, d)), dt)

        @jax.jit
        def run(q_, k_, v_):
            # iterations loop INSIDE the jit: one dispatch, so the
            # tunneled chip's per-call RPC latency cannot bias the sweep
            def loss(args):
                o = flash_attention(*args, causal=causal, block_q=bq,
                                    block_k=bk, pallas_bwd=pb,
                                    autotune=False)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            def body(i, carry):
                g = jax.grad(loss)((q_ * (1 + carry * 1e-12).astype(dt),
                                    k_, v_))
                return carry + sum(
                    jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in g)
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(q, k, v))                      # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(q, k, v))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("flash", key, cands, bench, default))


# -- fused cross-entropy -----------------------------------------------------

def _ce_candidates(t: int, v: int, dtype: str) -> list:
    """(block_t, block_v) candidates for the fused cross-entropy: the
    vocab block must divide V; VMEM holds the io block (double-buffered)
    plus one fp32 working copy and the [bt, 1] statistics."""
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    out = []
    for bt in (64, 128, 256):
        if bt > max(t, 8):
            continue
        for bv in (256, 512, 1024, 2048):
            if v % bv:
                continue
            vmem = bt * bv * (2 * itemsize + 4) + 8 * bt * 4
            if vmem < 10 * (1 << 20):
                out.append((bt, bv))
    if not out:
        from paddle_tpu.ops.pallas.cross_entropy import _default_blocks
        out = [_default_blocks(t, v)]
    return out


def ce_key(t, v, dtype, backend=None, interpret=None):
    return f"t{t}v{v}{dtype}@{backend or backend_tag(interpret)}"


def ce_block_sizes(t: int, v: int, dtype: str) -> Tuple[int, int]:
    """Measured (block_t, block_v) for the fused cross-entropy at this
    [tokens, vocab] shape (loss + grad timed together — the backward is
    where the one-hot traffic used to live)."""
    from paddle_tpu.ops.pallas.cross_entropy import _default_blocks
    default = _default_blocks(t, v)
    cands = _ce_candidates(t, v, dtype)
    cands, _ = _verify_prune("fused_ce", (t, v, dtype), cands)
    if len(cands) == 1:
        return tuple(cands[0])
    key = ce_key(t, v, dtype)

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.cross_entropy import \
            fused_softmax_cross_entropy

        bt, bv = blocks
        iters = 8
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.standard_normal((t, v)), dt)
        lbl = jnp.asarray(rng.integers(0, v, (t,)), jnp.int32)

        @jax.jit
        def run(x_, lbl_):
            def loss(a):
                return jnp.sum(fused_softmax_cross_entropy(
                    a, lbl_, block_t=bt, block_v=bv, autotune=False))

            def body(i, carry):
                g = jax.grad(loss)(x_ * (1 + carry * 1e-12).astype(dt))
                return carry + jnp.sum(jnp.abs(g).astype(jnp.float32))
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(x, lbl))                       # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(x, lbl))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("fused_ce", key, cands, bench, default))


# -- fused rmsnorm + QKV -----------------------------------------------------

def _qkv_candidates(t, d, dq, dk, dv, dtype) -> list:
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    out = []
    for bo in (128, 256, 512):
        if dq % bo or dk % bo or dv % bo:
            continue
        for bt in (64, 128, 256, 512):
            if t % bt:
                continue
            vmem = (2 * bt * d * itemsize + bt * d * 4
                    + 6 * d * bo * itemsize + 6 * bt * bo * itemsize)
            if vmem < 10 * (1 << 20):
                out.append((bt, bo))
    if not out:
        from paddle_tpu.ops.pallas.fused_block import _default_qkv_blocks
        out = [_default_qkv_blocks(t, d, dq, dk, dv, dtype)]
    return out


def qkv_key(t, d, dq, dk, dv, dtype, backend=None, interpret=None):
    return f"t{t}d{d}q{dq}k{dk}v{dv}{dtype}" \
           f"@{backend or backend_tag(interpret)}"


def qkv_block_sizes(t: int, d: int, dq: int, dk: int, dv: int,
                    dtype: str) -> Tuple[int, int]:
    """Measured (block_t, block_o) for the fused rmsnorm+QKV kernel
    (fwd + bwd timed together, matching how training hits it)."""
    from paddle_tpu.ops.pallas.fused_block import _default_qkv_blocks
    default = _default_qkv_blocks(t, d, dq, dk, dv, dtype)
    cands = _qkv_candidates(t, d, dq, dk, dv, dtype)
    cands, _ = _verify_prune("fused_qkv", (t, d, dq, dk, dv, dtype),
                             cands)
    if len(cands) == 1:
        return tuple(cands[0])
    key = qkv_key(t, d, dq, dk, dv, dtype)

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.fused_block import fused_rmsnorm_qkv

        bt, bo = blocks
        iters = 8
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.standard_normal((t, d)), dt)
        wn = jnp.ones((d,), dt)
        wq = jnp.asarray(rng.standard_normal((d, dq)) * 0.02, dt)
        wk = jnp.asarray(rng.standard_normal((d, dk)) * 0.02, dt)
        wv = jnp.asarray(rng.standard_normal((d, dv)) * 0.02, dt)

        @jax.jit
        def run(x_, wn_, wq_, wk_, wv_):
            def loss(a):
                q, k, v = fused_rmsnorm_qkv(a, wn_, wq_, wk_, wv_,
                                            block_t=bt, block_o=bo,
                                            autotune=False)
                return sum(jnp.sum(o.astype(jnp.float32) ** 2)
                           for o in (q, k, v))

            def body(i, carry):
                g = jax.grad(loss)(x_ * (1 + carry * 1e-12).astype(dt))
                return carry + jnp.sum(jnp.abs(g).astype(jnp.float32))
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(x, wn, wq, wk, wv))            # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(x, wn, wq, wk, wv))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("fused_qkv", key, cands, bench, default))


# -- fused MLP ---------------------------------------------------------------

def _mlp_candidates(t, d, f, dtype) -> list:
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    out = []
    for bf in (128, 256, 512):
        if f % bf:
            continue
        for bt in (64, 128, 256, 512):
            if t % bt:
                continue
            vmem = (4 * bt * d * itemsize + bt * d * 4
                    + 6 * d * bf * itemsize)
            if vmem < 10 * (1 << 20):
                out.append((bt, bf))
    if not out:
        from paddle_tpu.ops.pallas.fused_block import _default_mlp_blocks
        out = [_default_mlp_blocks(t, d, f, dtype)]
    return out


def mlp_key(t, d, f, dtype, backend=None, interpret=None):
    return f"t{t}d{d}f{f}{dtype}@{backend or backend_tag(interpret)}"


def mlp_block_sizes(t: int, d: int, f: int, dtype: str) -> Tuple[int, int]:
    """Measured (block_t, block_f) for the fused SwiGLU MLP kernel
    (fwd + bwd timed together)."""
    from paddle_tpu.ops.pallas.fused_block import _default_mlp_blocks
    default = _default_mlp_blocks(t, d, f, dtype)
    cands = _mlp_candidates(t, d, f, dtype)
    cands, _ = _verify_prune("fused_mlp", (t, d, f, dtype), cands)
    if len(cands) == 1:
        return tuple(cands[0])
    key = mlp_key(t, d, f, dtype)

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.fused_block import fused_mlp

        bt, bf = blocks
        iters = 8
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.standard_normal((t, d)), dt)
        wg = jnp.asarray(rng.standard_normal((d, f)) * 0.02, dt)
        wu = jnp.asarray(rng.standard_normal((d, f)) * 0.02, dt)
        wd = jnp.asarray(rng.standard_normal((f, d)) * 0.02, dt)

        @jax.jit
        def run(x_, wg_, wu_, wd_):
            def loss(a):
                y = fused_mlp(a, wg_, wu_, wd_, block_t=bt, block_f=bf,
                              autotune=False)
                return jnp.sum(y.astype(jnp.float32) ** 2)

            def body(i, carry):
                g = jax.grad(loss)(x_ * (1 + carry * 1e-12).astype(dt))
                return carry + jnp.sum(jnp.abs(g).astype(jnp.float32))
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(x, wg, wu, wd))                # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(x, wg, wu, wd))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("fused_mlp", key, cands, bench, default))


# -- whole-decoder-block megakernel ------------------------------------------

def _decoder_candidates(s, d, dq, dkv, hd, f, dtype) -> list:
    """(block_t, block_o, block_f) candidates for the whole-block
    kernel, bounded by its VMEM working set (the sequence-wide K/V
    scratch is a fixed cost every candidate pays)."""
    from paddle_tpu.ops.pallas.fused_block import (_DECODER_VMEM_BUDGET,
                                                   decoder_vmem_bytes)
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    qmin = 16 if itemsize == 2 else 8
    out = []
    for bo in (128, 256, 512):
        if bo % hd or dq % bo or dkv % bo or d % bo:
            continue
        for bf in (128, 256, 512):
            if f % bf:
                continue
            for bt in (qmin, 32, 64, 128, 256):
                if bt < qmin or s % bt:
                    continue
                if decoder_vmem_bytes(s, d, dq, dkv, hd, f, bt, bo, bf,
                                      dtype) < _DECODER_VMEM_BUDGET:
                    out.append((bt, bo, bf))
    if not out:
        from paddle_tpu.ops.pallas.fused_block import \
            _default_decoder_blocks
        fallback = _default_decoder_blocks(s, d, dq, dkv, hd, f, dtype)
        out = [fallback] if fallback else []
    return sorted(set(out))


def decoder_key(b, s, d, dq, dkv, hd, f, dtype, backend=None,
                interpret=None):
    return (f"b{b}s{s}d{d}q{dq}k{dkv}h{hd}f{f}{dtype}"
            f"@{backend or backend_tag(interpret)}")


def decoder_block_sizes(b, s, d, dq, dkv, hd, f,
                        dtype: str) -> Tuple[int, int, int]:
    """Measured (block_t, block_o, block_f) for the whole-decoder-block
    kernel (fwd + bwd timed together — the backward is the reference
    recompute, so the win being tuned lives in the forward)."""
    from paddle_tpu.ops.pallas.fused_block import _default_decoder_blocks
    default = _default_decoder_blocks(s, d, dq, dkv, hd, f, dtype)
    cands = _decoder_candidates(s, d, dq, dkv, hd, f, dtype)
    cands, _ = _verify_prune(
        "fused_decoder", (b, s, d, dq, dkv, hd, f, dtype), cands)
    if default is None:
        raise ValueError(
            f"no decoder block sizes fit the VMEM budget at s={s} d={d} "
            f"dkv={dkv} f={f}")
    if len(cands) <= 1:
        return tuple(cands[0]) if cands else tuple(default)
    key = decoder_key(b, s, d, dq, dkv, hd, f, dtype)

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.fused_block import fused_decoder_block

        bt, bo, bf = blocks
        iters = 4
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        nh, nkvh = dq // hd, dkv // hd
        x = jnp.asarray(rng.standard_normal((b, s, d)), dt)
        wn1 = jnp.ones((d,), dt)
        wn2 = jnp.ones((d,), dt)
        wq = jnp.asarray(rng.standard_normal((d, dq)) * 0.02, dt)
        wk = jnp.asarray(rng.standard_normal((d, dkv)) * 0.02, dt)
        wv = jnp.asarray(rng.standard_normal((d, dkv)) * 0.02, dt)
        wo = jnp.asarray(rng.standard_normal((dq, d)) * 0.02, dt)
        wg = jnp.asarray(rng.standard_normal((d, f)) * 0.02, dt)
        wu = jnp.asarray(rng.standard_normal((d, f)) * 0.02, dt)
        wd = jnp.asarray(rng.standard_normal((f, d)) * 0.02, dt)
        from paddle_tpu.nn.functional.attention import rotary_freqs
        cos, sin = rotary_freqs(hd, s)

        @jax.jit
        def run(x_):
            def loss(a):
                y = fused_decoder_block(
                    a, wn1, wq, wk, wv, cos, sin, wo, wn2, wg, wu, wd,
                    num_heads=nh, num_kv_heads=nkvh, block_t=bt,
                    block_o=bo, block_f=bf, autotune=False,
                    use_pallas=True)
                return jnp.sum(y.astype(jnp.float32) ** 2)

            def body(i, carry):
                g = jax.grad(loss)(x_ * (1 + carry * 1e-12).astype(dt))
                return carry + jnp.sum(jnp.abs(g).astype(jnp.float32))
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(x))                            # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(x))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("fused_decoder", key, cands, bench, default))


# -- weight-only quantized matmul --------------------------------------------

def _quant_candidates(t, k, n, wdtype, xdtype) -> list:
    """(block_t, block_n) candidates for the weight-only quant matmul:
    K is unblocked, so VMEM holds the x tile, the quantized [k, bn]
    weight tile (1 byte/elem for int8 AND fp8), its up-converted copy,
    the fp32 accumulator tile, and the [1, bn] scale row."""
    x_item = 2 if ("bfloat16" in xdtype or "float16" in xdtype) else 4
    out = []
    for bn in (128, 256, 512):
        if n % bn:
            continue
        for bt in (8, 16, 32, 64, 128, 256, 512):
            if t % bt or bt > t:
                continue
            vmem = (2 * bt * k * x_item          # double-buffered x io
                    + k * bn * (1 + x_item)      # quant block + upcast
                    + bt * bn * (4 + x_item)     # fp32 acc + out tile
                    + bn * 4)
            if vmem < 10 * (1 << 20):
                out.append((bt, bn))
    if not out:
        from paddle_tpu.ops.pallas.quant_matmul import \
            _default_quant_blocks
        out = [_default_quant_blocks(t, n, xdtype)]
    return out


def quant_key(t, k, n, wdtype, xdtype, backend=None, interpret=None):
    return (f"t{t}k{k}n{n}w{wdtype}x{xdtype}"
            f"@{backend or backend_tag(interpret)}")


def quant_block_sizes(t: int, k: int, n: int, wdtype: str,
                      xdtype: str) -> Tuple[int, int]:
    """Measured (block_t, block_n) for the weight-only quantized matmul
    at this [t, k] x [k, n] shape — forward only (serving decode never
    differentiates through it)."""
    from paddle_tpu.ops.pallas.quant_matmul import _default_quant_blocks
    default = _default_quant_blocks(t, n, xdtype)
    cands = _quant_candidates(t, k, n, wdtype, xdtype)
    cands, _ = _verify_prune("quant_matmul", (t, k, n, wdtype, xdtype),
                             cands)
    if len(cands) == 1:
        return tuple(cands[0])
    key = quant_key(t, k, n, wdtype, xdtype)

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.quant_matmul import quant_matmul_pallas

        bt, bn = blocks
        iters = 8
        rng = np.random.default_rng(0)
        xdt = jnp.dtype(xdtype)
        wdt = jnp.dtype(wdtype) if "int8" in wdtype else None
        w = rng.standard_normal((k, n)).astype(np.float32)
        scale = jnp.asarray(np.abs(w).max(axis=0) / 127.0, jnp.float32)
        if wdt is not None:
            qw = jnp.asarray(np.clip(np.round(w / np.asarray(scale)),
                                     -127, 127).astype(np.int8))
        else:
            import ml_dtypes
            qw = jnp.asarray((w / np.asarray(scale))
                             .astype(ml_dtypes.float8_e4m3fn))
        x = jnp.asarray(rng.standard_normal((t, k)), xdt)

        @jax.jit
        def run(x_, qw_, s_):
            def body(i, carry):
                o = quant_matmul_pallas(
                    x_ * (1 + carry * 1e-12).astype(xdt), qw_, s_,
                    block_t=bt, block_n=bn, autotune=False)
                return carry + jnp.sum(jnp.abs(o).astype(jnp.float32))
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(x, qw, scale))                 # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(x, qw, scale))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("quant_matmul", key, cands, bench, default))


# -- grouped expert-matmul (MoE) ---------------------------------------------

def _grouped_candidates(g, c, d, h, dtype) -> list:
    """(block_c, block_f) candidates for the grouped expert FFN: the
    f (hidden) axis is the sequential dim, so VMEM holds the x/y tiles,
    the fp32 accumulator, and double-buffered [d, bf]/[bf, d] weight
    tiles — the same working set as the fused MLP plus nothing (the
    counts operand is one int32 word per group)."""
    item = 2 if ("bfloat16" in dtype or "float16" in dtype) else 4
    quantum = 16 if item == 2 else 8
    out = []
    for bf in (128, 256, 512):
        if h % bf:
            continue
        for bc in (8, 16, 32, 64, 128, 256, 512):
            if bc % quantum or c % bc or bc > c:
                continue
            vmem = (2 * bc * d * item            # x, double-buffered
                    + bc * d * 4                 # fp32 accumulator
                    + 2 * bc * d * item          # y, double-buffered
                    + 4 * d * bf * item)         # w1 + w2 tiles, 2x
            if vmem < 10 * (1 << 20):
                out.append((bc, bf))
    if not out:
        from paddle_tpu.ops.pallas.grouped_matmul import \
            _default_grouped_blocks
        out = [_default_grouped_blocks(c, d, h, dtype)]
    return out


def grouped_key(g, c, d, h, dtype, backend=None, interpret=None):
    return (f"g{g}c{c}d{d}h{h}x{dtype}"
            f"@{backend or backend_tag(interpret)}")


def grouped_block_sizes(g: int, c: int, d: int, h: int,
                        dtype: str) -> Tuple[int, int]:
    """Measured (block_c, block_f) for the grouped expert FFN at this
    [g, c, d] x stacked [g, d, h] shape.  Benched with full counts
    (worst case: no empty-block skip) so the winner is robust to
    routing balance."""
    from paddle_tpu.ops.pallas.grouped_matmul import _default_grouped_blocks
    default = _default_grouped_blocks(c, d, h, dtype)
    cands = _grouped_candidates(g, c, d, h, dtype)
    cands, _ = _verify_prune("grouped_matmul", (g, c, d, h, dtype), cands)
    if len(cands) == 1:
        return tuple(cands[0])
    key = grouped_key(g, c, d, h, dtype)

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.grouped_matmul import \
            grouped_expert_ffn_pallas

        bc, bf = blocks
        iters = 8
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.standard_normal((g, c, d)), dt)
        w1 = jnp.asarray(rng.standard_normal((g, d, h)) * 0.02, dt)
        b1 = jnp.zeros((g, h), dt)
        w2 = jnp.asarray(rng.standard_normal((g, h, d)) * 0.02, dt)
        b2 = jnp.zeros((g, d), dt)
        counts = jnp.full((g,), c, jnp.int32)

        @jax.jit
        def run(x_, w1_, b1_, w2_, b2_, cnt_):
            def body(i, carry):
                o = grouped_expert_ffn_pallas(
                    x_ * (1 + carry * 1e-12).astype(dt), w1_, b1_, w2_,
                    b2_, cnt_, act=jax.nn.gelu, block_c=bc, block_f=bf,
                    interpret=False)
                return carry + jnp.sum(jnp.abs(o).astype(jnp.float32))
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(x, w1, b1, w2, b2, counts))    # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(x, w1, b1, w2, b2, counts))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("grouped_matmul", key, cands, bench, default))


# -- offline sweep -----------------------------------------------------------

# the bench llama (bench.py on-TPU config: 810M-param Llama-3 proportions,
# b4/s2048 bf16) plus the short-context variant from the r2 sweep notes
SWEEP_SHAPES = {
    "flash": [
        (4, 2048, 16, 8, 128, "bfloat16", True),
        (8, 1024, 16, 8, 128, "bfloat16", True),
    ],
    "fused_ce": [
        (8192, 32000, "bfloat16"),
    ],
    "fused_qkv": [
        (8192, 2048, 2048, 1024, 1024, "bfloat16"),
        (8192, 4096, 4096, 1024, 1024, "bfloat16"),
    ],
    "fused_mlp": [
        (8192, 2048, 7168, "bfloat16"),
        (8192, 4096, 14336, "bfloat16"),
    ],
    # whole-decoder-block megakernel: the VMEM budget (sequence-wide K/V
    # scratch) bounds it to short/medium contexts — sweep the shapes it
    # actually serves: a short-context training block and a
    # prefill/verify-sized row batch at bench-llama widths
    "fused_decoder": [
        (4, 512, 1024, 1024, 512, 128, 3584, "bfloat16"),
        (8, 128, 2048, 2048, 1024, 128, 7168, "bfloat16"),
    ],
    # weight-only quantized GEMM (serving): the bench_serve llama's
    # prefill-chunk and batched-decode token counts over its projection
    # shapes, int8 and fp8 weight storage
    "quant_matmul": [
        (256, 1024, 3584, "int8", "bfloat16"),
        (256, 1024, 1024, "int8", "bfloat16"),
        (256, 1024, 3584, "float8_e4m3fn", "bfloat16"),
        (16, 1024, 1024, "int8", "bfloat16"),
    ],
    # grouped expert-matmul (MoE): the bench_moe llama's E=8 experts at
    # bench widths — capacity from b4/s2048 top-2 routing at
    # capacity_factor 1.25 (C = 1.25*2*8192/8 = 2560), plus the
    # short-context variant
    "grouped_matmul": [
        (8, 2560, 1024, 3584, "bfloat16"),
        (8, 1280, 1024, 3584, "bfloat16"),
    ],
}


def _sweep_one(op, shape, dry_run, backend):
    """(key, winner, n_candidates, n_pruned) for one (op, shape) sweep
    entry — ``n_pruned`` counts candidates the static verifier rejected
    before any timing (``pruned_invalid`` in the sweep output)."""
    if op == "flash":
        b, s, h, hk, d, dtype, causal = shape
        cands = _flash_candidates(s, d, dtype)
        default = (min(128, s), min(128, s), True)
        key = flash_key(b, s, h, hk, d, dtype, causal, None,
                        backend=backend)
        _, npruned = _verify_prune(op, shape, cands)
        if not dry_run:
            return key, flash_block_sizes(b, s, h, hk, d, dtype, causal), \
                len(cands), npruned
    elif op == "fused_ce":
        t, v, dtype = shape
        from paddle_tpu.ops.pallas.cross_entropy import _default_blocks
        cands = _ce_candidates(t, v, dtype)
        default = _default_blocks(t, v)
        key = ce_key(t, v, dtype, backend=backend)
        _, npruned = _verify_prune(op, shape, cands)
        if not dry_run:
            return key, ce_block_sizes(t, v, dtype), len(cands), npruned
    elif op == "fused_qkv":
        t, d, dq, dk, dv, dtype = shape
        from paddle_tpu.ops.pallas.fused_block import _default_qkv_blocks
        cands = _qkv_candidates(t, d, dq, dk, dv, dtype)
        default = _default_qkv_blocks(t, d, dq, dk, dv, dtype)
        key = qkv_key(t, d, dq, dk, dv, dtype, backend=backend)
        _, npruned = _verify_prune(op, shape, cands)
        if not dry_run:
            return key, qkv_block_sizes(t, d, dq, dk, dv, dtype), \
                len(cands), npruned
    elif op == "fused_mlp":
        t, d, f, dtype = shape
        from paddle_tpu.ops.pallas.fused_block import _default_mlp_blocks
        cands = _mlp_candidates(t, d, f, dtype)
        default = _default_mlp_blocks(t, d, f, dtype)
        key = mlp_key(t, d, f, dtype, backend=backend)
        _, npruned = _verify_prune(op, shape, cands)
        if not dry_run:
            return key, mlp_block_sizes(t, d, f, dtype), len(cands), \
                npruned
    elif op == "fused_decoder":
        b, s, d, dq, dkv, hd, f, dtype = shape
        from paddle_tpu.ops.pallas.fused_block import \
            _default_decoder_blocks
        cands = _decoder_candidates(s, d, dq, dkv, hd, f, dtype)
        default = _default_decoder_blocks(s, d, dq, dkv, hd, f, dtype)
        key = decoder_key(b, s, d, dq, dkv, hd, f, dtype, backend=backend)
        _, npruned = _verify_prune(op, shape, cands)
        if not dry_run:
            return key, decoder_block_sizes(b, s, d, dq, dkv, hd, f,
                                            dtype), len(cands), npruned
    elif op == "quant_matmul":
        t, k, n, wdtype, xdtype = shape
        from paddle_tpu.ops.pallas.quant_matmul import \
            _default_quant_blocks
        cands = _quant_candidates(t, k, n, wdtype, xdtype)
        default = _default_quant_blocks(t, n, xdtype)
        key = quant_key(t, k, n, wdtype, xdtype, backend=backend)
        _, npruned = _verify_prune(op, shape, cands)
        if not dry_run:
            return key, quant_block_sizes(t, k, n, wdtype, xdtype), \
                len(cands), npruned
    elif op == "grouped_matmul":
        g, c, d, h, dtype = shape
        from paddle_tpu.ops.pallas.grouped_matmul import \
            _default_grouped_blocks
        cands = _grouped_candidates(g, c, d, h, dtype)
        default = _default_grouped_blocks(c, d, h, dtype)
        key = grouped_key(g, c, d, h, dtype, backend=backend)
        _, npruned = _verify_prune(op, shape, cands)
        if not dry_run:
            return key, grouped_block_sizes(g, c, d, h, dtype), \
                len(cands), npruned
    else:
        raise ValueError(f"unknown sweep op {op!r}")
    # dry run: the heuristic default stands in for the measured winner —
    # exercises key construction + persistence without touching a chip
    _put(op, key, tuple(default))
    return key, tuple(default), len(cands), npruned


def _sweep_candidates(op, shape):
    """The sweep's candidate list for one (op, shape) entry."""
    if op == "flash":
        b, s, h, hk, d, dtype, causal = shape
        return _flash_candidates(s, d, dtype)
    if op == "fused_ce":
        t, v, dtype = shape
        return _ce_candidates(t, v, dtype)
    if op == "fused_qkv":
        t, d, dq, dk, dv, dtype = shape
        return _qkv_candidates(t, d, dq, dk, dv, dtype)
    if op == "fused_mlp":
        t, d, f, dtype = shape
        return _mlp_candidates(t, d, f, dtype)
    if op == "fused_decoder":
        b, s, d, dq, dkv, hd, f, dtype = shape
        return _decoder_candidates(s, d, dq, dkv, hd, f, dtype)
    if op == "quant_matmul":
        t, k, n, wdtype, xdtype = shape
        return _quant_candidates(t, k, n, wdtype, xdtype)
    if op == "grouped_matmul":
        g, c, d, h, dtype = shape
        return _grouped_candidates(g, c, d, h, dtype)
    raise ValueError(f"unknown sweep op {op!r}")


def _verify_only_main(args) -> int:
    """--sweep --verify-only: dry-validate every candidate for every
    sweep shape — zero timings, zero cache writes.  On-chip sweep day
    starts from this report and skips the doomed configs."""
    from paddle_tpu.analysis.kernel_verify import candidate_ok
    ops = sorted(SWEEP_SHAPES) if not args.ops else \
        [o.strip() for o in args.ops.split(",") if o.strip()]
    all_dead = []
    total = pruned = 0
    for op in ops:
        for shape in SWEEP_SHAPES[op]:
            cands = _sweep_candidates(op, shape)
            bad = []
            for c in cands:
                try:
                    ok = candidate_ok(op, shape, c)
                except Exception:
                    ok = True   # match _verify_prune: never lose a config
                if not ok:
                    bad.append(tuple(c))
            total += len(cands)
            pruned += len(bad)
            status = "ALL-PRUNED" if bad and len(bad) == len(cands) \
                else "ok"
            print(f"verify {op} {shape}: {len(cands) - len(bad)}/"
                  f"{len(cands)} valid, pruned_invalid={len(bad)} "
                  f"{('-> ' + status) if status != 'ok' else ''}".rstrip())
            if bad:
                print(f"  pruned: {bad}")
            if bad and len(bad) == len(cands):
                all_dead.append((op, shape))
    print(f"verify-only: {total} candidates checked, {pruned} pruned, "
          f"0 timed")
    if all_dead:
        print(f"FAIL: candidate set(s) 100% pruned (wrongly-strict "
              f"verifier or unservable shape): {all_dead}",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.ops.pallas.autotune",
        description="Offline TVM-style block-size sweep for the Pallas "
                    "kernels (flash attention, fused CE, fused "
                    "rmsnorm+QKV, fused MLP).")
    ap.add_argument("--sweep", action="store_true",
                    help="enumerate + persist winners for the bench "
                         "shape grid")
    ap.add_argument("--dry-run", action="store_true",
                    help="skip timing: write heuristic winners "
                         "(persistence round-trip without a chip)")
    ap.add_argument("--verify-only", action="store_true",
                    help="statically validate every sweep candidate "
                         "(analysis/kernel_verify) with ZERO timings "
                         "and no cache write; exit 1 if any op/shape "
                         "has its whole candidate set pruned")
    ap.add_argument("--cache", default=None,
                    help="cache file to write (default: "
                         "PADDLE_TPU_AUTOTUNE_CACHE / ~/.cache)")
    ap.add_argument("--target", default=None,
                    help="backend tag for the written keys (e.g. "
                         "'tpu:TPU_v5_lite'); default: this process's "
                         "backend")
    ap.add_argument("--ops", default=None,
                    help="comma-separated subset of "
                         f"{sorted(SWEEP_SHAPES)}")
    args = ap.parse_args(argv)
    if not args.sweep:
        ap.error("nothing to do (pass --sweep)")
    if args.verify_only:
        return _verify_only_main(args)

    if args.cache:
        os.environ["PADDLE_TPU_AUTOTUNE_CACHE"] = args.cache
        reload()
    backend = args.target or backend_tag()
    ops = sorted(SWEEP_SHAPES) if not args.ops else \
        [o.strip() for o in args.ops.split(",") if o.strip()]

    n = 0
    for op in ops:
        for shape in SWEEP_SHAPES[op]:
            try:
                key, winner, ncand, npruned = _sweep_one(
                    op, shape, args.dry_run, backend)
            except Exception as e:     # a shape too big for this host
                print(f"sweep {op} {shape}: SKIP ({type(e).__name__}: "
                      f"{e})", file=sys.stderr)
                continue
            n += 1
            mode = "dry-run default" if args.dry_run else "measured"
            print(f"sweep {op} {shape} -> {winner}  "
                  f"[{ncand} candidates, pruned_invalid={npruned}, "
                  f"{mode}]")
    _save(args.cache)
    print(f"autotune cache: wrote {n} entries (schema v{CACHE_VERSION}) "
          f"to {args.cache or cache_path()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
