"""Kernel block-size autotuner with persistent caching.

Reference parity: ``phi/kernels/autotune/auto_tune_base.h`` +
``cache_base.h`` — the reference times kernel variants at first invocation
and caches the winner per shape key.  TPU-native version: candidates are
Pallas block-size configurations; each is compiled and timed ONCE on the
real chip at first use of a shape (this works even when the op is hit
inside a ``jit`` trace — the measurement runs concrete side inputs, not
tracers), and the winner persists to a JSON cache so later processes skip
the sweep entirely.

Env knobs:
  PADDLE_TPU_AUTOTUNE=0           disable (use the heuristic default)
  PADDLE_TPU_AUTOTUNE_CACHE=path  cache file (default
                                  ~/.cache/paddle_tpu_autotune.json)
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Sequence, Tuple

__all__ = ["autotune", "flash_block_sizes", "ce_block_sizes", "cache_path",
           "clear_cache"]

_mem_cache: Dict[str, object] = {}
_loaded = False


def cache_path() -> str:
    return os.environ.get(
        "PADDLE_TPU_AUTOTUNE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "paddle_tpu_autotune.json"))


def _load():
    global _loaded
    if _loaded:
        return
    _loaded = True
    try:
        with open(cache_path()) as f:
            _mem_cache.update(json.load(f))
    except Exception:
        pass


def _save():
    path = cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # merge-then-atomic-replace: concurrent processes benching
        # different shapes must not clobber each other or expose a
        # half-written file to readers
        merged = {}
        try:
            with open(path) as f:
                merged.update(json.load(f))
        except Exception:
            pass
        merged.update(_mem_cache)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=0, sort_keys=True)
        os.replace(tmp, path)
    except Exception:
        pass  # read-only fs: in-memory cache still works


def clear_cache():
    global _loaded
    _mem_cache.clear()
    _loaded = True
    try:
        os.remove(cache_path())
    except OSError:
        pass


def enabled() -> bool:
    if os.environ.get("PADDLE_TPU_AUTOTUNE", "1") == "0":
        return False
    # multi-controller runs must compile IDENTICAL programs on every
    # process; per-host timing sweeps could disagree (noise) and deadlock
    # the first collective — use the deterministic default there
    try:
        import jax
        if jax.process_count() > 1:
            return False
    except Exception:
        pass
    return True


def _device_tag() -> str:
    try:
        import jax
        dev = jax.devices()[0]
        return f"{dev.platform}:{getattr(dev, 'device_kind', '?')}" \
            .replace(" ", "_")
    except Exception:
        return "unknown"


def autotune(op_name: str, key: str, candidates: Sequence,
             bench: Callable[[object], float], default):
    """Return the cached winner for (op_name, key), measuring once.

    bench(candidate) -> seconds (lower is better); raise/inf to disqualify
    a candidate.  Falls back to ``default`` when disabled or when every
    candidate fails."""
    full_key = f"{op_name}|{key}"
    _load()
    if full_key in _mem_cache:
        got = _mem_cache[full_key]
        return tuple(got) if isinstance(got, list) else got
    if not enabled():
        return default

    best, best_t = None, float("inf")
    for c in candidates:
        try:
            t = bench(c)
        except Exception:
            continue
        if t < best_t:
            best, best_t = c, t
    if best is None:
        best = default
    _mem_cache[full_key] = list(best) if isinstance(best, tuple) else best
    _save()
    return best


def _flash_candidates(s: int, d: int, dtype: str,
                      pallas_bwd=None) -> list:
    """(block_q, block_k, pallas_bwd) candidates: block sizes bounded by
    the VMEM working set, crossed with the two backward implementations
    (Pallas dq/dkv kernels vs the blockwise-jax recompute) — the variant
    choice is part of the tuning space, reference auto_tune_base style.
    A caller-pinned ``pallas_bwd`` constrains that dimension (no point
    benching a variant the call site will never use)."""
    blocks = []
    sizes = (128, 256) if s < 4096 else (128, 256, 512)
    for bq in sizes:
        for bk in sizes:
            if bq > s or bk > s or s % bq or s % bk:
                continue
            itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
            vmem = (2 * (bq + 2 * bk) * d * itemsize   # double-buffered io
                    + bq * bk * 4                      # score tile
                    + 2 * bq * d * 4)                  # fp32 accumulators
            if vmem < 10 * (1 << 20):
                blocks.append((bq, bk))
    blocks = blocks or [(min(128, s), min(128, s))]
    pbs = (True, False) if pallas_bwd is None else (bool(pallas_bwd),)
    return [(bq, bk, pb) for bq, bk in blocks for pb in pbs]


def flash_block_sizes(b: int, s: int, h: int, hk: int, d: int,
                      dtype: str, causal: bool,
                      pallas_bwd=None) -> Tuple[int, int, bool]:
    """Measured (block_q, block_k, pallas_bwd) for this shape (the last
    entry echoes ``pallas_bwd`` when the caller pinned it)."""
    default = (min(128, s), min(128, s),
               True if pallas_bwd is None else bool(pallas_bwd))
    cands = _flash_candidates(s, d, dtype, pallas_bwd)
    if len(cands) == 1:
        return cands[0]
    pb_tag = "x" if pallas_bwd is None else str(int(bool(pallas_bwd)))
    key = (f"b{b}s{s}h{h}k{hk}d{d}{dtype}c{int(causal)}"
           f"pb{pb_tag}@{_device_tag()}")

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.flash_attention import flash_attention

        bq, bk, pb = blocks
        iters = 8
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), dt)
        k = jnp.asarray(rng.standard_normal((b, s, hk, d)), dt)
        v = jnp.asarray(rng.standard_normal((b, s, hk, d)), dt)

        @jax.jit
        def run(q_, k_, v_):
            # iterations loop INSIDE the jit: one dispatch, so the
            # tunneled chip's per-call RPC latency cannot bias the sweep
            def loss(args):
                o = flash_attention(*args, causal=causal, block_q=bq,
                                    block_k=bk, pallas_bwd=pb,
                                    autotune=False)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            def body(i, carry):
                g = jax.grad(loss)((q_ * (1 + carry * 1e-12).astype(dt),
                                    k_, v_))
                return carry + sum(
                    jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in g)
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(q, k, v))                      # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(q, k, v))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("flash", key, cands, bench, default))


def _ce_candidates(t: int, v: int, dtype: str) -> list:
    """(block_t, block_v) candidates for the fused cross-entropy: the
    vocab block must divide V; VMEM holds the io block (double-buffered)
    plus one fp32 working copy and the [bt, 1] statistics."""
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    out = []
    for bt in (64, 128, 256):
        if bt > max(t, 8):
            continue
        for bv in (256, 512, 1024, 2048):
            if v % bv:
                continue
            vmem = bt * bv * (2 * itemsize + 4) + 8 * bt * 4
            if vmem < 10 * (1 << 20):
                out.append((bt, bv))
    if not out:
        from paddle_tpu.ops.pallas.cross_entropy import _default_blocks
        out = [_default_blocks(t, v)]
    return out


def ce_block_sizes(t: int, v: int, dtype: str) -> Tuple[int, int]:
    """Measured (block_t, block_v) for the fused cross-entropy at this
    [tokens, vocab] shape (loss + grad timed together — the backward is
    where the one-hot traffic used to live)."""
    from paddle_tpu.ops.pallas.cross_entropy import _default_blocks
    default = _default_blocks(t, v)
    cands = _ce_candidates(t, v, dtype)
    if len(cands) == 1:
        return tuple(cands[0])
    key = f"t{t}v{v}{dtype}@{_device_tag()}"

    def bench(blocks):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from paddle_tpu.ops.pallas.cross_entropy import \
            fused_softmax_cross_entropy

        bt, bv = blocks
        iters = 8
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.standard_normal((t, v)), dt)
        lbl = jnp.asarray(rng.integers(0, v, (t,)), jnp.int32)

        @jax.jit
        def run(x_, lbl_):
            def loss(a):
                return jnp.sum(fused_softmax_cross_entropy(
                    a, lbl_, block_t=bt, block_v=bv, autotune=False))

            def body(i, carry):
                g = jax.grad(loss)(x_ * (1 + carry * 1e-12).astype(dt))
                return carry + jnp.sum(jnp.abs(g).astype(jnp.float32))
            return lax.fori_loop(0, iters, body, 0.0)

        np.asarray(run(x, lbl))                       # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(x, lbl))
        return (time.perf_counter() - t0) / iters

    return tuple(autotune("fused_ce", key, cands, bench, default))
