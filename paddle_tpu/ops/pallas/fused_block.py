"""Transformer-block megakernels — fused rmsnorm+QKV and fused MLP.

Reference parity: the block-level fusion ops the reference keeps in
``phi/kernels/fusion`` (``fused_attention_op.cu`` front half,
``fused_bias_act`` / ``fused_gate_attention``); MPK-style
mega-kernelization (PAPERS.md) applied to the two segments PR 6's
roofline-gap attribution ranks highest once flash attention and the
fused lm-head CE are in place:

* ``fused_rmsnorm_qkv`` — RMSNorm statistics and the normalized
  activations are computed once per token block in VMEM and consumed by
  the q/k/v projections without ever round-tripping HBM.  The unfused
  lowering writes the normalized ``[T, d]`` activations and reads them
  back three times; here they live in a VMEM scratch for the lifetime
  of the token block.  Grid: (token_blocks, out_blocks) with the out
  axis walking q's, then k's, then v's column blocks — each weight
  block-spec clamps its index so a block is DMA'd exactly once.

* ``fused_mlp`` — SwiGLU (``down(silu(gate(x)) * up(x))``) with the
  ``[T, f]`` hidden intermediate VMEM-resident: the f axis is the inner
  grid dimension; each step computes a ``[bt, bf]`` gate/up tile, the
  activation product, and accumulates its contribution to the down
  projection into a ``[bt, d]`` fp32 scratch.  Neither ``gate(x)``,
  ``up(x)`` nor their product ever exists in HBM.  ``fused_ffn`` is the
  non-gated variant (``act(x@w1 + b1) @ w2 + b2``) for the classic
  Transformer encoder/decoder feed-forward.

All three carry custom VJPs: the backward recomputes the cheap
forward intermediates from the saved inputs (rmsnorm scale, gate/up
activations) in plain jax — XLA fuses those chains well, and the HBM
win lives in the forward, which inference/serving runs alone.

Numerics: norm statistics, activation math and all matmul
accumulation in fp32 (``preferred_element_type``) regardless of the
io dtype, mirroring the rest of the Pallas layer.

Env knobs:
  PADDLE_TPU_FUSED_BLOCK=1|0  force-enable (interpret off-TPU) /
                              disable; unset = auto (TPU backend only)
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["fused_rmsnorm_qkv", "fused_mlp", "fused_ffn",
           "fused_block_enabled", "fused_qkv_eligible",
           "fused_mlp_eligible", "record_path", "SUPPORTED_ACTS"]

_ACT = {
    "silu": jax.nn.silu,
    # exact erf form — matches F.gelu (jax.nn.gelu defaults to tanh)
    "gelu": functools.partial(jax.nn.gelu, approximate=False),
    "relu": jax.nn.relu,
}
SUPPORTED_ACTS = tuple(_ACT)


def fused_block_enabled() -> bool:
    """Routing gate: env wins, else auto = TPU backend only (interpret
    mode off-TPU is for tests, not the hot path)."""
    env = os.environ.get("PADDLE_TPU_FUSED_BLOCK", "").strip().lower()
    if env in ("0", "false", "off", "no"):
        return False
    if env in ("1", "true", "on", "yes"):
        return True
    return jax.default_backend() == "tpu"


def _row_quantum(dtype) -> int:
    """Min sublane tile: 8 rows for 4-byte dtypes, 16 for 16-bit."""
    s = str(dtype)
    return 16 if ("bfloat16" in s or "float16" in s) else 8


def fused_qkv_eligible(t: int, d: int, dq: int, dk: int, dv: int,
                       dtype="float32") -> bool:
    """Shape gate: feature dims must tile the 128-lane VPU/MXU; the
    token axis must tile the dtype's sublane minimum (serving decode
    with t = batch falls back to the reference path)."""
    q = _row_quantum(dtype)
    return (t >= q and t % q == 0 and d % 128 == 0 and
            dq % 128 == 0 and dk % 128 == 0 and dv % 128 == 0)


def fused_mlp_eligible(t: int, d: int, f: int, dtype="float32") -> bool:
    q = _row_quantum(dtype)
    return t >= q and t % q == 0 and d % 128 == 0 and f % 128 == 0


def _path_counter():
    from paddle_tpu.observability import default_registry
    return default_registry().counter(
        "paddle_tpu_fused_block_path_total",
        "fused-block kernel routing chosen at trace time",
        labelnames=("kernel", "path"))


def record_path(kernel: str, fused: bool):
    """Trace-time telemetry: which implementation this compile will run
    (same idiom as the flash-attention backward path counter)."""
    _path_counter().labels(
        kernel=kernel, path="fused" if fused else "reference").inc()


# ---------------------------------------------------------------------------
# fused rmsnorm + QKV projection
# ---------------------------------------------------------------------------

def _qkv_kernel(x_ref, wn_ref, wq_ref, wk_ref, wv_ref, *out_refs, eps, nq,
                nk, residuals):
    """Grid: (token_blocks, out_blocks); the out axis is innermost
    (sequential) so the normalized activations computed at j == 0 stay
    in VMEM scratch for every projection block of the token block.
    With ``residuals`` the normalized activations and the inverse rms
    are also emitted (once, at j == 0) for the custom VJP — the
    forward-only (inference) variant keeps the pure
    one-read/three-write form."""
    if residuals:
        q_ref, k_ref, v_ref, xn_out_ref, inv_ref, xn_ref = out_refs
    else:
        q_ref, k_ref, v_ref, xn_ref = out_refs
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _norm():
        xf = x_ref[:].astype(jnp.float32)
        ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(ms + eps)
        xn_ref[:] = (xf * inv) * wn_ref[:].astype(jnp.float32)
        if residuals:
            xn_out_ref[:] = xn_ref[:].astype(xn_out_ref.dtype)
            inv_ref[:] = inv

    def _proj(w_ref, o_ref):
        o_ref[:] = jax.lax.dot_general(
            xn_ref[:].astype(w_ref.dtype), w_ref[:],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(o_ref.dtype)

    @pl.when(j < nq)
    def _q():
        _proj(wq_ref, q_ref)

    @pl.when(jnp.logical_and(j >= nq, j < nq + nk))
    def _k():
        _proj(wk_ref, k_ref)

    @pl.when(j >= nq + nk)
    def _v():
        _proj(wv_ref, v_ref)


def _qkv_pallas(x2d, wn, wq, wk, wv, *, eps, block_t, block_o, interpret,
                residuals):
    t, d = x2d.shape
    dq, dk, dv = wq.shape[1], wk.shape[1], wv.shape[1]
    nt = t // block_t
    nq, nkb, nvb = dq // block_o, dk // block_o, dv // block_o

    # each weight/output spec clamps the out-axis index into its own
    # range: while j walks another projection's blocks the index map
    # returns the previous value, so Mosaic re-uses the resident block
    # instead of issuing a DMA — every block is fetched/flushed once
    def _clamped(lo, n):
        return lambda i, j: (0, jnp.clip(j - lo, 0, n - 1))

    def _clamped_out(lo, n):
        return lambda i, j: (i, jnp.clip(j - lo, 0, n - 1))

    out_specs = [
        pl.BlockSpec((block_t, block_o), _clamped_out(0, nq)),
        pl.BlockSpec((block_t, block_o), _clamped_out(nq, nkb)),
        pl.BlockSpec((block_t, block_o), _clamped_out(nq + nkb, nvb)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((t, dq), x2d.dtype),
        jax.ShapeDtypeStruct((t, dk), x2d.dtype),
        jax.ShapeDtypeStruct((t, dv), x2d.dtype),
    ]
    if residuals:
        out_specs += [pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
                      pl.BlockSpec((block_t, 1), lambda i, j: (i, 0))]
        out_shape += [jax.ShapeDtypeStruct((t, d), x2d.dtype),
                      jax.ShapeDtypeStruct((t, 1), jnp.float32)]

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    return pl.pallas_call(
        functools.partial(_qkv_kernel, eps=eps, nq=nq, nk=nkb,
                          residuals=residuals),
        grid=(nt, nq + nkb + nvb),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
            pl.BlockSpec((1, d), lambda i, j: (0, 0)),
            pl.BlockSpec((d, block_o), _clamped(0, nq)),
            pl.BlockSpec((d, block_o), _clamped(nq, nkb)),
            pl.BlockSpec((d, block_o), _clamped(nq + nkb, nvb)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((block_t, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(x2d, wn.reshape(1, d), wq, wk, wv)


def _qkv_reference(x2d, wn, wq, wk, wv, eps, residuals=False):
    xf = x2d.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    xn = ((xf * inv) * wn.astype(jnp.float32)).astype(x2d.dtype)

    def proj(w):
        return jax.lax.dot_general(
            xn, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x2d.dtype)

    out = (proj(wq), proj(wk), proj(wv))
    return out + (xn, inv) if residuals else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _qkv_core(x2d, wn, wq, wk, wv, eps, use_pallas, interpret,
              block_t, block_o):
    # primal (forward-only) path: no residual outputs — inference keeps
    # the pure one-read/three-write kernel
    if use_pallas:
        return tuple(_qkv_pallas(x2d, wn, wq, wk, wv, eps=eps,
                                 block_t=block_t, block_o=block_o,
                                 interpret=interpret, residuals=False))
    return _qkv_reference(x2d, wn, wq, wk, wv, eps)


def _qkv_fwd(x2d, wn, wq, wk, wv, eps, use_pallas, interpret,
             block_t, block_o):
    # differentiated path: the kernel additionally emits the normalized
    # activations and the inverse rms (flash-attention saved-lse style),
    # so the backward never recomputes the norm chain
    if use_pallas:
        q, k, v, xn, inv = _qkv_pallas(
            x2d, wn, wq, wk, wv, eps=eps, block_t=block_t,
            block_o=block_o, interpret=interpret, residuals=True)
    else:
        q, k, v, xn, inv = _qkv_reference(x2d, wn, wq, wk, wv, eps,
                                          residuals=True)
    return (q, k, v), (x2d, wn, wq, wk, wv, xn, inv)


def _qkv_bwd(eps, use_pallas, interpret, block_t, block_o, res, cts):
    # mixed-precision discipline matches what autodiff of the unfused
    # chain produces: matmuls accumulate fp32 on the MXU but cotangents
    # materialize in the io dtype (bf16 in training) — only the fused
    # rmsnorm-backward elementwise chain runs fp32, and XLA fuses it
    x2d, wn, wq, wk, wv, xn, inv = res
    dq, dk, dv = cts
    dt = x2d.dtype
    wnf = wn.astype(jnp.float32)

    def back(g, w):                                     # g @ w.T
        return jax.lax.dot_general(g, w, (((1,), (1,)), ((), ())))

    def wgrad(g):                                       # xn.T @ g, fp32
        return jax.lax.dot_general(
            xn, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dxn = (back(dq, wq) + back(dk, wk) + back(dv, wv)) \
        .astype(jnp.float32)                            # [T, d]
    dwq = wgrad(dq).astype(wq.dtype)
    dwk = wgrad(dk).astype(wk.dtype)
    dwv = wgrad(dv).astype(wv.dtype)
    xf = x2d.astype(jnp.float32)
    xhat = xf * inv                                     # saved inv: no
    dwn = jnp.sum(dxn * xhat, axis=0).astype(wn.dtype)  # stat recompute
    # rmsnorm backward (same equations as ops/pallas/rmsnorm.py):
    # dx = inv * g - x * inv^3 * mean(g * x), with g = dxn * w
    gx = dxn * wnf
    dot = jnp.mean(gx * xf, axis=-1, keepdims=True)
    dx = (inv * gx - xf * (inv ** 3) * dot).astype(dt)
    return dx, dwn, dwq, dwk, dwv


_qkv_core.defvjp(_qkv_fwd, _qkv_bwd)


def _default_qkv_blocks(t, d, dq, dk, dv, dtype):
    """Heuristic fallback: the first (token, out) block pair — widest
    out block first, then tallest token block — whose working set (x +
    fp32 normalized scratch + weight/out blocks, double-buffered io)
    stays under ~10 MB of VMEM."""
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    # 16-bit dtypes tile (16, 128): never offer an 8-row block there
    bts = (512, 256, 128, 64, 32, 16) if itemsize == 2 else \
        (512, 256, 128, 64, 32, 16, 8)
    for bo in (512, 256, 128):
        if dq % bo or dk % bo or dv % bo:
            continue
        for bt in bts:
            if t % bt:
                continue
            vmem = (2 * bt * d * itemsize        # x, double-buffered
                    + bt * d * 4                 # fp32 xn scratch
                    + 6 * d * bo * itemsize      # 3 weight blocks, 2x
                    + 6 * bt * bo * itemsize)    # 3 out blocks, 2x
            if vmem < 10 * (1 << 20):
                return bt, bo
    return bts[-1], 128


def fused_rmsnorm_qkv(x, norm_weight, wq, wk, wv, epsilon: float = 1e-5,
                      block_t: int = None, block_o: int = None,
                      interpret: bool = None, autotune: bool = None,
                      use_pallas: bool = None):
    """``q, k, v = (rmsnorm(x) * norm_weight) @ (wq | wk | wv)`` in one
    fused pass — the normalized activations never round-trip HBM.

    x: [..., d]; norm_weight: [d]; wq/wk/wv: [d, dq/dk/dv] (paddle
    [in, out] layout).  Returns projections with x's leading dims.
    Differentiable wrt every array input.  Ineligible shapes fall back
    to reference math inside the same custom VJP (the API is total)."""
    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)
    t = x2d.shape[0]
    dq, dk, dv = int(wq.shape[-1]), int(wk.shape[-1]), int(wv.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = fused_qkv_eligible(t, d, dq, dk, dv, x.dtype)
    if autotune is None:
        autotune = not interpret
    if use_pallas and (block_t is None or block_o is None):
        if autotune and not interpret:
            from paddle_tpu.ops.pallas.autotune import qkv_block_sizes
            bt, bo = qkv_block_sizes(t, d, dq, dk, dv, str(x.dtype))
        else:
            bt, bo = _default_qkv_blocks(t, d, dq, dk, dv, str(x.dtype))
        block_t = block_t or bt
        block_o = block_o or bo
    if use_pallas and (t % block_t or dq % block_o or dk % block_o
                       or dv % block_o):
        raise ValueError(
            f"shapes t={t} dq={dq} dk={dk} dv={dv} not divisible by "
            f"blocks ({block_t}, {block_o})")
    q, k, v = _qkv_core(x2d, norm_weight, wq, wk, wv, float(epsilon),
                        bool(use_pallas), bool(interpret),
                        int(block_t or 0), int(block_o or 0))
    lead = shape[:-1]
    return (q.reshape(*lead, dq), k.reshape(*lead, dk),
            v.reshape(*lead, dv))


# ---------------------------------------------------------------------------
# fused MLP (gated SwiGLU and plain act+bias feed-forward)
# ---------------------------------------------------------------------------

def _mlp_kernel(*refs, act, gated, has_bias):
    """Grid: (token_blocks, hidden_blocks); the hidden (f) axis is the
    innermost (sequential) dim — each step materializes only a
    [bt, bf] tile of the hidden activations in VMEM and folds it into
    the fp32 down-projection accumulator."""
    if gated:
        x_ref, wg_ref, wu_ref, wd_ref, y_ref, acc_ref = refs
        bu_ref = bd_ref = None
    else:
        x_ref, wu_ref, wd_ref, bu_ref, bd_ref, y_ref, acc_ref = refs
        wg_ref = None
    j = pl.program_id(1)
    nf = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    xb = x_ref[:]
    u = jax.lax.dot_general(
        xb, wu_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bt, bf]
    if has_bias:
        u = u + bu_ref[:].astype(jnp.float32)
    if gated:
        g = jax.lax.dot_general(
            xb, wg_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        h = _ACT[act](g) * u
    else:
        h = _ACT[act](u)
    acc_ref[:] += jax.lax.dot_general(
        h.astype(wd_ref.dtype), wd_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bt, d]

    @pl.when(j == nf - 1)
    def _finalize():
        out = acc_ref[:]
        if has_bias:
            out = out + bd_ref[:].astype(jnp.float32)
        y_ref[:] = out.astype(y_ref.dtype)


def _mlp_pallas(x2d, weights, biases, *, act, gated, block_t, block_f,
                interpret):
    t, d = x2d.shape
    f = weights[-2].shape[1] if gated else weights[0].shape[1]
    nt = t // block_t
    nf = f // block_f

    in_specs = [pl.BlockSpec((block_t, d), lambda i, j: (i, 0))]
    args = [x2d]
    for w in weights[:-1]:                               # gate/up: [d, f]
        in_specs.append(pl.BlockSpec((d, block_f), lambda i, j: (0, j)))
        args.append(w)
    in_specs.append(pl.BlockSpec((block_f, d), lambda i, j: (j, 0)))
    args.append(weights[-1])                             # down: [f, d]
    if biases is not None:
        b1, b2 = biases
        in_specs.append(pl.BlockSpec((1, block_f), lambda i, j: (0, j)))
        args.append(b1.reshape(1, f))
        in_specs.append(pl.BlockSpec((1, d), lambda i, j: (0, 0)))
        args.append(b2.reshape(1, d))

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    return pl.pallas_call(
        functools.partial(_mlp_kernel, act=act, gated=gated,
                          has_bias=biases is not None),
        grid=(nt, nf),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t, d), x2d.dtype),
        scratch_shapes=[pltpu.VMEM((block_t, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(*args)


def _dot(a, b, contract):
    return jax.lax.dot_general(a, b, (contract, ((), ())),
                               preferred_element_type=jnp.float32)


def _mlp_gated_reference(x2d, wg, wu, wd, act):
    g = _dot(x2d, wg, ((1,), (0,)))
    u = _dot(x2d, wu, ((1,), (0,)))
    h = (_ACT[act](g) * u).astype(x2d.dtype)
    return _dot(h, wd, ((1,), (0,))).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _mlp_gated_core(x2d, wg, wu, wd, act, use_pallas, interpret,
                    block_t, block_f):
    return _mlp_gated_fwd(x2d, wg, wu, wd, act, use_pallas, interpret,
                          block_t, block_f)[0]


def _mlp_gated_fwd(x2d, wg, wu, wd, act, use_pallas, interpret,
                   block_t, block_f):
    if use_pallas:
        y = _mlp_pallas(x2d, (wg, wu, wd), None, act=act, gated=True,
                        block_t=block_t, block_f=block_f,
                        interpret=interpret)
    else:
        y = _mlp_gated_reference(x2d, wg, wu, wd, act)
    return y, (x2d, wg, wu, wd)


def _mlp_gated_bwd(act, use_pallas, interpret, block_t, block_f, res, dy):
    # recompute in the io dtype (matmuls still accumulate fp32 on the
    # MXU) — the materialized [T, f] intermediates cost the same HBM
    # bytes autodiff of the unfused bf16 chain would spend
    x2d, wg, wu, wd = res
    dt = x2d.dtype

    def dot_t(a, b, contract):      # io-dtype out, fp32 MXU accumulate
        return jax.lax.dot_general(a, b, (contract, ((), ())))

    g = dot_t(x2d, wg, ((1,), (0,)))                    # recompute
    u = dot_t(x2d, wu, ((1,), (0,)))
    s, act_vjp = jax.vjp(_ACT[act], g)
    h = s * u
    dh = dot_t(dy, wd, ((1,), (1,)))                    # [T, f]
    dwd = _dot(h, dy, ((0,), (0,))).astype(wd.dtype)
    du = dh * s
    dg = act_vjp(dh * u)[0].astype(dt)
    dx = dot_t(dg, wg, ((1,), (1,))) + dot_t(du, wu, ((1,), (1,)))
    dwg = _dot(x2d, dg, ((0,), (0,))).astype(wg.dtype)
    dwu = _dot(x2d, du, ((0,), (0,))).astype(wu.dtype)
    return dx.astype(dt), dwg, dwu, dwd


_mlp_gated_core.defvjp(_mlp_gated_fwd, _mlp_gated_bwd)


def _ffn_reference(x2d, w1, b1, w2, b2, act):
    u = _dot(x2d, w1, ((1,), (0,))) + b1.astype(jnp.float32)
    h = _ACT[act](u).astype(x2d.dtype)
    y = _dot(h, w2, ((1,), (0,))) + b2.astype(jnp.float32)
    return y.astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _ffn_core(x2d, w1, b1, w2, b2, act, use_pallas, interpret,
              block_t, block_f):
    return _ffn_fwd(x2d, w1, b1, w2, b2, act, use_pallas, interpret,
                    block_t, block_f)[0]


def _ffn_fwd(x2d, w1, b1, w2, b2, act, use_pallas, interpret,
             block_t, block_f):
    if use_pallas:
        y = _mlp_pallas(x2d, (w1, w2), (b1, b2), act=act, gated=False,
                        block_t=block_t, block_f=block_f,
                        interpret=interpret)
    else:
        y = _ffn_reference(x2d, w1, b1, w2, b2, act)
    return y, (x2d, w1, b1, w2, b2)


def _ffn_bwd(act, use_pallas, interpret, block_t, block_f, res, dy):
    x2d, w1, b1, w2, b2 = res
    dt = x2d.dtype
    u = (_dot(x2d, w1, ((1,), (0,))) + b1.astype(jnp.float32)).astype(dt)
    h, act_vjp = jax.vjp(_ACT[act], u)
    dh = jax.lax.dot_general(dy, w2,
                             ((((1,), (1,))), ((), ()))).astype(dt)
    dw2 = _dot(h, dy, ((0,), (0,))).astype(w2.dtype)
    db2 = jnp.sum(dy.astype(jnp.float32), axis=0).astype(b2.dtype)
    du = act_vjp(dh)[0].astype(dt)
    dx = _dot(du, w1, ((1,), (1,))).astype(dt)
    dw1 = _dot(x2d, du, ((0,), (0,))).astype(w1.dtype)
    db1 = jnp.sum(du.astype(jnp.float32), axis=0).astype(b1.dtype)
    return dx, dw1, db1, dw2, db2


_ffn_core.defvjp(_ffn_fwd, _ffn_bwd)


def _default_mlp_blocks(t, d, f, dtype):
    """Heuristic fallback: the first (token, hidden) block pair — widest
    hidden block first, then tallest token block — whose working set (x
    + y + fp32 accumulator + gate/up/down weight blocks, double-buffered
    io) stays under ~10 MB of VMEM."""
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    # 16-bit dtypes tile (16, 128): never offer an 8-row block there
    bts = (512, 256, 128, 64, 32, 16) if itemsize == 2 else \
        (512, 256, 128, 64, 32, 16, 8)
    for bf in (512, 256, 128):
        if f % bf:
            continue
        for bt in bts:
            if t % bt:
                continue
            vmem = (2 * bt * d * itemsize        # x, double-buffered
                    + bt * d * 4                 # fp32 accumulator
                    + 2 * bt * d * itemsize      # y, double-buffered
                    + 6 * d * bf * itemsize)     # 3 weight blocks, 2x
            if vmem < 10 * (1 << 20):
                return bt, bf
    return bts[-1], 128


def _mlp_blocks(t, d, f, dtype, block_t, block_f, interpret, autotune):
    if block_t is None or block_f is None:
        if autotune and not interpret:
            from paddle_tpu.ops.pallas.autotune import mlp_block_sizes
            bt, bf = mlp_block_sizes(t, d, f, dtype)
        else:
            bt, bf = _default_mlp_blocks(t, d, f, dtype)
        block_t = block_t or bt
        block_f = block_f or bf
    if t % block_t or f % block_f:
        raise ValueError(f"shapes t={t} f={f} not divisible by blocks "
                         f"({block_t}, {block_f})")
    return int(block_t), int(block_f)


def fused_mlp(x, w_gate, w_up, w_down, activation: str = "silu",
              block_t: int = None, block_f: int = None,
              interpret: bool = None, autotune: bool = None,
              use_pallas: bool = None):
    """``y = (act(x @ w_gate) * (x @ w_up)) @ w_down`` with the [T, f]
    hidden intermediate VMEM-resident (SwiGLU when ``activation='silu'``).

    x: [..., d]; w_gate/w_up: [d, f]; w_down: [f, d].  Differentiable
    wrt every array input; ineligible shapes take reference math inside
    the same custom VJP."""
    if activation not in _ACT:
        raise ValueError(f"unsupported activation {activation!r}; "
                         f"expected one of {SUPPORTED_ACTS}")
    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)
    t = x2d.shape[0]
    f = int(w_up.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = fused_mlp_eligible(t, d, f, x.dtype)
    if autotune is None:
        autotune = not interpret
    if use_pallas:
        block_t, block_f = _mlp_blocks(t, d, f, str(x.dtype), block_t,
                                       block_f, interpret, autotune)
    y = _mlp_gated_core(x2d, w_gate, w_up, w_down, str(activation),
                        bool(use_pallas), bool(interpret),
                        int(block_t or 0), int(block_f or 0))
    return y.reshape(shape)


def fused_ffn(x, w1, w2, b1=None, b2=None, activation: str = "relu",
              block_t: int = None, block_f: int = None,
              interpret: bool = None, autotune: bool = None,
              use_pallas: bool = None):
    """``y = act(x @ w1 + b1) @ w2 + b2`` — the classic Transformer
    feed-forward, hidden intermediate VMEM-resident (non-gated variant
    of :func:`fused_mlp`).  ``b1``/``b2`` may be None."""
    if activation not in _ACT:
        raise ValueError(f"unsupported activation {activation!r}; "
                         f"expected one of {SUPPORTED_ACTS}")
    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)
    t = x2d.shape[0]
    f = int(w1.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = fused_mlp_eligible(t, d, f, x.dtype)
    if autotune is None:
        autotune = not interpret
    if use_pallas:
        block_t, block_f = _mlp_blocks(t, d, f, str(x.dtype), block_t,
                                       block_f, interpret, autotune)
    if b1 is None:
        b1 = jnp.zeros((f,), x2d.dtype)
    if b2 is None:
        b2 = jnp.zeros((int(w2.shape[-1]),), x2d.dtype)
    y = _ffn_core(x2d, w1, b1, w2, b2, str(activation),
                  bool(use_pallas), bool(interpret),
                  int(block_t or 0), int(block_f or 0))
    return y.reshape(shape)
