"""Transformer-block megakernels — fused rmsnorm+QKV and fused MLP.

Reference parity: the block-level fusion ops the reference keeps in
``phi/kernels/fusion`` (``fused_attention_op.cu`` front half,
``fused_bias_act`` / ``fused_gate_attention``); MPK-style
mega-kernelization (PAPERS.md) applied to the two segments PR 6's
roofline-gap attribution ranks highest once flash attention and the
fused lm-head CE are in place:

* ``fused_rmsnorm_qkv`` — RMSNorm statistics and the normalized
  activations are computed once per token block in VMEM and consumed by
  the q/k/v projections without ever round-tripping HBM.  The unfused
  lowering writes the normalized ``[T, d]`` activations and reads them
  back three times; here they live in a VMEM scratch for the lifetime
  of the token block.  Grid: (token_blocks, out_blocks) with the out
  axis walking q's, then k's, then v's column blocks — each weight
  block-spec clamps its index so a block is DMA'd exactly once.

* ``fused_mlp`` — SwiGLU (``down(silu(gate(x)) * up(x))``) with the
  ``[T, f]`` hidden intermediate VMEM-resident: the f axis is the inner
  grid dimension; each step computes a ``[bt, bf]`` gate/up tile, the
  activation product, and accumulates its contribution to the down
  projection into a ``[bt, d]`` fp32 scratch.  Neither ``gate(x)``,
  ``up(x)`` nor their product ever exists in HBM.  ``fused_ffn`` is the
  non-gated variant (``act(x@w1 + b1) @ w2 + b2``) for the classic
  Transformer encoder/decoder feed-forward.

All three carry custom VJPs: the backward recomputes the cheap
forward intermediates from the saved inputs (rmsnorm scale, gate/up
activations) in plain jax — XLA fuses those chains well, and the HBM
win lives in the forward, which inference/serving runs alone.

Numerics: norm statistics, activation math and all matmul
accumulation in fp32 (``preferred_element_type``) regardless of the
io dtype, mirroring the rest of the Pallas layer.

* ``fused_decoder_block`` — the whole-decoder-block megakernel (ISSUE
  15, MPK-style): ONE ``pallas_call`` runs rmsnorm → QKV projections →
  RoPE → causal flash attention (online softmax over VMEM-resident K/V)
  → output projection → residual add → post-attention rmsnorm → SwiGLU
  MLP → residual add.  The block-boundary activations (normalized x,
  q/k/v, attention output, pre-MLP hidden state) never round-trip HBM:
  a decoder block reads its input activations once and writes its
  output once.  The grid is (batch, token_blocks, inner) where the
  inner axis walks phases — projection column blocks, (head, k-block)
  attention folds, output-projection columns, MLP hidden blocks — and
  per-token-block state lives in VMEM scratch across the inner walk,
  with K/V rows for the WHOLE sequence carried in scratch across token
  blocks (causal attention only ever looks back).  That K/V residency
  is the VMEM budget: eligibility requires ``2·s·dkv`` io-dtype bytes
  plus the walked weight blocks to fit (~12 MB), so the kernel serves
  short/medium contexts and decode-sized rows; longer shapes fall back
  to the per-segment kernels above.  The custom VJP recomputes the
  block from its saved INPUTS (reference math + the flash blockwise
  backward) — block-boundary remat: training saves only x per layer
  instead of every intermediate.

Env knobs:
  PADDLE_TPU_FUSED_BLOCK=1|0      force-enable the per-segment kernels
                                  (interpret off-TPU) / disable;
                                  unset = auto (TPU backend only)
  PADDLE_TPU_FUSED_BLOCK=decoder  additionally route eligible llama
                                  decoder layers through the
                                  whole-block megakernel (per-segment
                                  kernels keep ineligible layers)
  PADDLE_TPU_FUSED_BLOCK=measured per-shape decision from the
                                  measurement ledger: an eligible
                                  decoder layer routes through the
                                  megakernel only when the ledger
                                  measured it faster than the
                                  per-segment path for that shape on
                                  this backend (no coverage -> the
                                  per-segment tier, i.e. auto)
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["fused_rmsnorm_qkv", "fused_mlp", "fused_ffn",
           "fused_decoder_block", "fused_block_enabled",
           "fused_block_tier", "fused_decoder_enabled",
           "measured_tier_for",
           "fused_qkv_eligible", "fused_mlp_eligible",
           "fused_decoder_eligible", "decoder_vmem_bytes", "record_path",
           "SUPPORTED_ACTS"]

_ACT = {
    "silu": jax.nn.silu,
    # exact erf form — matches F.gelu (jax.nn.gelu defaults to tanh)
    "gelu": functools.partial(jax.nn.gelu, approximate=False),
    "relu": jax.nn.relu,
}
SUPPORTED_ACTS = tuple(_ACT)


def fused_block_tier() -> str:
    """The PADDLE_TPU_FUSED_BLOCK knob as a tier: ``"off"`` (reference
    lowering everywhere), ``"fused"`` (the PR-8 per-segment kernels —
    rmsnorm+QKV and MLP), ``"decoder"`` (additionally route eligible
    llama decoder layers through the whole-block megakernel).  Unset =
    auto: ``"fused"`` on a TPU backend, ``"off"`` elsewhere — the
    decoder tier is opt-in only, so existing knob values reproduce
    their previous jaxprs exactly.  ``"measured"`` resolves the
    decoder-vs-per-segment choice per shape from the measurement
    ledger (:func:`measured_tier_for`) instead of globally."""
    env = os.environ.get("PADDLE_TPU_FUSED_BLOCK", "").strip().lower()
    if env in ("0", "false", "off", "no"):
        return "off"
    if env == "decoder":
        return "decoder"
    if env == "measured":
        return "measured"
    if env in ("1", "true", "on", "yes"):
        return "fused"
    return "fused" if jax.default_backend() == "tpu" else "off"


def fused_block_enabled() -> bool:
    """Routing gate: env wins, else auto = TPU backend only (interpret
    mode off-TPU is for tests, not the hot path)."""
    return fused_block_tier() != "off"


def fused_decoder_enabled() -> bool:
    """True only at the explicit ``PADDLE_TPU_FUSED_BLOCK=decoder``
    tier — never auto-on, so every pre-existing knob value keeps its
    exact previous lowering.  (The ``measured`` tier routes the
    megakernel per shape through :func:`measured_tier_for`, not through
    this global gate.)"""
    return fused_block_tier() == "decoder"


def measured_tier_for(shape, dtype) -> str:
    """The ``PADDLE_TPU_FUSED_BLOCK=measured`` decision for one decoder
    activation shape ``(b, s, d)``: which tier the measurement ledger
    recorded as fastest on THIS backend.

    The DeviceProfiler feeder tags every ``decoder_block`` /
    ``decoder_block_fused`` segment row with the fusion tier active
    when it was measured, so the three lowerings are distinct ledger
    populations: a sweep day that profiles under ``off``, ``1`` and
    ``decoder`` gives this function all three measurements to compare.
    Returns ``"decoder"``, ``"fused"`` or ``"off"`` — the fastest tier
    with coverage; without any coverage the answer is ``"fused"`` (the
    auto default), so an empty ledger makes ``measured`` behave exactly
    like the per-segment tier.

    Only the decoder-layer boundary consults this (megakernel vs
    per-segment routing, the decision with measured 10x+ spread); the
    per-segment kernels themselves stay enabled under ``measured`` as
    under auto."""
    dtype = str(dtype)
    times = {}
    try:
        from paddle_tpu.observability import calibration
        model = calibration.CalibratedCostModel()
        t = model.measured_for("decoder_block_fused", shape, dtype,
                               layout="tier=decoder")
        if t is not None:
            times["decoder"] = t
        for tier, op in (("fused", "decoder_block"),
                         ("off", "decoder_block")):
            t = model.measured_for(op, shape, dtype,
                                   layout=f"tier={tier}")
            if t is not None:
                times[tier] = t
    except Exception:
        return "fused"
    if not times:
        return "fused"
    return min(times, key=times.get)


def _row_quantum(dtype) -> int:
    """Min sublane tile: 8 rows for 4-byte dtypes, 16 for 16-bit."""
    s = str(dtype)
    return 16 if ("bfloat16" in s or "float16" in s) else 8


def fused_qkv_eligible(t: int, d: int, dq: int, dk: int, dv: int,
                       dtype="float32") -> bool:
    """Shape gate: feature dims must tile the 128-lane VPU/MXU; the
    token axis must tile the dtype's sublane minimum (serving decode
    with t = batch falls back to the reference path)."""
    q = _row_quantum(dtype)
    return (t >= q and t % q == 0 and d % 128 == 0 and
            dq % 128 == 0 and dk % 128 == 0 and dv % 128 == 0)


def fused_mlp_eligible(t: int, d: int, f: int, dtype="float32") -> bool:
    q = _row_quantum(dtype)
    return t >= q and t % q == 0 and d % 128 == 0 and f % 128 == 0


def _path_counter():
    from paddle_tpu.observability import default_registry
    return default_registry().counter(
        "paddle_tpu_fused_block_path_total",
        "fused-block kernel routing chosen at trace time",
        labelnames=("kernel", "path"))


def record_path(kernel: str, fused: bool):
    """Trace-time telemetry: which implementation this compile will run
    (same idiom as the flash-attention backward path counter)."""
    _path_counter().labels(
        kernel=kernel, path="fused" if fused else "reference").inc()


# ---------------------------------------------------------------------------
# clamped index maps — the DMA-once idiom shared by the fused kernels
# and their static-verifier specs (analysis/kernel_verify checks the
# "each block DMAs exactly once per inner sweep" invariant concretely)
# ---------------------------------------------------------------------------

def _clamped(lo, n):
    """Weight-spec index map: clamp the walking axis into [lo, lo+n) so
    a block outside its phase re-uses the resident block (no DMA)."""
    return lambda i, j: (0, jnp.clip(j - lo, 0, n - 1))


def _clamped_out(lo, n):
    """Output-spec variant of :func:`_clamped` (row block tracks i)."""
    return lambda i, j: (i, jnp.clip(j - lo, 0, n - 1))


def _clamp3(lo, n):
    """Decoder-grid (batch, token, inner) variant of :func:`_clamped`."""
    return lambda bi, i, j: (0, jnp.clip(j - lo, 0, n - 1))


# ---------------------------------------------------------------------------
# fused rmsnorm + QKV projection
# ---------------------------------------------------------------------------

def _qkv_kernel(x_ref, wn_ref, wq_ref, wk_ref, wv_ref, *out_refs, eps, nq,
                nk, residuals):
    """Grid: (token_blocks, out_blocks); the out axis is innermost
    (sequential) so the normalized activations computed at j == 0 stay
    in VMEM scratch for every projection block of the token block.
    With ``residuals`` the normalized activations and the inverse rms
    are also emitted (once, at j == 0) for the custom VJP — the
    forward-only (inference) variant keeps the pure
    one-read/three-write form."""
    if residuals:
        q_ref, k_ref, v_ref, xn_out_ref, inv_ref, xn_ref = out_refs
    else:
        q_ref, k_ref, v_ref, xn_ref = out_refs
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _norm():
        xf = x_ref[:].astype(jnp.float32)
        ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(ms + eps)
        xn_ref[:] = (xf * inv) * wn_ref[:].astype(jnp.float32)
        if residuals:
            xn_out_ref[:] = xn_ref[:].astype(xn_out_ref.dtype)
            inv_ref[:] = inv

    def _proj(w_ref, o_ref):
        o_ref[:] = jax.lax.dot_general(
            xn_ref[:].astype(w_ref.dtype), w_ref[:],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(o_ref.dtype)

    @pl.when(j < nq)
    def _q():
        _proj(wq_ref, q_ref)

    @pl.when(jnp.logical_and(j >= nq, j < nq + nk))
    def _k():
        _proj(wk_ref, k_ref)

    @pl.when(j >= nq + nk)
    def _v():
        _proj(wv_ref, v_ref)


def _qkv_pallas(x2d, wn, wq, wk, wv, *, eps, block_t, block_o, interpret,
                residuals):
    t, d = x2d.shape
    dq, dk, dv = wq.shape[1], wk.shape[1], wv.shape[1]
    nt = t // block_t
    nq, nkb, nvb = dq // block_o, dk // block_o, dv // block_o

    # each weight/output spec clamps the out-axis index into its own
    # range (module-level _clamped/_clamped_out): while j walks another
    # projection's blocks the index map returns the previous value, so
    # Mosaic re-uses the resident block instead of issuing a DMA —
    # every block is fetched/flushed once
    out_specs = [
        pl.BlockSpec((block_t, block_o), _clamped_out(0, nq)),
        pl.BlockSpec((block_t, block_o), _clamped_out(nq, nkb)),
        pl.BlockSpec((block_t, block_o), _clamped_out(nq + nkb, nvb)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((t, dq), x2d.dtype),
        jax.ShapeDtypeStruct((t, dk), x2d.dtype),
        jax.ShapeDtypeStruct((t, dv), x2d.dtype),
    ]
    if residuals:
        out_specs += [pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
                      pl.BlockSpec((block_t, 1), lambda i, j: (i, 0))]
        out_shape += [jax.ShapeDtypeStruct((t, d), x2d.dtype),
                      jax.ShapeDtypeStruct((t, 1), jnp.float32)]

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    return pl.pallas_call(
        functools.partial(_qkv_kernel, eps=eps, nq=nq, nk=nkb,
                          residuals=residuals),
        grid=(nt, nq + nkb + nvb),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
            pl.BlockSpec((1, d), lambda i, j: (0, 0)),
            pl.BlockSpec((d, block_o), _clamped(0, nq)),
            pl.BlockSpec((d, block_o), _clamped(nq, nkb)),
            pl.BlockSpec((d, block_o), _clamped(nq + nkb, nvb)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((block_t, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(x2d, wn.reshape(1, d), wq, wk, wv)


def _qkv_reference(x2d, wn, wq, wk, wv, eps, residuals=False):
    xf = x2d.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    xn = ((xf * inv) * wn.astype(jnp.float32)).astype(x2d.dtype)

    def proj(w):
        return jax.lax.dot_general(
            xn, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x2d.dtype)

    out = (proj(wq), proj(wk), proj(wv))
    return out + (xn, inv) if residuals else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _qkv_core(x2d, wn, wq, wk, wv, eps, use_pallas, interpret,
              block_t, block_o):
    # primal (forward-only) path: no residual outputs — inference keeps
    # the pure one-read/three-write kernel
    if use_pallas:
        return tuple(_qkv_pallas(x2d, wn, wq, wk, wv, eps=eps,
                                 block_t=block_t, block_o=block_o,
                                 interpret=interpret, residuals=False))
    return _qkv_reference(x2d, wn, wq, wk, wv, eps)


def _qkv_fwd(x2d, wn, wq, wk, wv, eps, use_pallas, interpret,
             block_t, block_o):
    # differentiated path: the kernel additionally emits the normalized
    # activations and the inverse rms (flash-attention saved-lse style),
    # so the backward never recomputes the norm chain
    if use_pallas:
        q, k, v, xn, inv = _qkv_pallas(
            x2d, wn, wq, wk, wv, eps=eps, block_t=block_t,
            block_o=block_o, interpret=interpret, residuals=True)
    else:
        q, k, v, xn, inv = _qkv_reference(x2d, wn, wq, wk, wv, eps,
                                          residuals=True)
    return (q, k, v), (x2d, wn, wq, wk, wv, xn, inv)


def _qkv_bwd(eps, use_pallas, interpret, block_t, block_o, res, cts):
    # mixed-precision discipline matches what autodiff of the unfused
    # chain produces: matmuls accumulate fp32 on the MXU but cotangents
    # materialize in the io dtype (bf16 in training) — only the fused
    # rmsnorm-backward elementwise chain runs fp32, and XLA fuses it
    x2d, wn, wq, wk, wv, xn, inv = res
    dq, dk, dv = cts
    dt = x2d.dtype
    wnf = wn.astype(jnp.float32)

    def back(g, w):                                     # g @ w.T
        return jax.lax.dot_general(g, w, (((1,), (1,)), ((), ())))

    def wgrad(g):                                       # xn.T @ g, fp32
        return jax.lax.dot_general(
            xn, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dxn = (back(dq, wq) + back(dk, wk) + back(dv, wv)) \
        .astype(jnp.float32)                            # [T, d]
    dwq = wgrad(dq).astype(wq.dtype)
    dwk = wgrad(dk).astype(wk.dtype)
    dwv = wgrad(dv).astype(wv.dtype)
    xf = x2d.astype(jnp.float32)
    xhat = xf * inv                                     # saved inv: no
    dwn = jnp.sum(dxn * xhat, axis=0).astype(wn.dtype)  # stat recompute
    # rmsnorm backward (same equations as ops/pallas/rmsnorm.py):
    # dx = inv * g - x * inv^3 * mean(g * x), with g = dxn * w
    gx = dxn * wnf
    dot = jnp.mean(gx * xf, axis=-1, keepdims=True)
    dx = (inv * gx - xf * (inv ** 3) * dot).astype(dt)
    return dx, dwn, dwq, dwk, dwv


_qkv_core.defvjp(_qkv_fwd, _qkv_bwd)


def _default_qkv_blocks(t, d, dq, dk, dv, dtype):
    """Heuristic fallback: the first (token, out) block pair — widest
    out block first, then tallest token block — whose working set (x +
    fp32 normalized scratch + weight/out blocks, double-buffered io)
    stays under ~10 MB of VMEM."""
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    # 16-bit dtypes tile (16, 128): never offer an 8-row block there
    bts = (512, 256, 128, 64, 32, 16) if itemsize == 2 else \
        (512, 256, 128, 64, 32, 16, 8)
    for bo in (512, 256, 128):
        if dq % bo or dk % bo or dv % bo:
            continue
        for bt in bts:
            if t % bt:
                continue
            vmem = (2 * bt * d * itemsize        # x, double-buffered
                    + bt * d * 4                 # fp32 xn scratch
                    + 6 * d * bo * itemsize      # 3 weight blocks, 2x
                    + 6 * bt * bo * itemsize)    # 3 out blocks, 2x
            if vmem < 10 * (1 << 20):
                return bt, bo
    return bts[-1], 128


def fused_rmsnorm_qkv(x, norm_weight, wq, wk, wv, epsilon: float = 1e-5,
                      block_t: int = None, block_o: int = None,
                      interpret: bool = None, autotune: bool = None,
                      use_pallas: bool = None):
    """``q, k, v = (rmsnorm(x) * norm_weight) @ (wq | wk | wv)`` in one
    fused pass — the normalized activations never round-trip HBM.

    x: [..., d]; norm_weight: [d]; wq/wk/wv: [d, dq/dk/dv] (paddle
    [in, out] layout).  Returns projections with x's leading dims.
    Differentiable wrt every array input.  Ineligible shapes fall back
    to reference math inside the same custom VJP (the API is total)."""
    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)
    t = x2d.shape[0]
    dq, dk, dv = int(wq.shape[-1]), int(wk.shape[-1]), int(wv.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = fused_qkv_eligible(t, d, dq, dk, dv, x.dtype)
    if autotune is None:
        autotune = not interpret
    if use_pallas and (block_t is None or block_o is None):
        if autotune and not interpret:
            from paddle_tpu.ops.pallas.autotune import qkv_block_sizes
            bt, bo = qkv_block_sizes(t, d, dq, dk, dv, str(x.dtype))
        else:
            bt, bo = _default_qkv_blocks(t, d, dq, dk, dv, str(x.dtype))
        block_t = block_t or bt
        block_o = block_o or bo
    if use_pallas and (t % block_t or dq % block_o or dk % block_o
                       or dv % block_o):
        raise ValueError(
            f"shapes t={t} dq={dq} dk={dk} dv={dv} not divisible by "
            f"blocks ({block_t}, {block_o})")
    q, k, v = _qkv_core(x2d, norm_weight, wq, wk, wv, float(epsilon),
                        bool(use_pallas), bool(interpret),
                        int(block_t or 0), int(block_o or 0))
    lead = shape[:-1]
    return (q.reshape(*lead, dq), k.reshape(*lead, dk),
            v.reshape(*lead, dv))


# ---------------------------------------------------------------------------
# fused MLP (gated SwiGLU and plain act+bias feed-forward)
# ---------------------------------------------------------------------------

def _mlp_kernel(*refs, act, gated, has_bias):
    """Grid: (token_blocks, hidden_blocks); the hidden (f) axis is the
    innermost (sequential) dim — each step materializes only a
    [bt, bf] tile of the hidden activations in VMEM and folds it into
    the fp32 down-projection accumulator."""
    if gated:
        x_ref, wg_ref, wu_ref, wd_ref, y_ref, acc_ref = refs
        bu_ref = bd_ref = None
    else:
        x_ref, wu_ref, wd_ref, bu_ref, bd_ref, y_ref, acc_ref = refs
        wg_ref = None
    j = pl.program_id(1)
    nf = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    xb = x_ref[:]
    u = jax.lax.dot_general(
        xb, wu_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bt, bf]
    if has_bias:
        u = u + bu_ref[:].astype(jnp.float32)
    if gated:
        g = jax.lax.dot_general(
            xb, wg_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        h = _ACT[act](g) * u
    else:
        h = _ACT[act](u)
    acc_ref[:] += jax.lax.dot_general(
        h.astype(wd_ref.dtype), wd_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bt, d]

    @pl.when(j == nf - 1)
    def _finalize():
        out = acc_ref[:]
        if has_bias:
            out = out + bd_ref[:].astype(jnp.float32)
        y_ref[:] = out.astype(y_ref.dtype)


def _mlp_pallas(x2d, weights, biases, *, act, gated, block_t, block_f,
                interpret):
    t, d = x2d.shape
    f = weights[-2].shape[1] if gated else weights[0].shape[1]
    nt = t // block_t
    nf = f // block_f

    in_specs = [pl.BlockSpec((block_t, d), lambda i, j: (i, 0))]
    args = [x2d]
    for w in weights[:-1]:                               # gate/up: [d, f]
        in_specs.append(pl.BlockSpec((d, block_f), lambda i, j: (0, j)))
        args.append(w)
    in_specs.append(pl.BlockSpec((block_f, d), lambda i, j: (j, 0)))
    args.append(weights[-1])                             # down: [f, d]
    if biases is not None:
        b1, b2 = biases
        in_specs.append(pl.BlockSpec((1, block_f), lambda i, j: (0, j)))
        args.append(b1.reshape(1, f))
        in_specs.append(pl.BlockSpec((1, d), lambda i, j: (0, 0)))
        args.append(b2.reshape(1, d))

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    return pl.pallas_call(
        functools.partial(_mlp_kernel, act=act, gated=gated,
                          has_bias=biases is not None),
        grid=(nt, nf),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t, d), x2d.dtype),
        scratch_shapes=[pltpu.VMEM((block_t, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(*args)


def _dot(a, b, contract):
    return jax.lax.dot_general(a, b, (contract, ((), ())),
                               preferred_element_type=jnp.float32)


def _mlp_gated_reference(x2d, wg, wu, wd, act):
    g = _dot(x2d, wg, ((1,), (0,)))
    u = _dot(x2d, wu, ((1,), (0,)))
    h = (_ACT[act](g) * u).astype(x2d.dtype)
    return _dot(h, wd, ((1,), (0,))).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _mlp_gated_core(x2d, wg, wu, wd, act, use_pallas, interpret,
                    block_t, block_f):
    return _mlp_gated_fwd(x2d, wg, wu, wd, act, use_pallas, interpret,
                          block_t, block_f)[0]


def _mlp_gated_fwd(x2d, wg, wu, wd, act, use_pallas, interpret,
                   block_t, block_f):
    if use_pallas:
        y = _mlp_pallas(x2d, (wg, wu, wd), None, act=act, gated=True,
                        block_t=block_t, block_f=block_f,
                        interpret=interpret)
    else:
        y = _mlp_gated_reference(x2d, wg, wu, wd, act)
    return y, (x2d, wg, wu, wd)


def _mlp_gated_bwd(act, use_pallas, interpret, block_t, block_f, res, dy):
    # recompute in the io dtype (matmuls still accumulate fp32 on the
    # MXU) — the materialized [T, f] intermediates cost the same HBM
    # bytes autodiff of the unfused bf16 chain would spend
    x2d, wg, wu, wd = res
    dt = x2d.dtype

    def dot_t(a, b, contract):      # io-dtype out, fp32 MXU accumulate
        return jax.lax.dot_general(a, b, (contract, ((), ())))

    g = dot_t(x2d, wg, ((1,), (0,)))                    # recompute
    u = dot_t(x2d, wu, ((1,), (0,)))
    s, act_vjp = jax.vjp(_ACT[act], g)
    h = s * u
    dh = dot_t(dy, wd, ((1,), (1,)))                    # [T, f]
    dwd = _dot(h, dy, ((0,), (0,))).astype(wd.dtype)
    du = dh * s
    dg = act_vjp(dh * u)[0].astype(dt)
    dx = dot_t(dg, wg, ((1,), (1,))) + dot_t(du, wu, ((1,), (1,)))
    dwg = _dot(x2d, dg, ((0,), (0,))).astype(wg.dtype)
    dwu = _dot(x2d, du, ((0,), (0,))).astype(wu.dtype)
    return dx.astype(dt), dwg, dwu, dwd


_mlp_gated_core.defvjp(_mlp_gated_fwd, _mlp_gated_bwd)


def _ffn_reference(x2d, w1, b1, w2, b2, act):
    u = _dot(x2d, w1, ((1,), (0,))) + b1.astype(jnp.float32)
    h = _ACT[act](u).astype(x2d.dtype)
    y = _dot(h, w2, ((1,), (0,))) + b2.astype(jnp.float32)
    return y.astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _ffn_core(x2d, w1, b1, w2, b2, act, use_pallas, interpret,
              block_t, block_f):
    return _ffn_fwd(x2d, w1, b1, w2, b2, act, use_pallas, interpret,
                    block_t, block_f)[0]


def _ffn_fwd(x2d, w1, b1, w2, b2, act, use_pallas, interpret,
             block_t, block_f):
    if use_pallas:
        y = _mlp_pallas(x2d, (w1, w2), (b1, b2), act=act, gated=False,
                        block_t=block_t, block_f=block_f,
                        interpret=interpret)
    else:
        y = _ffn_reference(x2d, w1, b1, w2, b2, act)
    return y, (x2d, w1, b1, w2, b2)


def _ffn_bwd(act, use_pallas, interpret, block_t, block_f, res, dy):
    x2d, w1, b1, w2, b2 = res
    dt = x2d.dtype
    u = (_dot(x2d, w1, ((1,), (0,))) + b1.astype(jnp.float32)).astype(dt)
    h, act_vjp = jax.vjp(_ACT[act], u)
    dh = jax.lax.dot_general(dy, w2,
                             ((((1,), (1,))), ((), ()))).astype(dt)
    dw2 = _dot(h, dy, ((0,), (0,))).astype(w2.dtype)
    db2 = jnp.sum(dy.astype(jnp.float32), axis=0).astype(b2.dtype)
    du = act_vjp(dh)[0].astype(dt)
    dx = _dot(du, w1, ((1,), (1,))).astype(dt)
    dw1 = _dot(x2d, du, ((0,), (0,))).astype(w1.dtype)
    db1 = jnp.sum(du.astype(jnp.float32), axis=0).astype(b1.dtype)
    return dx, dw1, db1, dw2, db2


_ffn_core.defvjp(_ffn_fwd, _ffn_bwd)


def _default_mlp_blocks(t, d, f, dtype):
    """Heuristic fallback: the first (token, hidden) block pair — widest
    hidden block first, then tallest token block — whose working set (x
    + y + fp32 accumulator + gate/up/down weight blocks, double-buffered
    io) stays under ~10 MB of VMEM."""
    itemsize = 2 if "bfloat16" in dtype or "float16" in dtype else 4
    # 16-bit dtypes tile (16, 128): never offer an 8-row block there
    bts = (512, 256, 128, 64, 32, 16) if itemsize == 2 else \
        (512, 256, 128, 64, 32, 16, 8)
    for bf in (512, 256, 128):
        if f % bf:
            continue
        for bt in bts:
            if t % bt:
                continue
            vmem = (2 * bt * d * itemsize        # x, double-buffered
                    + bt * d * 4                 # fp32 accumulator
                    + 2 * bt * d * itemsize      # y, double-buffered
                    + 6 * d * bf * itemsize)     # 3 weight blocks, 2x
            if vmem < 10 * (1 << 20):
                return bt, bf
    return bts[-1], 128


def _mlp_blocks(t, d, f, dtype, block_t, block_f, interpret, autotune):
    if block_t is None or block_f is None:
        if autotune and not interpret:
            from paddle_tpu.ops.pallas.autotune import mlp_block_sizes
            bt, bf = mlp_block_sizes(t, d, f, dtype)
        else:
            bt, bf = _default_mlp_blocks(t, d, f, dtype)
        block_t = block_t or bt
        block_f = block_f or bf
    if t % block_t or f % block_f:
        raise ValueError(f"shapes t={t} f={f} not divisible by blocks "
                         f"({block_t}, {block_f})")
    return int(block_t), int(block_f)


def fused_mlp(x, w_gate, w_up, w_down, activation: str = "silu",
              block_t: int = None, block_f: int = None,
              interpret: bool = None, autotune: bool = None,
              use_pallas: bool = None):
    """``y = (act(x @ w_gate) * (x @ w_up)) @ w_down`` with the [T, f]
    hidden intermediate VMEM-resident (SwiGLU when ``activation='silu'``).

    x: [..., d]; w_gate/w_up: [d, f]; w_down: [f, d].  Differentiable
    wrt every array input; ineligible shapes take reference math inside
    the same custom VJP."""
    if activation not in _ACT:
        raise ValueError(f"unsupported activation {activation!r}; "
                         f"expected one of {SUPPORTED_ACTS}")
    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)
    t = x2d.shape[0]
    f = int(w_up.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = fused_mlp_eligible(t, d, f, x.dtype)
    if autotune is None:
        autotune = not interpret
    if use_pallas:
        block_t, block_f = _mlp_blocks(t, d, f, str(x.dtype), block_t,
                                       block_f, interpret, autotune)
    y = _mlp_gated_core(x2d, w_gate, w_up, w_down, str(activation),
                        bool(use_pallas), bool(interpret),
                        int(block_t or 0), int(block_f or 0))
    return y.reshape(shape)


# ---------------------------------------------------------------------------
# whole-decoder-block megakernel (ISSUE 15)
# ---------------------------------------------------------------------------
#
# Inner-axis phase layout for grid (batch, token_blocks, inner):
#
#   [0, nqc)            q-projection column blocks (+ RoPE, into q scratch)
#   [nqc, nqc+nkc)      k/v-projection column blocks (+ RoPE on k), written
#                       into the sequence-wide K/V scratch at this token
#                       block's rows — later token blocks read them back
#                       for causal attention without any HBM traffic
#   [B0, B0+nh*nt)      attention: (head, k-block) pairs, online softmax
#                       in fp32 scratch; k-blocks past the causal frontier
#                       are pl.when-skipped
#   [C0, C0+no)         output-projection column blocks + residual add
#   [D0, D0+nf)         post-attention rmsnorm (at the first step) and the
#                       SwiGLU MLP hidden blocks folded into an fp32
#                       down-projection accumulator; the final step adds
#                       the residual and emits the block's single output
#
# Numerics mirror the unfused chain: every intermediate that the unfused
# lowering materializes in the io dtype is cast to the io dtype at the
# same point in-register (norm outputs, roped q/k, v, attention output,
# o-proj output); statistics, softmax and matmul accumulation stay fp32.

_DECODER_VMEM_BUDGET = 12 * (1 << 20)


def decoder_vmem_bytes(s, d, dq, dkv, hd, f, bt, bo, bf, dtype) -> int:
    """VMEM working set of the whole-block kernel, computed by the
    SHARED verifier footprint model (``analysis/kernel_verify``): the
    sequence-wide K/V scratch dominates; walked weight/io blocks are
    double-buffered by the grid pipeline, constant-map norm weights are
    resident.  Because the eligibility gate and ``lint --kernels`` both
    read this one model, their verdicts can never disagree."""
    from paddle_tpu.analysis.kernel_verify import footprint_bytes
    return footprint_bytes(
        _decoder_verify_spec(1, s, d, dq, dkv, hd, f, bt, bo, bf, dtype))


def _default_decoder_blocks(s, d, dq, dkv, hd, f, dtype):
    """First (block_t, block_o, block_f) — widest out/hidden blocks
    first, then tallest token block — whose working set fits the VMEM
    budget; None when nothing fits (the eligibility gate)."""
    q = _row_quantum(dtype)
    bts = [b for b in (256, 128, 64, 32, 16, 8) if b >= q]
    for bo in (512, 256, 128):
        if bo % hd or dq % bo or dkv % bo or d % bo:
            continue
        for bf in (512, 256, 128):
            if f % bf:
                continue
            for bt in bts:
                if s % bt:
                    continue
                if decoder_vmem_bytes(s, d, dq, dkv, hd, f, bt, bo, bf,
                                      dtype) < _DECODER_VMEM_BUDGET:
                    return bt, bo, bf
    return None


def fused_decoder_eligible(b, s, d, dq, dkv, hd, f, dtype="float32") -> bool:
    """Shape gate for the whole-block kernel: lane-tileable feature
    dims, whole 128-aligned heads (RoPE and the per-head attention
    slices walk head boundaries), a flash-legal sequence for the VJP
    recompute, and a (bt, bo, bf) choice inside the VMEM budget."""
    q = _row_quantum(dtype)
    if s < q or s % q:
        return False
    if s % min(128, s):                 # flash blocks in the backward
        return False
    if d % 128 or dq % 128 or dkv % 128 or f % 128:
        return False
    if hd <= 0 or hd % 128 or dq % hd or dkv % hd:
        return False
    if (dq // hd) % (dkv // hd):        # GQA: q heads per kv head
        return False
    return _default_decoder_blocks(s, d, dq, dkv, hd, f,
                                   str(dtype)) is not None


def _decoder_kernel(x_ref, wn1_ref, wq_ref, wk_ref, wv_ref, cos_ref,
                    sin_ref, wo_ref, wn2_ref, wg_ref, wu_ref, wd_ref,
                    y_ref, xn_scr, q_scr, k_scr, v_scr, attn_scr, x2_scr,
                    m_scr, l_scr, acc_scr, yacc_scr, *, eps, nh, nkvh,
                    hd, bt, bo, bf, nqc, nkc, nt, no, nf):
    i = pl.program_id(1)
    j = pl.program_id(2)
    B0 = nqc + nkc
    C0 = B0 + nh * nt
    D0 = C0 + no
    hh = hd // 2
    rep = nh // nkvh
    scale = 1.0 / (hd ** 0.5)
    io_dt = y_ref.dtype

    def _rmsnorm_into(src_f32, wn_ref):
        inv = jax.lax.rsqrt(
            jnp.mean(src_f32 * src_f32, axis=-1, keepdims=True) + eps)
        xn_scr[:] = ((src_f32 * inv)
                     * wn_ref[:].astype(jnp.float32)).astype(xn_scr.dtype)

    @pl.when(j == 0)
    def _norm1():
        _rmsnorm_into(x_ref[0].astype(jnp.float32), wn1_ref)

    def _proj(w_ref):
        return jax.lax.dot_general(
            xn_scr[:], w_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def _rope_heads(blk_f32):
        """RoPE per whole head of a [bt, bo] projection block — the
        unfused chain quantizes projections to the io dtype before the
        fp32 rotation, so this does too."""
        cos = cos_ref[:].astype(jnp.float32)           # [bt, hd//2]
        sin = sin_ref[:].astype(jnp.float32)
        heads = []
        for h0 in range(bo // hd):
            gh = blk_f32[:, h0 * hd:(h0 + 1) * hd].astype(io_dt) \
                .astype(jnp.float32)
            x1, x2 = gh[:, :hh], gh[:, hh:]
            heads.append(jnp.concatenate(
                [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1))
        return jnp.concatenate(heads, axis=-1) if len(heads) > 1 \
            else heads[0]

    # -- phase A: projections + RoPE into scratch ---------------------------
    @pl.when(j < nqc)
    def _q_cols():
        q_scr[:, pl.ds(j * bo, bo)] = _rope_heads(_proj(wq_ref)) \
            .astype(q_scr.dtype)

    @pl.when(jnp.logical_and(j >= nqc, j < B0))
    def _kv_cols():
        jk = j - nqc
        rows = pl.ds(i * bt, bt)
        k_scr[rows, pl.ds(jk * bo, bo)] = _rope_heads(_proj(wk_ref)) \
            .astype(k_scr.dtype)
        v_scr[rows, pl.ds(jk * bo, bo)] = _proj(wv_ref).astype(v_scr.dtype)

    # -- phase B: causal flash attention over the VMEM-resident K/V --------
    @pl.when(jnp.logical_and(j >= B0, j < C0))
    def _attention():
        t = j - B0
        h = t // nt
        kj = t % nt

        @pl.when(kj == 0)
        def _init():
            acc_scr[:] = jnp.zeros_like(acc_scr)
            m_scr[:] = jnp.full_like(m_scr, _DEC_NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)

        @pl.when(kj <= i)
        def _fold():
            qh = q_scr[:, pl.ds(h * hd, hd)]
            kvh = h // rep
            kb = k_scr[pl.ds(kj * bt, bt), pl.ds(kvh * hd, hd)]
            vb = v_scr[pl.ds(kj * bt, bt), pl.ds(kvh * hd, hd)]
            s_ = jax.lax.dot_general(
                qh, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [bt, bt]
            q_pos = i * bt + jax.lax.broadcasted_iota(
                jnp.int32, (bt, bt), 0)
            k_pos = kj * bt + jax.lax.broadcasted_iota(
                jnp.int32, (bt, bt), 1)
            s_ = jnp.where(q_pos >= k_pos, s_, _DEC_NEG_INF)
            m_prev = m_scr[:]
            m_new = jnp.maximum(m_prev, jnp.max(s_, axis=-1, keepdims=True))
            p = jnp.exp(s_ - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_scr[:] = corr * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc_scr[:] = acc_scr[:] * corr + pv
            m_scr[:] = m_new

        @pl.when(kj == i)                   # last visible block: finalize
        def _finalize():
            l = l_scr[:]
            safe_l = jnp.where(l > 0, l, 1.0)
            attn_scr[:, pl.ds(h * hd, hd)] = \
                (acc_scr[:] / safe_l).astype(attn_scr.dtype)

    # -- phase C: output projection + residual ------------------------------
    @pl.when(jnp.logical_and(j >= C0, j < D0))
    def _o_proj():
        jo = j - C0
        cols = pl.ds(jo * bo, bo)
        ob = jax.lax.dot_general(
            attn_scr[:], wo_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        x2_scr[:, cols] = x_ref[0, :, cols] + ob.astype(io_dt)

    # -- phase D: post-attention norm + SwiGLU MLP + residual ---------------
    @pl.when(j == D0)
    def _norm2():
        _rmsnorm_into(x2_scr[:].astype(jnp.float32), wn2_ref)
        yacc_scr[:] = jnp.zeros_like(yacc_scr)

    @pl.when(j >= D0)
    def _mlp():
        xb = xn_scr[:]
        g = jax.lax.dot_general(
            xb, wg_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        u = jax.lax.dot_general(
            xb, wu_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        hgu = jax.nn.silu(g) * u
        yacc_scr[:] += jax.lax.dot_general(
            hgu.astype(wd_ref.dtype), wd_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == D0 + nf - 1)
    def _emit():
        y_ref[0] = x2_scr[:] + yacc_scr[:].astype(io_dt)


_DEC_NEG_INF = -1e30


def _decoder_pallas(x, wn1, wq, wk, wv, cos, sin, wo, wn2, wg, wu, wd, *,
                    eps, nh, nkvh, bt, bo, bf, interpret):
    b, s, d = x.shape
    dq, dkv, f = wq.shape[1], wk.shape[1], wu.shape[1]
    hd = dq // nh
    nt = s // bt
    nqc, nkc = dq // bo, dkv // bo
    no, nf = d // bo, f // bf
    B0 = nqc + nkc
    C0 = B0 + nh * nt
    D0 = C0 + no
    inner = D0 + nf

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"))

    return pl.pallas_call(
        functools.partial(_decoder_kernel, eps=eps, nh=nh, nkvh=nkvh,
                          hd=hd, bt=bt, bo=bo, bf=bf, nqc=nqc, nkc=nkc,
                          nt=nt, no=no, nf=nf),
        grid=(b, nt, inner),
        in_specs=[
            pl.BlockSpec((1, bt, d), lambda bi, i, j: (bi, i, 0)),
            pl.BlockSpec((1, d), lambda bi, i, j: (0, 0)),
            pl.BlockSpec((d, bo), _clamp3(0, nqc)),
            pl.BlockSpec((d, bo), _clamp3(nqc, nkc)),
            pl.BlockSpec((d, bo), _clamp3(nqc, nkc)),
            pl.BlockSpec((bt, hd // 2), lambda bi, i, j: (i, 0)),
            pl.BlockSpec((bt, hd // 2), lambda bi, i, j: (i, 0)),
            pl.BlockSpec((dq, bo), _clamp3(C0, no)),
            pl.BlockSpec((1, d), lambda bi, i, j: (0, 0)),
            pl.BlockSpec((d, bf), _clamp3(D0, nf)),
            pl.BlockSpec((d, bf), _clamp3(D0, nf)),
            pl.BlockSpec((bf, d),
                         lambda bi, i, j: (jnp.clip(j - D0, 0, nf - 1), 0)),
        ],
        out_specs=pl.BlockSpec((1, bt, d), lambda bi, i, j: (bi, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, s, d), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((bt, d), x.dtype),       # xn (norm1, reused norm2)
            pltpu.VMEM((bt, dq), x.dtype),      # roped q
            pltpu.VMEM((s, dkv), x.dtype),      # K rows, whole sequence
            pltpu.VMEM((s, dkv), x.dtype),      # V rows, whole sequence
            pltpu.VMEM((bt, dq), x.dtype),      # attention output
            pltpu.VMEM((bt, d), x.dtype),       # post-attention residual
            pltpu.VMEM((bt, 1), jnp.float32),   # online-softmax max
            pltpu.VMEM((bt, 1), jnp.float32),   # online-softmax sum
            pltpu.VMEM((bt, hd), jnp.float32),  # per-head softmax acc
            pltpu.VMEM((bt, d), jnp.float32),   # MLP down accumulator
        ],
        interpret=interpret,
        **params,
    )(x, wn1.reshape(1, d), wq, wk, wv, cos, sin, wo,
      wn2.reshape(1, d), wg, wu, wd)


def _rope_ref(x, cos, sin):
    """Reference RoPE on [b, s, heads, hd] with [s, hd//2] tables — the
    same half-rotation math as F.apply_rotary_emb at offset 0."""
    c = cos[None, :, None, :].astype(jnp.float32)
    s_ = sin[None, :, None, :].astype(jnp.float32)
    half = x.shape[-1] // 2
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    return jnp.concatenate([x1 * c - x2 * s_, x2 * c + x1 * s_],
                           axis=-1).astype(x.dtype)


def _decoder_reference(x, wn1, wq, wk, wv, cos, sin, wo, wn2, wg, wu, wd,
                       *, eps, nh, nkvh):
    """The unfused decoder-block composition: rmsnorm → projections →
    RoPE → causal flash attention → o-proj → residual → rmsnorm →
    SwiGLU MLP → residual.  Differentiable end-to-end (flash's blockwise
    backward keeps memory O(s·block)) — both the ineligible-shape
    fallback of :func:`fused_decoder_block` and the recompute target of
    its block-boundary-remat VJP."""
    b, s, d = x.shape
    dq, dkv = wq.shape[1], wk.shape[1]
    hd = dq // nh
    x2d = x.reshape(-1, d)
    q, k, v = _qkv_reference(x2d, wn1, wq, wk, wv, eps)
    q = _rope_ref(q.reshape(b, s, nh, hd), cos, sin)
    k = _rope_ref(k.reshape(b, s, nkvh, hd), cos, sin)
    v = v.reshape(b, s, nkvh, hd)
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    blk = min(128, s)
    o = flash_attention(q, k, v, causal=True, block_q=blk, block_k=blk,
                        autotune=False)
    h = jax.lax.dot_general(
        o.reshape(-1, dq), wo, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    x2 = x + h.reshape(b, s, d)
    xf = x2.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    xn2 = ((xf * inv) * wn2.astype(jnp.float32)).astype(x.dtype)
    y = _mlp_gated_reference(xn2.reshape(-1, d), wg, wu, wd, "silu")
    return x2 + y.reshape(b, s, d)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(12, 13, 14, 15, 16, 17, 18, 19))
def _decoder_core(x, wn1, wq, wk, wv, cos, sin, wo, wn2, wg, wu, wd,
                  eps, nh, nkvh, use_pallas, interpret, bt, bo, bf):
    if use_pallas:
        return _decoder_pallas(x, wn1, wq, wk, wv, cos, sin, wo, wn2,
                               wg, wu, wd, eps=eps, nh=nh, nkvh=nkvh,
                               bt=bt, bo=bo, bf=bf, interpret=interpret)
    return _decoder_reference(x, wn1, wq, wk, wv, cos, sin, wo, wn2,
                              wg, wu, wd, eps=eps, nh=nh, nkvh=nkvh)


def _decoder_fwd(x, wn1, wq, wk, wv, cos, sin, wo, wn2, wg, wu, wd,
                 eps, nh, nkvh, use_pallas, interpret, bt, bo, bf):
    y = _decoder_core(x, wn1, wq, wk, wv, cos, sin, wo, wn2, wg, wu, wd,
                      eps, nh, nkvh, use_pallas, interpret, bt, bo, bf)
    # block-boundary remat: save only the INPUTS — one activation tensor
    # per layer instead of the unfused chain's q/k/v/attention/hidden set
    return y, (x, wn1, wq, wk, wv, cos, sin, wo, wn2, wg, wu, wd)


def _decoder_bwd(eps, nh, nkvh, use_pallas, interpret, bt, bo, bf, res, dy):
    # recompute the block from its saved inputs in reference math and
    # differentiate that — the VJP of the unfused chain (flash keeps the
    # attention backward blockwise), costing one extra block forward but
    # no saved intermediates: the training memory story of the kernel
    def ref(*args):
        return _decoder_reference(*args, eps=eps, nh=nh, nkvh=nkvh)

    _, vjp = jax.vjp(ref, *res)
    return vjp(dy)


_decoder_core.defvjp(_decoder_fwd, _decoder_bwd)


def fused_decoder_block(x, norm1_weight, wq, wk, wv, rope_cos, rope_sin,
                        wo, norm2_weight, wg, wu, wd, *, num_heads: int,
                        num_kv_heads: int, epsilon: float = 1e-5,
                        block_t: int = None, block_o: int = None,
                        block_f: int = None, interpret: bool = None,
                        autotune: bool = None, use_pallas: bool = None):
    """One whole llama decoder block — rmsnorm → QKV → RoPE → causal
    attention → o-proj (+residual) → rmsnorm → SwiGLU MLP (+residual) —
    as a single Pallas pass whose boundary activations never round-trip
    HBM.

    x: [b, s, d]; rope_cos/rope_sin: [max_pos, head_dim//2] tables
    (rows [0, s) are used — the no-cache, offset-0 training/prefill
    form).  Weight layouts match the llama Linears ([in, out]).
    Differentiable wrt every array input via block-boundary remat;
    ineligible shapes take the unfused reference composition inside the
    same custom VJP (the API is total)."""
    if x.ndim != 3:
        raise ValueError(f"fused_decoder_block expects [b, s, d], got "
                         f"shape {tuple(x.shape)}")
    b, s, d = int(x.shape[0]), int(x.shape[1]), int(x.shape[2])
    dq, dkv, f = int(wq.shape[-1]), int(wk.shape[-1]), int(wu.shape[-1])
    nh, nkvh = int(num_heads), int(num_kv_heads)
    hd = dq // nh
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = (int(rope_cos.shape[0]) >= s and
                      fused_decoder_eligible(b, s, d, dq, dkv, hd, f,
                                             x.dtype))
    if autotune is None:
        autotune = not interpret
    if use_pallas and (block_t is None or block_o is None
                       or block_f is None):
        if autotune and not interpret:
            from paddle_tpu.ops.pallas.autotune import decoder_block_sizes
            blocks = decoder_block_sizes(b, s, d, dq, dkv, hd, f,
                                         str(x.dtype))
        else:
            blocks = _default_decoder_blocks(s, d, dq, dkv, hd, f,
                                             str(x.dtype))
        if blocks is None:
            raise ValueError(
                f"no decoder block sizes fit the VMEM budget at "
                f"s={s} d={d} dkv={dkv} f={f}")
        block_t = block_t or blocks[0]
        block_o = block_o or blocks[1]
        block_f = block_f or blocks[2]
    if use_pallas and (s % block_t or dq % block_o or dkv % block_o
                       or d % block_o or f % block_f or block_o % hd):
        raise ValueError(
            f"shapes s={s} d={d} dq={dq} dkv={dkv} f={f} hd={hd} not "
            f"divisible by blocks ({block_t}, {block_o}, {block_f})")
    cos = jnp.asarray(rope_cos)[:s].astype(jnp.float32)
    sin = jnp.asarray(rope_sin)[:s].astype(jnp.float32)
    return _decoder_core(x, norm1_weight, wq, wk, wv, cos, sin, wo,
                         norm2_weight, wg, wu, wd, float(epsilon), nh,
                         nkvh, bool(use_pallas), bool(interpret),
                         int(block_t or 0), int(block_o or 0),
                         int(block_f or 0))


def fused_ffn(x, w1, w2, b1=None, b2=None, activation: str = "relu",
              block_t: int = None, block_f: int = None,
              interpret: bool = None, autotune: bool = None,
              use_pallas: bool = None):
    """``y = act(x @ w1 + b1) @ w2 + b2`` — the classic Transformer
    feed-forward, hidden intermediate VMEM-resident (non-gated variant
    of :func:`fused_mlp`).  ``b1``/``b2`` may be None."""
    if activation not in _ACT:
        raise ValueError(f"unsupported activation {activation!r}; "
                         f"expected one of {SUPPORTED_ACTS}")
    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)
    t = x2d.shape[0]
    f = int(w1.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = fused_mlp_eligible(t, d, f, x.dtype)
    if autotune is None:
        autotune = not interpret
    if use_pallas:
        block_t, block_f = _mlp_blocks(t, d, f, str(x.dtype), block_t,
                                       block_f, interpret, autotune)
    if b1 is None:
        b1 = jnp.zeros((f,), x2d.dtype)
    if b2 is None:
        b2 = jnp.zeros((int(w2.shape[-1]),), x2d.dtype)
    y = _ffn_core(x2d, w1, b1, w2, b2, str(activation),
                  bool(use_pallas), bool(interpret),
                  int(block_t or 0), int(block_f or 0))
    return y.reshape(shape)


# ---------------------------------------------------------------------------
# static verification (analysis/kernel_verify) — the fused kernels
# described as KernelSpecs so the Mosaic-legality model can check them
# without a chip.  The specs reuse the SAME index-map closures the
# pallas_calls install (_clamped/_clamped_out/_clamp3), so what the
# verifier sweeps is what Mosaic would see.
# ---------------------------------------------------------------------------

def _qkv_verify_spec(t, d, dq, dk, dv, bt, bo, dtype, residuals=True):
    from paddle_tpu.analysis import kernel_verify as kv
    nt = t // bt if bt else 0
    nq, nkb, nvb = dq // bo, dk // bo, dv // bo
    args = [
        kv.ArgSpec("x", (t, d), (bt, d), lambda i, j: (i, 0), dtype),
        kv.ArgSpec("wn", (1, d), (1, d), lambda i, j: (0, 0), dtype,
                   resident=True),
        kv.ArgSpec("wq", (d, dq), (d, bo), _clamped(0, nq), dtype,
                   dma_once=True),
        kv.ArgSpec("wk", (d, dk), (d, bo), _clamped(nq, nkb), dtype,
                   dma_once=True),
        kv.ArgSpec("wv", (d, dv), (d, bo), _clamped(nq + nkb, nvb), dtype,
                   dma_once=True),
        kv.ArgSpec("q", (t, dq), (bt, bo), _clamped_out(0, nq), dtype,
                   is_output=True),
        kv.ArgSpec("k", (t, dk), (bt, bo), _clamped_out(nq, nkb), dtype,
                   is_output=True),
        kv.ArgSpec("v", (t, dv), (bt, bo), _clamped_out(nq + nkb, nvb),
                   dtype, is_output=True),
    ]
    if residuals:
        args += [
            kv.ArgSpec("xn", (t, d), (bt, d), lambda i, j: (i, 0), dtype,
                       is_output=True),
            kv.ArgSpec("inv", (t, 1), (bt, 1), lambda i, j: (i, 0),
                       "float32", is_output=True),
        ]
    return kv.KernelSpec(
        name="fused_qkv", grid=(nt, nq + nkb + nvb), args=args,
        scratch=[kv.ScratchSpec("xn_scr", (bt, d), "float32")],
        dimension_semantics=("parallel", "arbitrary"),
        needs_fp32_acc=True,
        where=f"fused_qkv[t={t} d={d} dq={dq} dk={dk} dv={dv} "
              f"bt={bt} bo={bo} {dtype}]")


def verify_static_qkv(t, d, dq, dk, dv, dtype="float32", block_t=None,
                      block_o=None, residuals=True):
    """Static Mosaic-legality findings for the fused rmsnorm+QKV kernel
    at this shape/config (defaults = the heuristic blocks)."""
    from paddle_tpu.analysis import kernel_verify as kv
    if block_t is None or block_o is None:
        bt, bo = _default_qkv_blocks(t, d, dq, dk, dv, str(dtype))
        block_t = block_t or bt
        block_o = block_o or bo
    spec = _qkv_verify_spec(t, d, dq, dk, dv, int(block_t), int(block_o),
                            str(dtype), residuals=residuals)
    return kv.verify_kernel(spec)


def _mlp_verify_spec(t, d, f, bt, bf, dtype, gated=True):
    from paddle_tpu.analysis import kernel_verify as kv
    nt = t // bt if bt else 0
    nf = f // bf if bf else 0
    args = [
        kv.ArgSpec("x", (t, d), (bt, d), lambda i, j: (i, 0), dtype),
    ]
    wnames = ("wg", "wu") if gated else ("w1",)
    for w in wnames:
        args.append(kv.ArgSpec(w, (d, f), (d, bf),
                               lambda i, j: (0, j), dtype, dma_once=True))
    args.append(kv.ArgSpec("wd", (f, d), (bf, d),
                           lambda i, j: (j, 0), dtype, dma_once=True))
    args.append(kv.ArgSpec("y", (t, d), (bt, d), lambda i, j: (i, 0),
                           dtype, is_output=True))
    return kv.KernelSpec(
        name="fused_mlp" if gated else "fused_ffn",
        grid=(nt, nf), args=args,
        scratch=[kv.ScratchSpec("acc", (bt, d), "float32")],
        dimension_semantics=("parallel", "arbitrary"),
        needs_fp32_acc=True,
        where=f"fused_mlp[t={t} d={d} f={f} bt={bt} bf={bf} {dtype}]")


def verify_static_mlp(t, d, f, dtype="float32", block_t=None,
                      block_f=None, gated=True):
    """Static Mosaic-legality findings for the fused MLP/FFN kernel at
    this shape/config (defaults = the heuristic blocks)."""
    from paddle_tpu.analysis import kernel_verify as kv
    if block_t is None or block_f is None:
        bt, bf = _default_mlp_blocks(t, d, f, str(dtype))
        block_t = block_t or bt
        block_f = block_f or bf
    spec = _mlp_verify_spec(t, d, f, int(block_t), int(block_f),
                            str(dtype), gated=gated)
    return kv.verify_kernel(spec)


def _decoder_verify_spec(b, s, d, dq, dkv, hd, f, bt, bo, bf, dtype):
    from paddle_tpu.analysis import kernel_verify as kv
    dtype = str(dtype)
    nh, nkvh = dq // hd, dkv // hd
    nt = s // bt if bt else 0
    nqc, nkc = dq // bo, dkv // bo
    no, nf = d // bo, f // bf
    B0 = nqc + nkc
    C0 = B0 + nh * nt
    D0 = C0 + no
    inner = D0 + nf
    hh = hd // 2
    args = [
        kv.ArgSpec("x", (b, s, d), (1, bt, d),
                   lambda bi, i, j: (bi, i, 0), dtype),
        kv.ArgSpec("wn1", (1, d), (1, d), lambda bi, i, j: (0, 0), dtype,
                   resident=True),
        kv.ArgSpec("wq", (d, dq), (d, bo), _clamp3(0, nqc), dtype,
                   dma_once=True),
        kv.ArgSpec("wk", (d, dkv), (d, bo), _clamp3(nqc, nkc), dtype,
                   dma_once=True),
        kv.ArgSpec("wv", (d, dkv), (d, bo), _clamp3(nqc, nkc), dtype,
                   dma_once=True),
        kv.ArgSpec("cos", (s, hh), (bt, hh),
                   lambda bi, i, j: (i, 0), "float32"),
        kv.ArgSpec("sin", (s, hh), (bt, hh),
                   lambda bi, i, j: (i, 0), "float32"),
        kv.ArgSpec("wo", (dq, d), (dq, bo), _clamp3(C0, no), dtype,
                   dma_once=True),
        kv.ArgSpec("wn2", (1, d), (1, d), lambda bi, i, j: (0, 0), dtype,
                   resident=True),
        kv.ArgSpec("wg", (d, f), (d, bf), _clamp3(D0, nf), dtype,
                   dma_once=True),
        kv.ArgSpec("wu", (d, f), (d, bf), _clamp3(D0, nf), dtype,
                   dma_once=True),
        kv.ArgSpec("wd", (f, d), (bf, d),
                   lambda bi, i, j: (jnp.clip(j - D0, 0, nf - 1), 0),
                   dtype, dma_once=True),
        kv.ArgSpec("y", (b, s, d), (1, bt, d),
                   lambda bi, i, j: (bi, i, 0), dtype, is_output=True),
    ]
    kv_note = (f"K/V rows for the WHOLE sequence stay VMEM-resident "
               f"(s={s}, dkv={dkv})")
    scratch = [
        kv.ScratchSpec("xn", (bt, d), dtype),
        kv.ScratchSpec("q", (bt, dq), dtype),
        kv.ScratchSpec("k_seq", (s, dkv), dtype, seq_scaling=True,
                       note=kv_note),
        kv.ScratchSpec("v_seq", (s, dkv), dtype, seq_scaling=True,
                       note=kv_note),
        kv.ScratchSpec("attn", (bt, dq), dtype),
        kv.ScratchSpec("x2", (bt, d), dtype),
        kv.ScratchSpec("m", (bt, 1), "float32"),
        kv.ScratchSpec("l", (bt, 1), "float32"),
        kv.ScratchSpec("acc", (bt, hd), "float32"),
        kv.ScratchSpec("yacc", (bt, d), "float32"),
    ]
    return kv.KernelSpec(
        name="fused_decoder", grid=(b, nt, inner), args=args,
        scratch=scratch,
        dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        vmem_budget=_DECODER_VMEM_BUDGET,
        needs_fp32_acc=True,
        lane_concat=(
            f"in-kernel RoPE concatenates rotated half-heads and "
            f"{bo // hd} head slice(s) along the last axis of a "
            f"[{bt}, {bo}] block (hd={hd})"),
        where=f"fused_decoder[b={b} s={s} d={d} dq={dq} dkv={dkv} "
              f"f={f} bt={bt} bo={bo} bf={bf} {dtype}]")


def verify_static_decoder(b, s, d, dq, dkv, hd, f, dtype="float32",
                          block_t=None, block_o=None, block_f=None):
    """Static Mosaic-legality findings for the whole-decoder-block
    megakernel at this shape/config.  Surfaces the two named Mosaic
    risks as WARNINGs (lane-axis RoPE concat, seq-scaling K/V scratch)
    and errors when no block choice fits the VMEM budget."""
    from paddle_tpu.analysis import kernel_verify as kv
    dtype = str(dtype)
    if block_t is None or block_o is None or block_f is None:
        blocks = _default_decoder_blocks(s, d, dq, dkv, hd, f, dtype)
        if blocks is None:
            diags = [kv._d(
                kv.Severity.ERROR, kv.VMEM_EXCEEDED,
                f"fused_decoder: no (block_t, block_o, block_f) choice "
                f"fits the {_DECODER_VMEM_BUDGET >> 20} MiB budget at "
                f"s={s} d={d} dkv={dkv} f={f} ({dtype})",
                where=f"fused_decoder[b={b} s={s} d={d} {dtype}]",
                hint="the 2*s*dkv K/V scratch dominates; shorten the "
                     "sequence or fall back to the per-segment kernels")]
            kv._record("fused_decoder", kv.verdict_of(diags))
            return diags
        block_t = block_t or blocks[0]
        block_o = block_o or blocks[1]
        block_f = block_f or blocks[2]
    spec = _decoder_verify_spec(b, s, d, dq, dkv, hd, f, int(block_t),
                                int(block_o), int(block_f), dtype)
    return kv.verify_kernel(spec)
