"""Fused softmax-cross-entropy — Pallas TPU kernel, vocab-blockwise.

Reference parity: the fused softmax_with_cross_entropy kernels
(phi/kernels/fusion, c_softmax_with_cross_entropy) — the op the memory
roofline says dominates the tail of an LM train step when left to XLA:
``log_softmax`` materializes a full fp32 ``[tokens, vocab]`` array in HBM
and the one-hot backward reads it again.  Here neither survives:

* forward: vocab blocks stream HBM→VMEM; an online max/logsumexp (the
  flash-attention trick applied along the class axis) and the gathered
  gold logit live in VMEM scratch as ``[block_t, 1]`` fp32 columns.  Only
  the per-token loss and logsumexp (``[T, 1]`` each) are written back.
* backward: embarrassingly parallel over (token, vocab) blocks — each
  block recomputes its probabilities from the saved logsumexp and writes
  ``(p - onehot) * g`` straight in the input dtype.  The only
  ``[T, V]``-sized arrays in the whole fwd+bwd are the caller's logits
  and their cotangent, both in the caller's dtype (bf16 in training).

Distinct from ``F.fused_linear_cross_entropy`` (which fuses the lm-head
matmul and re-materializes logits chunkwise): this kernel takes logits
that already exist and removes the fp32 softmax intermediate — it is the
automatic fast path under plain ``F.cross_entropy``.

Mosaic legality (see flash_attention.py): per-token columns ride as
``[T, 1]`` arrays with ``(block_t, 1)`` blocks — trailing dims
(multiple-of-8, 1) match the array, same shape trick the fused rmsnorm
uses for its inverse-rms output.

Env knobs:
  PADDLE_TPU_FUSED_CE=1|0   force-enable (interpret off-TPU) / disable;
                            unset = auto (TPU backend only)
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["fused_softmax_cross_entropy", "fused_ce_enabled",
           "fused_ce_eligible"]

_NEG_INF = -1e30


def fused_ce_enabled() -> bool:
    """Routing gate: env wins, else auto = TPU backend only (interpret
    mode off-TPU is for tests, not the hot path)."""
    env = os.environ.get("PADDLE_TPU_FUSED_CE", "").strip().lower()
    if env in ("0", "false", "off", "no"):
        return False
    if env in ("1", "true", "on", "yes"):
        return True
    return jax.default_backend() == "tpu"


def fused_ce_eligible(t: int, v: int) -> bool:
    """Shape gate: the vocab axis must tile the 128-lane VPU; tokens pad
    to the row block inside the wrapper, so any T works."""
    return v >= 128 and v % 128 == 0 and t >= 1


# -- forward -----------------------------------------------------------------

def _fwd_kernel(x_ref, lbl_ref, loss_ref, lse_ref, m_ref, s_ref, gold_ref,
                *, block_v):
    """Grid: (token_blocks, vocab_blocks); the vocab axis is innermost
    (sequential) so VMEM scratch carries the online-softmax state."""
    vj = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        s_ref[:] = jnp.zeros_like(s_ref)
        gold_ref[:] = jnp.zeros_like(gold_ref)

    x = x_ref[:].astype(jnp.float32)                   # [bt, bv]
    bt = x.shape[0]
    col = vj * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (bt, block_v), 1)
    m_prev = m_ref[:]                                  # [bt, 1]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=1, keepdims=True))
    s_ref[:] = s_ref[:] * jnp.exp(m_prev - m_new) + \
        jnp.sum(jnp.exp(x - m_new), axis=1, keepdims=True)
    m_ref[:] = m_new
    hit = col == lbl_ref[:]                            # [bt, bv]
    gold_ref[:] += jnp.sum(jnp.where(hit, x, 0.0), axis=1, keepdims=True)

    @pl.when(vj == nv - 1)
    def _finalize():
        lse = m_ref[:] + jnp.log(s_ref[:])
        lse_ref[:] = lse
        loss_ref[:] = lse - gold_ref[:]


def _fwd_pallas(x, lbl_col, *, block_t, block_v, interpret):
    """x: [T, V]; lbl_col: [T, 1] int32 → (loss [T, 1], lse [T, 1]) fp32."""
    t, v = x.shape
    nt = t // block_t
    nv = v // block_v

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    return pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v),
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda i, j: (i, j)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, 1), jnp.float32),
            jax.ShapeDtypeStruct((t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(x, lbl_col)


# -- backward ----------------------------------------------------------------

def _bwd_kernel(x_ref, lbl_ref, lse_ref, g_ref, dx_ref, *, block_v):
    """Grid: (token_blocks, vocab_blocks), fully parallel — each block is
    self-contained given the saved logsumexp."""
    vj = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)                   # [bt, bv]
    bt = x.shape[0]
    p = jnp.exp(x - lse_ref[:])
    col = vj * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (bt, block_v), 1)
    onehot = (col == lbl_ref[:]).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * g_ref[:]).astype(dx_ref.dtype)


def _bwd_pallas(x, lbl_col, lse, g_col, *, block_t, block_v, interpret):
    t, v = x.shape
    nt = t // block_t
    nv = v // block_v

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"))

    return pl.pallas_call(
        functools.partial(_bwd_kernel, block_v=block_v),
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda i, j: (i, j)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_v), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((t, v), x.dtype),
        interpret=interpret,
        **params,
    )(x, lbl_col, lse, g_col)


# -- differentiable core -----------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _ce_core(x, lbl_col, block_t, block_v, interpret):
    loss, _ = _fwd_pallas(x, lbl_col, block_t=block_t, block_v=block_v,
                          interpret=interpret)
    return loss[:, 0]


def _ce_core_fwd(x, lbl_col, block_t, block_v, interpret):
    loss, lse = _fwd_pallas(x, lbl_col, block_t=block_t, block_v=block_v,
                            interpret=interpret)
    return loss[:, 0], (x, lbl_col, lse)


def _ce_core_bwd(block_t, block_v, interpret, res, g):
    x, lbl_col, lse = res
    dx = _bwd_pallas(x, lbl_col, lse, g.astype(jnp.float32)[:, None],
                     block_t=block_t, block_v=block_v, interpret=interpret)
    return dx, None


_ce_core.defvjp(_ce_core_fwd, _ce_core_bwd)


def _default_blocks(t: int, v: int):
    """Heuristic fallback: biggest lane block that divides the vocab
    (more vocab per visit = fewer scratch rescales), 128 token rows."""
    block_v = 128
    for cand in (2048, 1024, 512, 256, 128):
        if v % cand == 0:
            block_v = cand
            break
    if v % block_v:
        # odd vocab (no power-of-two divisor >= 128): a non-dividing
        # block would leave uncovered columns — fall back to one whole-
        # vocab block (the verifier's coverage check catches regressions)
        block_v = v
    block_t = 128 if t >= 128 else max(8, -(-t // 8) * 8)
    return block_t, block_v


def fused_softmax_cross_entropy(logits, labels, block_t=None, block_v=None,
                                interpret=None, autotune=None):
    """Per-token ``-log_softmax(logits)[labels]`` without the ``[T, V]``
    fp32 intermediate.

    logits: [T, V] (flatten leading dims first; any float dtype — softmax
    math is fp32 per block); labels: [T] int, all in ``[0, V)`` (mask
    ignore_index to a safe class BEFORE calling; the cotangent you zero
    outside also zeroes the row's dlogits).  Returns fp32 [T].
    Differentiable wrt logits.
    """
    t, v = logits.shape
    if not fused_ce_eligible(t, v):
        raise ValueError(f"vocab {v} must be a multiple of 128")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if autotune is None:
        autotune = not interpret
    if block_t is None or block_v is None:
        if autotune and not interpret:
            from paddle_tpu.ops.pallas.autotune import ce_block_sizes
            bt_t, bv_t = ce_block_sizes(t, v, str(logits.dtype))
            block_t = block_t or bt_t
            block_v = block_v or bv_t
        else:
            bt_d, bv_d = _default_blocks(t, v)
            block_t = block_t or bt_d
            block_v = block_v or bv_d
    if v % block_v:
        raise ValueError(f"vocab {v} not divisible by block_v {block_v}")

    lbl = jnp.asarray(labels).astype(jnp.int32)
    # pad the token axis up to the row block; the pad/slice pair is
    # outside the custom vjp, so pad-row cotangents are exactly zero
    tp = -(-t // block_t) * block_t
    x = logits
    if tp != t:
        x = jnp.pad(x, ((0, tp - t), (0, 0)))
        lbl = jnp.pad(lbl, (0, tp - t))
    per_tok = _ce_core(x, lbl[:, None], int(block_t), int(block_v),
                       bool(interpret))
    return per_tok[:t]


# ---------------------------------------------------------------------------
# static verification (analysis/kernel_verify)


def _fwd_verify_spec(tp, v, bt, bv, dtype):
    from paddle_tpu.analysis import kernel_verify as kv
    nt, nv = tp // bt, v // bv
    col = lambda i, j: (i, 0)
    return kv.KernelSpec(
        name="fused_ce_fwd", grid=(nt, nv),
        args=[
            kv.ArgSpec("x", (tp, v), (bt, bv), lambda i, j: (i, j), dtype),
            kv.ArgSpec("lbl", (tp, 1), (bt, 1), col, "int32"),
            kv.ArgSpec("loss", (tp, 1), (bt, 1), col, "float32",
                       is_output=True),
            kv.ArgSpec("lse", (tp, 1), (bt, 1), col, "float32",
                       is_output=True),
        ],
        scratch=[kv.ScratchSpec("m", (bt, 1), "float32"),
                 kv.ScratchSpec("s", (bt, 1), "float32"),
                 kv.ScratchSpec("gold", (bt, 1), "float32")],
        dimension_semantics=("parallel", "arbitrary"),
        needs_fp32_acc=True,
        where=f"fused_ce_fwd[t={tp} v={v} bt={bt} bv={bv} {dtype}]")


def _bwd_verify_spec(tp, v, bt, bv, dtype):
    from paddle_tpu.analysis import kernel_verify as kv
    nt, nv = tp // bt, v // bv
    col = lambda i, j: (i, 0)
    return kv.KernelSpec(
        name="fused_ce_bwd", grid=(nt, nv),
        args=[
            kv.ArgSpec("x", (tp, v), (bt, bv), lambda i, j: (i, j), dtype),
            kv.ArgSpec("lbl", (tp, 1), (bt, 1), col, "int32"),
            kv.ArgSpec("lse", (tp, 1), (bt, 1), col, "float32"),
            kv.ArgSpec("g", (tp, 1), (bt, 1), col, "float32"),
            kv.ArgSpec("dx", (tp, v), (bt, bv), lambda i, j: (i, j),
                       dtype, is_output=True),
        ],
        dimension_semantics=("parallel", "parallel"),
        where=f"fused_ce_bwd[t={tp} v={v} bt={bt} bv={bv} {dtype}]")


def verify_static(t, v, dtype="float32", block_t=None, block_v=None):
    """Static Mosaic-legality findings for the fused cross-entropy
    (fwd + bwd pallas_calls) at this shape/config.  The token axis pads
    to the row block exactly like the wrapper does."""
    from paddle_tpu.analysis import kernel_verify as kv
    dtype = str(dtype)
    if block_t is None or block_v is None:
        bt_d, bv_d = _default_blocks(t, v)
        block_t = block_t or bt_d
        block_v = block_v or bv_d
    bt, bv = int(block_t), int(block_v)
    tp = -(-t // bt) * bt
    return (kv.verify_kernel(_fwd_verify_spec(tp, v, bt, bv, dtype))
            + kv.verify_kernel(_bwd_verify_spec(tp, v, bt, bv, dtype)))
