"""Grouped expert-matmul — all E experts' FFNs in one Pallas call.

The MoE tentpole (ROADMAP item 1): every dispatch path in
``distributed/moe.py`` funnels expert compute through a stacked-weight
FFN over ``[G, C, d]`` capacity-grouped token blocks (G groups, each
bound to expert ``g // (G // E)``; the einsum/index paths have G == E,
the all_to_all paths G == E_loc * n_shards source chunks).  Upstream
Paddle loops experts through gather/scatter collectives; the dense
einsum pair here already beats that, but it still spends full
``[E, C, d]`` HBM traffic on padding rows and re-reads activations
between the up- and down-projection.  This kernel runs the whole
grouped FFN as ONE ``pallas_call``:

* grid ``(G, C/block_c, h/block_f)`` with the hidden (f) axis innermost
  — only a ``[block_c, block_f]`` tile of the hidden activations ever
  exists, folded into an fp32 VMEM accumulator (the fused-MLP
  discipline, fused_block.py);
* per-group valid-row counts ride along as a ``[G, 1, 1]`` int32
  operand and ``pl.when`` skips capacity blocks with no routed tokens —
  under GShard capacity factors most tail blocks are empty, so skipped
  blocks cost neither MXU flops nor the w1/w2 HBM reads their grid
  steps would re-issue;
* rows past a group's count are zeroed (their combine weights are zero
  in every dispatch path, so MoE outputs are unchanged), which makes
  the kernel's semantics block-size independent and gives the jnp
  reference an exact contract to oracle against;
* custom VJP: backward is the plain-JAX masked einsum chain (the
  ``_bwd_blockwise`` idiom), with a ``float0`` cotangent for counts.

Routing is trace-time and OFF by default: ``PADDLE_TPU_GROUPED_MOE=1``
flips ``_expert_ffn`` to this kernel (interpret mode off-TPU); unset or
0 keeps the dense einsum pair with a byte-identical jaxpr (regression-
tested).  Block sizes are one more autotune-v2 axis
(``autotune.grouped_block_sizes``) and the static Mosaic-legality spec
is in the kernel-verify catalog via :func:`verify_static`.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["grouped_expert_ffn", "grouped_expert_ffn_pallas",
           "grouped_expert_ffn_reference", "grouped_moe_enabled",
           "grouped_ffn_eligible", "record_path"]


def grouped_moe_enabled() -> bool:
    """``PADDLE_TPU_GROUPED_MOE=1`` routes stacked-expert FFNs through
    the grouped Pallas kernel; unset/0 keeps the dense einsum pair (and
    its exact jaxpr)."""
    raw = os.environ.get("PADDLE_TPU_GROUPED_MOE")
    return raw is not None and raw.strip().lower() in ("1", "true", "yes",
                                                       "on")


def grouped_ffn_eligible(G: int, C: int, d: int, h: int, E: int) -> bool:
    """Structural + (on TPU) alignment gate for the grouped kernel.
    Off-TPU the kernel runs in interpret mode, where Mosaic tiling does
    not constrain shapes."""
    if E <= 0 or G % E:
        return False
    if jax.default_backend() != "tpu":
        return True
    return d % 128 == 0 and h % 128 == 0 and C >= 8


def record_path(path: str):
    """Trace-time implementation counter — the grouped-MoE analog of the
    quant/fused-block path counters."""
    try:
        from paddle_tpu.observability import default_registry
        default_registry().counter(
            "paddle_tpu_grouped_moe_path_total",
            "grouped expert-FFN implementation chosen at trace time",
            labelnames=("path",)).labels(path=path).inc()
    except Exception:  # pragma: no cover - telemetry must never trace-fail
        pass


def _default_grouped_blocks(C: int, d: int, h: int, dtype):
    """Heuristic (block_c, block_f) when the autotune cache is cold:
    widest hidden block, then the tallest capacity block whose working
    set (x/y/acc + double-buffered w1/w2 tiles) stays under ~10 MB of
    VMEM.  Degenerate dims fall back to spanning blocks (always
    Mosaic-legal: a block equal to the array dim needs no tiling)."""
    s = str(dtype)
    itemsize = 2 if ("bfloat16" in s or "float16" in s) else 4
    quantum = 16 if itemsize == 2 else 8
    bcs = [c for c in (512, 256, 128, 64, 32, 16, 8)
           if c % quantum == 0 and C % c == 0 and C >= c]
    if not bcs:
        bcs = [C]                       # spanning block — no sublane tiling
    bfs = [f for f in (512, 256, 128) if h % f == 0]
    if not bfs:
        bfs = [h]
    for bf in bfs:
        for bc in bcs:
            vmem = (2 * bc * d * itemsize        # x, double-buffered
                    + bc * d * 4                 # fp32 accumulator
                    + 2 * bc * d * itemsize      # y, double-buffered
                    + 4 * d * bf * itemsize)     # w1 + w2 tiles, 2x
            if vmem < 10 * (1 << 20):
                return bc, bf
    return bcs[-1], bfs[-1]


def _grouped_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, cnt_ref, o_ref,
                    acc_ref, *, act, block_c):
    """One (group, capacity, hidden) tile.  The hidden axis is the
    innermost (sequential) grid dim; the fp32 accumulator in VMEM folds
    each ``[block_c, block_f]`` hidden tile into the down-projection.
    Capacity blocks past the group's routed-token count are skipped
    entirely (no MXU work, zeros written at finalize)."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    nf = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    cnt = cnt_ref[0, 0, 0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_c, 1), 0) \
        + i * block_c
    valid = rows < cnt

    @pl.when(i * block_c < cnt)
    def _compute():
        xb = x_ref[0]                                    # [bc, d]
        u = jax.lax.dot_general(
            xb, w1_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bc, bf]
        u = u + b1_ref[0].astype(jnp.float32)
        hb = jnp.where(valid, act(u), 0.0)               # mask pad rows
        acc_ref[:] += jax.lax.dot_general(
            hb.astype(x_ref.dtype), w2_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bc, d]

    @pl.when(j == nf - 1)
    def _finalize():
        out = acc_ref[:] + b2_ref[0].astype(jnp.float32)
        o_ref[0] = jnp.where(valid, out, 0.0).astype(o_ref.dtype)


def grouped_expert_ffn_pallas(x, w1, b1, w2, b2, counts, *, act,
                              block_c, block_f, interpret):
    """``[G, C, d] -> [G, C, d]`` grouped FFN via the Pallas kernel.
    ``counts [G]`` int32 bounds each group's valid-row prefix; rows past
    it come back exactly zero."""
    G, C, d = x.shape
    E, _, h = w1.shape
    rep = G // E
    nc = C // block_c
    nf = h // block_f

    params = {}
    if _HAVE_TPU_PL and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    return pl.pallas_call(
        functools.partial(_grouped_kernel, act=act, block_c=block_c),
        grid=(G, nc, nf),
        in_specs=[
            pl.BlockSpec((1, block_c, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, d, block_f), lambda g, i, j: (g // rep, 0, j)),
            pl.BlockSpec((1, 1, block_f), lambda g, i, j: (g // rep, 0, j)),
            pl.BlockSpec((1, block_f, d), lambda g, i, j: (g // rep, j, 0)),
            pl.BlockSpec((1, 1, d), lambda g, i, j: (g // rep, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda g, i, j: (g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_c, d), lambda g, i, j: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((G, C, d), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_c, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(x, w1, b1.reshape(E, 1, h), w2, b2.reshape(E, 1, d),
      counts.reshape(G, 1, 1))


def grouped_expert_ffn_reference(x, w1, b1, w2, b2, counts=None, *,
                                 act=None):
    """The jnp oracle: same op order as the kernel (fp32 MXU
    accumulation, activation in fp32, one cast between the projections)
    with rows past ``counts`` zeroed — block-size independent, so the
    kernel must match it to blocked-accumulation noise."""
    act = act or jax.nn.gelu
    G, C, d = x.shape
    E, _, h = w1.shape
    rep = G // E
    xr = x.reshape(E, rep * C, d)
    u = jnp.einsum("ecd,edh->ech", xr, w1,
                   preferred_element_type=jnp.float32) + b1[:, None, :]
    hb = act(u).astype(x.dtype)
    y = jnp.einsum("ech,ehd->ecd", hb, w2,
                   preferred_element_type=jnp.float32) + b2[:, None, :]
    y = y.astype(x.dtype).reshape(G, C, d)
    if counts is not None:
        rows = jax.lax.broadcasted_iota(jnp.int32, (G, C), 1)
        y = jnp.where((rows < counts[:, None])[..., None], y, 0)
    return y


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _grouped_core(x, w1, b1, w2, b2, counts, act, block_c, block_f,
                  interpret):
    return _grouped_fwd(x, w1, b1, w2, b2, counts, act, block_c, block_f,
                        interpret)[0]


def _grouped_fwd(x, w1, b1, w2, b2, counts, act, block_c, block_f,
                 interpret):
    y = grouped_expert_ffn_pallas(x, w1, b1, w2, b2, counts, act=act,
                                  block_c=block_c, block_f=block_f,
                                  interpret=interpret)
    return y, (x, w1, b1, w2, b2, counts)


def _grouped_bwd(act, block_c, block_f, interpret, res, dy):
    # recompute the masked einsum chain in plain JAX (the flash
    # _bwd_blockwise idiom): rows past counts carry zero cotangent and
    # zero input, so padded slots contribute nothing to any grad
    x, w1, b1, w2, b2, counts = res
    G, C, d = x.shape
    E = w1.shape[0]
    rep = G // E
    rows = jax.lax.broadcasted_iota(jnp.int32, (G, C), 1)
    valid = (rows < counts[:, None])[..., None]
    xm = jnp.where(valid, x, 0).reshape(E, rep * C, d)
    gy = jnp.where(valid, dy, 0).reshape(E, rep * C, d)
    u = jnp.einsum("ecd,edh->ech", xm, w1,
                   preferred_element_type=jnp.float32) + b1[:, None, :]
    s, act_vjp = jax.vjp(act, u)
    dh = jnp.einsum("ecd,ehd->ech", gy, w2,
                    preferred_element_type=jnp.float32)
    dw2 = jnp.einsum("ech,ecd->ehd", s.astype(x.dtype), gy,
                     preferred_element_type=jnp.float32).astype(w2.dtype)
    db2 = gy.astype(jnp.float32).sum(axis=1).astype(b2.dtype)
    du = act_vjp(dh)[0]
    dw1 = jnp.einsum("ecd,ech->edh", xm, du.astype(x.dtype),
                     preferred_element_type=jnp.float32).astype(w1.dtype)
    db1 = du.sum(axis=1).astype(b1.dtype)
    dx = jnp.einsum("ech,edh->ecd", du.astype(x.dtype), w1,
                    preferred_element_type=jnp.float32)
    dx = dx.reshape(G, C, d).astype(x.dtype)
    dcounts = np.zeros(counts.shape, dtype=jax.dtypes.float0)
    return dx, dw1, db1, dw2, db2, dcounts


_grouped_core.defvjp(_grouped_fwd, _grouped_bwd)


def grouped_expert_ffn(x, w1, b1, w2, b2, *, counts=None, act=None,
                       block_c=None, block_f=None, interpret=None,
                       autotune=True):
    """Grouped expert FFN with trace-time block selection.

    ``x``: ``[G, C, d]`` capacity-grouped tokens (group ``g`` belongs
    to expert ``g // (G // E)``); ``w1/b1/w2/b2``: stacked
    ``[E, d, h] / [E, h] / [E, h, d] / [E, d]`` expert weights;
    ``counts``: optional ``[G]`` int32 valid-row prefix per group (rows
    past it return exactly zero — their combine weights are zero in
    every MoE dispatch path).  Differentiable in x and the weights.
    """
    act = act or jax.nn.gelu
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    G, C, d = x.shape
    E, _, h = w1.shape
    if G % E:
        raise ValueError(f"group count {G} not divisible by experts {E}")
    if block_c is None or block_f is None:
        if autotune and not interpret:
            from paddle_tpu.ops.pallas.autotune import grouped_block_sizes
            bc, bf = grouped_block_sizes(G, C, d, h, str(x.dtype))
        else:
            bc, bf = _default_grouped_blocks(C, d, h, str(x.dtype))
        block_c = block_c or bc
        block_f = block_f or bf
    if C % block_c or h % block_f:
        block_c, block_f = _default_grouped_blocks(C, d, h, str(x.dtype))
    if counts is None:
        counts = jnp.full((G,), C, jnp.int32)
    return _grouped_core(x, w1, b1, w2, b2, counts.astype(jnp.int32),
                         act, int(block_c), int(block_f), bool(interpret))


# ---------------------------------------------------------------------------
# static verification (analysis/kernel_verify)


def verify_static(G, C, d, h, E=None, dtype="bfloat16", block_c=None,
                  block_f=None):
    """Static Mosaic-legality findings for the grouped expert-matmul at
    this shape/config — the counts operand travels as ``[G, 1, 1]`` with
    ``(1, 1, 1)`` blocks (trailing dims span the array, so no sublane
    tiling applies; the flash-lse layout trick)."""
    from paddle_tpu.analysis import kernel_verify as kv
    dtype = str(dtype)
    E = int(E or G)
    rep = max(1, G // E)
    if block_c is None or block_f is None:
        bc_d, bf_d = _default_grouped_blocks(C, d, h, dtype)
        block_c = block_c or bc_d
        block_f = block_f or bf_d
    bc, bf = int(block_c), int(block_f)
    spec = kv.KernelSpec(
        name="grouped_matmul",
        grid=(G, C // bc if bc else 0, h // bf if bf else 0),
        args=[
            kv.ArgSpec("x", (G, C, d), (1, bc, d),
                       lambda g, i, j: (g, i, 0), dtype),
            kv.ArgSpec("w1", (E, d, h), (1, d, bf),
                       lambda g, i, j: (g // rep, 0, j), dtype,
                       dma_once=True),
            kv.ArgSpec("b1", (E, 1, h), (1, 1, bf),
                       lambda g, i, j: (g // rep, 0, j), dtype),
            kv.ArgSpec("w2", (E, h, d), (1, bf, d),
                       lambda g, i, j: (g // rep, j, 0), dtype,
                       dma_once=True),
            kv.ArgSpec("b2", (E, 1, d), (1, 1, d),
                       lambda g, i, j: (g // rep, 0, 0), dtype),
            kv.ArgSpec("counts", (G, 1, 1), (1, 1, 1),
                       lambda g, i, j: (g, 0, 0), "int32"),
            kv.ArgSpec("o", (G, C, d), (1, bc, d),
                       lambda g, i, j: (g, i, 0), dtype, is_output=True),
        ],
        scratch=[kv.ScratchSpec("acc", (bc, d), "float32")],
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        needs_fp32_acc=True,
        where=f"grouped_matmul[G={G} C={C} d={d} h={h} E={E} "
              f"bc={bc} bf={bf} {dtype}]")
    return kv.verify_kernel(spec)
