"""Pallas TPU kernels — the hot-op layer.

Where the reference ships hand-written CUDA (fused_attention_op.cu,
flash_attn kernels, fused_multi_transformer_op.cu — SURVEY.md §2.2), this
package holds the TPU equivalents as Pallas kernels.  Everything else is
left to XLA fusion on purpose: only ops where blockwise scheduling beats
the compiler get a kernel.
"""
