"""Fused residual-add + RMSNorm — Pallas TPU kernel.

Reference parity: the fused norm ops the reference keeps in its fusion
layer (``fused_bias_residual_layernorm``, ``rms_norm`` under
paddle/phi/kernels/fusion/gpu) — one HBM round-trip for what XLA would
otherwise schedule as add → square → reduce → rsqrt → mul → mul chains
with the residual re-read.

Design: rows stream HBM→VMEM in (block_rows, d) tiles; the row-wise mean
square, rsqrt, scale and the residual sum all happen in one VMEM pass in
fp32; the kernel emits BOTH the normalized output and the residual sum
(the value the next block needs) plus the per-row inverse rms for the
backward.  Backward is plain jax (pure elementwise + a row reduction —
XLA fuses it into neighbors; the win here is the forward's memory
traffic).

Falls back to pure jax when the shape can't tile (d % 128, rows % 8) so
the API is total.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["fused_rmsnorm"]


def _fwd_kernel(x_ref, res_ref, w_ref, y_ref, h_ref, inv_ref, *, eps,
                has_res):
    x = x_ref[:].astype(jnp.float32)
    if has_res:
        x = x + res_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)                      # [br, 1]
    y = (x * inv) * w_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    h_ref[:] = x.astype(h_ref.dtype)
    inv_ref[:] = inv


def _fwd_pallas(x2d, res2d, w, *, eps, block_rows, interpret):
    rows, d = x2d.shape
    nr = rows // block_rows
    has_res = res2d is not None
    kernel = functools.partial(_fwd_kernel, eps=eps, has_res=has_res)

    in_specs = [pl.BlockSpec((block_rows, d), lambda i: (i, 0))]
    args = [x2d]
    if has_res:
        in_specs.append(pl.BlockSpec((block_rows, d), lambda i: (i, 0)))
        args.append(res2d)
    else:
        # keep the kernel signature uniform: alias x as the (unread) res
        in_specs.append(pl.BlockSpec((block_rows, d), lambda i: (i, 0)))
        args.append(x2d)
    in_specs.append(pl.BlockSpec((1, d), lambda i: (0, 0)))
    args.append(w.reshape(1, d))

    y, h, inv = pl.pallas_call(
        kernel,
        grid=(nr,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, d), x2d.dtype),
            jax.ShapeDtypeStruct((rows, d), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return y, h, inv


def _default_block_rows(rows, d, dtype):
    """Row-block heuristic.  VMEM budget: the block holds x, res, y, h
    (io dtype) plus ~3 fp32 working copies — keep it under ~8 MB."""
    import numpy as np
    per_row = d * (4 * np.dtype(dtype).itemsize + 3 * 4)
    budget = (8 << 20) // max(per_row, 1)
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if cand <= budget and rows % cand == 0:
            return cand
    return 8


def _ref_fwd(x2d, res2d, w, eps):
    h = x2d.astype(jnp.float32)
    if res2d is not None:
        h = h + res2d.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(h * h, axis=-1, keepdims=True) + eps)
    y = (h * inv) * w.astype(jnp.float32)
    return y.astype(x2d.dtype), h.astype(x2d.dtype), inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _core(x2d, res2d, w, eps, has_res, use_pallas, interpret):
    return _fwd(x2d, res2d, w, eps, has_res, use_pallas, interpret)[0]


def _fwd(x2d, res2d, w, eps, has_res, use_pallas, interpret):
    r = res2d if has_res else None
    if use_pallas:
        rows, d = x2d.shape
        block_rows = _default_block_rows(rows, d, x2d.dtype)
        y, h, inv = _fwd_pallas(x2d, r, w, eps=eps, block_rows=block_rows,
                                interpret=interpret)
    else:
        y, h, inv = _ref_fwd(x2d, r, w, eps)
    return (y, h), (h, inv, w)


def _bwd(eps, has_res, use_pallas, interpret, saved, cts):
    gy, gh_extra = cts                 # cotangents of (y, h)
    h, inv, w = saved
    hf = h.astype(jnp.float32)
    g = gy.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    d = hf.shape[-1]
    gw_row = g * wf                                        # [R, d]
    # dL/dh = inv * gw - h * inv^3 * mean(gw * h)
    dot = jnp.mean(gw_row * hf, axis=-1, keepdims=True)
    dh = inv * gw_row - hf * (inv ** 3) * dot
    if gh_extra is not None:
        dh = dh + gh_extra.astype(jnp.float32)
    dw = jnp.sum(g * hf * inv, axis=0).astype(w.dtype)
    dx = dh.astype(h.dtype)
    # no residual: res2d was an ALIAS of x2d (placeholder) — its cotangent
    # must be zero or the caller's x gradient double-counts
    dres = dx if has_res else jnp.zeros_like(dx)
    return dx, dres, dw


_core.defvjp(_fwd, _bwd)


def fused_rmsnorm(x, weight, residual=None, epsilon: float = 1e-5,
                  interpret: bool = None, use_pallas: bool = None):
    """y, h = fused_rmsnorm(x, w, residual): h = x (+ residual), y =
    RMSNorm(h) * w — one fused pass; ``h`` is the pre-norm sum the next
    residual branch consumes.

    x: [..., d]; weight: [d]; residual: same shape as x or None.
    """
    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)
    res2d = residual.reshape(-1, d) if residual is not None else None
    rows = x2d.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = (d % 128 == 0) and (rows % 8 == 0)
    has_res = residual is not None
    if not has_res:
        res2d = x2d  # unread placeholder keeps the vjp signature stable
    y, h = _core(x2d, res2d, weight, float(epsilon), has_res,
                 bool(use_pallas), bool(interpret))
    return y.reshape(shape), h.reshape(shape)


# ---------------------------------------------------------------------------
# static verification (analysis/kernel_verify)


def verify_static(rows, d, dtype="float32", block_rows=None,
                  residual=True):
    """Static Mosaic-legality findings for the fused rmsnorm forward at
    this shape/config (the residual-add variant by default — it is a
    superset of the plain one's operand list)."""
    from paddle_tpu.analysis import kernel_verify as kv
    dtype = str(dtype)
    br = int(block_rows or _default_block_rows(rows, d, dtype))
    row = lambda i: (i, 0)
    args = [
        kv.ArgSpec("x", (rows, d), (br, d), row, dtype),
        kv.ArgSpec("res", (rows, d), (br, d), row, dtype),
        kv.ArgSpec("w", (1, d), (1, d), lambda i: (0, 0), dtype,
                   resident=True),
        kv.ArgSpec("y", (rows, d), (br, d), row, dtype, is_output=True),
        kv.ArgSpec("h", (rows, d), (br, d), row, dtype, is_output=True),
        kv.ArgSpec("inv", (rows, 1), (br, 1), row, "float32",
                   is_output=True),
    ]
    if not residual:
        args = [a for a in args if a.name != "res"]
    spec = kv.KernelSpec(
        name="rmsnorm_fwd", grid=(rows // br,), args=args,
        dimension_semantics=("parallel",),
        where=f"rmsnorm_fwd[rows={rows} d={d} br={br} {dtype}]")
    return kv.verify_kernel(spec)
