"""Paged decode attention — Pallas TPU kernel over block-table KV pools.

The serving engine's paged KV cache (``inference/kv_cache.py``) stores
each sequence's keys/values as fixed-size token blocks scattered through
``[num_blocks, block_size, kv_heads, head_dim]`` pools, addressed by a
per-row block table.  The XLA fallback gathers the whole logical table
back to HBM-contiguous form every step — correct, but it re-materializes
``max_len`` rows per layer per token.  This kernel reads the pools
**in place**: the block table rides in as scalar prefetch
(``PrefetchScalarGridSpec``), the K/V ``BlockSpec`` index maps chase it
(``bt[b, j]`` picks the physical block each grid step DMAs), and an
online-softmax accumulator in VMEM scratch walks the sequence's logical
blocks.  Nothing is gathered; blocks past the row's length are skipped
entirely (``pl.when``), so decode reads exactly the live KV bytes.

GQA is handled in-kernel: q heads reshape to ``[kv_heads, group, hd]``
and both matmuls run batched over kv heads, so KV blocks stream once per
group (the same trick the flash kernel plays in its grid).

Eligibility mirrors the flash kernel's Mosaic constraints: TPU backend,
lane-aligned ``head_dim % 128 == 0``, sublane-aligned
``block_size % 8 == 0``.  Elsewhere the engine's ``jnp.take`` gather
fallback runs (``paddle_tpu_paged_attention_path_total{path=...}``
records the trace-time choice).  ``PADDLE_TPU_PAGED_ATTN=0`` forces the
fallback.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU use interpret mode
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_TPU_PL = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAVE_TPU_PL = False

__all__ = ["paged_decode_attention", "paged_decode_eligible",
           "paged_attention_env", "record_path"]

_NEG_INF = -1e30


def paged_attention_env():
    """``PADDLE_TPU_PAGED_ATTN``: 1 forces the Pallas kernel (still
    TPU-only), 0 forces the gather fallback, unset → auto (kernel when
    eligible)."""
    raw = os.environ.get("PADDLE_TPU_PAGED_ATTN")
    if raw is None:
        return None
    return raw.strip().lower() in ("1", "true", "yes", "on")


def paged_decode_eligible(head_dim: int, block_size: int, dtype) -> bool:
    """Trace-time routing decision for the decode (s == 1) path."""
    env = paged_attention_env()
    if env is False:
        return False
    if jax.default_backend() != "tpu" or not _HAVE_TPU_PL:
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    return head_dim % 128 == 0 and block_size % 8 == 0


def record_path(path: str):
    """Trace-time path counter (pallas | fallback) — BENCH trajectories
    attribute serving wins to the exact attention implementation."""
    try:
        from paddle_tpu.observability import default_registry
        default_registry().counter(
            "paddle_tpu_paged_attention_path_total",
            "paged-attention implementation chosen at trace time",
            labelnames=("path",)).labels(path=path).inc()
    except Exception:  # pragma: no cover - telemetry must never trace-fail
        pass


def _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, block_size, kv_heads, group,
                   head_dim, scale, ks_ref=None, vs_ref=None):
    """Grid (batch, max_blocks); the block axis is innermost/sequential so
    VMEM scratch carries the online-softmax state across a row's blocks.
    Quantized pools (``ks_ref/vs_ref`` given) dequantize AT THE BLOCK
    LOAD: the int8 tile and its ``[bs, kvh]`` scales widen in VMEM
    registers — the fp16/bf16 KV never exists in HBM."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    plen = len_ref[b]                     # valid tokens in this row

    @pl.when(j * block_size < plen)
    def _compute():
        q = q_ref[0].reshape(kv_heads, group, head_dim)
        if ks_ref is not None:
            ks = jnp.swapaxes(ks_ref[0], 0, 1)[..., None]  # [kvh, bs, 1]
            vs = jnp.swapaxes(vs_ref[0], 0, 1)[..., None]
            k = (jnp.swapaxes(k_ref[0], 0, 1).astype(jnp.float32)
                 * ks).astype(q.dtype)                 # [kvh, bs, hd]
            v = (jnp.swapaxes(v_ref[0], 0, 1).astype(jnp.float32)
                 * vs).astype(q.dtype)
        else:
            k = jnp.swapaxes(k_ref[0], 0, 1)           # [kvh, bs, hd]
            v = jnp.swapaxes(v_ref[0], 0, 1)           # [kvh, bs, hd]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # [kvh, g, bs]
        kpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (kv_heads, group, block_size), 2)
        s = jnp.where(kpos < plen, s, _NEG_INF)

        m_prev = m_ref[:]                              # [kvh, g, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # [kvh, g, bs]
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = corr * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [kvh, g, hd]
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new

    @pl.when(j == nb - 1)
    def _finish():
        safe_l = jnp.maximum(l_ref[:], 1e-30)
        out = (acc_ref[:] / safe_l).reshape(
            kv_heads * group, head_dim)
        o_ref[0] = out.astype(o_ref.dtype)


def _decode_kernel_quant(bt_ref, len_ref, q_ref, k_ref, v_ref, ks_ref,
                         vs_ref, o_ref, acc_ref, m_ref, l_ref, **kw):
    """Positional adapter: the quantized variant's extra scale inputs
    sit between the pools and the output in pallas_call order."""
    _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, ks_ref=ks_ref, vs_ref=vs_ref,
                   **kw)


def paged_decode_attention(q, k_pool, v_pool, block_table, lengths,
                           scale=None, interpret=None,
                           k_scale=None, v_scale=None):
    """Single-token paged attention.

    q: ``[B, heads, head_dim]`` (the step's one query row per sequence,
    RoPE already applied); k_pool/v_pool:
    ``[num_blocks, block_size, kv_heads, head_dim]``; block_table:
    ``[B, max_blocks]`` int32 (scratch block 0 beyond a row's
    allocation); lengths: ``[B]`` int32 — row b attends positions
    ``< lengths[b]`` (the current token's KV must already be written).
    ``k_scale/v_scale`` (``[num_blocks, block_size, kv_heads]`` fp32)
    mark an int8-quantized pool: blocks dequantize at the load, chased
    by the same block-table index maps.  Returns ``[B, heads, hd]``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, h, hd = q.shape
    nb, bs, kvh, _ = k_pool.shape
    mb = block_table.shape[1]
    group = h // kvh
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    quant = k_scale is not None

    kw = dict(block_size=bs, kv_heads=kvh, group=group, head_dim=hd,
              scale=scale)
    kernel = functools.partial(
        _decode_kernel_quant if quant else _decode_kernel, **kw)

    in_specs = [
        pl.BlockSpec((1, h, hd), lambda b, j, bt, ln: (b, 0, 0)),
        pl.BlockSpec((1, bs, kvh, hd),
                     lambda b, j, bt, ln: (bt[b, j], 0, 0, 0)),
        pl.BlockSpec((1, bs, kvh, hd),
                     lambda b, j, bt, ln: (bt[b, j], 0, 0, 0)),
    ]
    operands = [q, k_pool, v_pool]
    if quant:
        in_specs += [
            pl.BlockSpec((1, bs, kvh),
                         lambda b, j, bt, ln: (bt[b, j], 0, 0)),
            pl.BlockSpec((1, bs, kvh),
                         lambda b, j, bt, ln: (bt[b, j], 0, 0)),
        ]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, hd), lambda b, j, bt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, group, hd), jnp.float32),
            pltpu.VMEM((kvh, group, 1), jnp.float32),
            pltpu.VMEM((kvh, group, 1), jnp.float32),
        ],
    )

    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h, hd), q.dtype),
        interpret=interpret,
        **params,
    )(block_table.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)


# ---------------------------------------------------------------------------
# static verification (analysis/kernel_verify)


def verify_static(B, h, hd, kvh, bs, nb, mb, dtype="bfloat16",
                  quant=False):
    """Static Mosaic-legality findings for the paged decode kernel.
    The block-table scalar-prefetch operand is synthesized (row b's
    logical block j lives at physical block ``(b*mb + j) % nb``) so the
    pool index maps evaluate concretely over the whole (B, mb) grid."""
    import numpy as np
    from paddle_tpu.analysis import kernel_verify as kv
    dtype = str(dtype)
    group = h // kvh
    bt = (np.arange(B, dtype=np.int32)[:, None] * mb
          + np.arange(mb, dtype=np.int32)[None, :]) % nb
    lengths = np.full((B,), mb * bs, dtype=np.int32)
    pool4 = (nb, bs, kvh, hd)
    pool_map = lambda b, j, bt, ln: (bt[b, j], 0, 0, 0)
    row_map = lambda b, j, bt, ln: (b, 0, 0)
    args = [
        kv.ArgSpec("q", (B, h, hd), (1, h, hd), row_map, dtype),
        kv.ArgSpec("k_pool", pool4, (1, bs, kvh, hd), pool_map,
                   "int8" if quant else dtype),
        kv.ArgSpec("v_pool", pool4, (1, bs, kvh, hd), pool_map,
                   "int8" if quant else dtype),
    ]
    if quant:
        scale_map = lambda b, j, bt, ln: (bt[b, j], 0, 0)
        args += [
            kv.ArgSpec("k_scale", (nb, bs, kvh), (1, bs, kvh), scale_map,
                       "float32"),
            kv.ArgSpec("v_scale", (nb, bs, kvh), (1, bs, kvh), scale_map,
                       "float32"),
        ]
    args.append(kv.ArgSpec("o", (B, h, hd), (1, h, hd), row_map, dtype,
                           is_output=True))
    spec = kv.KernelSpec(
        name="paged_decode", grid=(B, mb), args=args,
        scratch=[kv.ScratchSpec("acc", (kvh, group, hd), "float32"),
                 kv.ScratchSpec("m", (kvh, group, 1), "float32"),
                 kv.ScratchSpec("l", (kvh, group, 1), "float32")],
        dimension_semantics=("parallel", "arbitrary"),
        scalar_prefetch=(bt, lengths),
        needs_fp32_acc=True,
        scale_pairs=[("k_scale", "k_pool"),
                     ("v_scale", "v_pool")] if quant else [],
        where=f"paged_decode[B={B} h={h}/{kvh} hd={hd} bs={bs} nb={nb} "
              f"mb={mb} {dtype}{' int8-kv' if quant else ''}]")
    return kv.verify_kernel(spec)
