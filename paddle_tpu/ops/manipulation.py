"""Shape / layout manipulation ops (parity: python/paddle/tensor/manipulation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import eager_op, unwrap
from paddle_tpu.core.tensor import Tensor


def _static_ints(v):
    if isinstance(v, Tensor):
        return [int(s) for s in np.asarray(v._data)]
    if isinstance(v, (int, np.integer)):
        return [int(v)]
    return [int(unwrap(s)) for s in v]


@eager_op
def reshape(x, shape):
    return jnp.reshape(x, _static_ints(shape))


def cast(x, dtype):
    """paddle.cast parity (dtype change through the dispatcher, taped)."""
    if isinstance(x, Tensor):
        return x.cast(dtype)
    from paddle_tpu.core import dtypes as _dtypes
    return jnp.asarray(x).astype(_dtypes.to_jax(dtype))


@eager_op
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    sa = start_axis % nd
    ea = stop_axis % nd
    new_shape = x.shape[:sa] + (-1,) + x.shape[ea + 1:]
    return jnp.reshape(x, new_shape)


@eager_op
def transpose(x, perm):
    return jnp.transpose(x, _static_ints(perm))


@eager_op
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@eager_op
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


@eager_op
def t(x):
    if x.ndim <= 1:
        return x
    return jnp.swapaxes(x, -1, -2) if x.ndim == 2 else jnp.transpose(x)


@eager_op
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    axes = _static_ints(axis) if not isinstance(axis, int) else [axis]
    axes = [a % x.ndim for a in axes]
    axes = [a for a in axes if x.shape[a] == 1]
    return jnp.squeeze(x, axis=tuple(axes)) if axes else x


@eager_op
def unsqueeze(x, axis):
    axes = _static_ints(axis) if not isinstance(axis, int) else [axis]
    out = x
    nd = x.ndim + len(axes)
    axes = sorted(a % nd for a in axes)
    for a in axes:
        out = jnp.expand_dims(out, a)
    return out


@eager_op
def concat(x, axis=0):
    if isinstance(axis, (jnp.ndarray, np.ndarray)):
        axis = int(axis)
    return jnp.concatenate(list(x), axis=int(axis))


@eager_op
def stack(x, axis=0):
    return jnp.stack(list(x), axis=int(axis))


@eager_op
def unstack(x, axis=0, num=None):
    n = num if num is not None else x.shape[axis]
    return [jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, n, axis=axis)]


@eager_op
def split(x, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return list(jnp.split(x, num_or_sections, axis=axis))
    secs = _static_ints(num_or_sections)
    total = x.shape[axis]
    if any(s == -1 for s in secs):
        known = sum(s for s in secs if s != -1)
        secs = [total - known if s == -1 else s for s in secs]
    idx = np.cumsum(secs)[:-1].tolist()
    return list(jnp.split(x, idx, axis=axis))


@eager_op
def chunk(x, chunks, axis=0):
    return list(jnp.array_split(x, chunks, axis=int(axis)))


@eager_op
def tile(x, repeat_times):
    return jnp.tile(x, _static_ints(repeat_times))


@eager_op
def expand(x, shape):
    tgt = _static_ints(shape)
    src = list(x.shape)
    # paddle expand: -1 keeps dim; broadcasting from the right
    while len(src) < len(tgt):
        src.insert(0, 1)
    out_shape = [s if t == -1 else t for s, t in zip(src, tgt)]
    return jnp.broadcast_to(jnp.reshape(x, src), out_shape)


@eager_op
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@eager_op
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, _static_ints(shape))


def broadcast_tensors(inputs):
    arrs = [unwrap(i) for i in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [broadcast_to(i, shape) for i in inputs]


@eager_op
def flip(x, axis):
    axes = _static_ints(axis) if not isinstance(axis, int) else [axis]
    return jnp.flip(x, axis=tuple(axes))


@eager_op
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@eager_op
def roll(x, shifts, axis=None):
    if axis is not None and not isinstance(axis, int):
        axis = tuple(_static_ints(axis))
    if not isinstance(shifts, int):
        shifts = tuple(_static_ints(shifts))
    return jnp.roll(x, shifts, axis=axis)


@eager_op
def slice(x, axes, starts, ends):
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e in zip(_static_ints(axes), _static_ints(starts), _static_ints(ends)):
        idx[a] = jnp.s_[s:e]
    return x[tuple(idx)]


@eager_op
def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e, st in zip(_static_ints(axes), _static_ints(starts),
                           _static_ints(ends), _static_ints(strides)):
        idx[a] = jnp.s_[s:e:st]
    return x[tuple(idx)]


@eager_op
def gather(x, index, axis=0):
    index = jnp.reshape(index, (-1,)) if index.ndim > 1 else index
    return jnp.take(x, index, axis=int(unwrap(axis)) if not isinstance(axis, int) else axis)


@eager_op
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@eager_op
def take_along_axis(arr, indices, axis, broadcast=True):
    if broadcast:
        shape = list(arr.shape)
        shape[axis] = indices.shape[axis]
        indices = jnp.broadcast_to(indices, shape)
    return jnp.take_along_axis(arr, indices, axis=axis)


@eager_op
def put_along_axis(arr, indices, values, axis, reduce="assign"):
    values = jnp.broadcast_to(values, indices.shape).astype(arr.dtype)
    dims = list(range(arr.ndim))
    idx = jnp.meshgrid(*[jnp.arange(s) for s in indices.shape], indexing="ij")
    idx[axis] = indices
    if reduce == "assign":
        return arr.at[tuple(idx)].set(values)
    if reduce in ("add", "sum"):
        return arr.at[tuple(idx)].add(values)
    if reduce in ("mul", "multiply"):
        return arr.at[tuple(idx)].multiply(values)
    raise ValueError(f"unknown reduce {reduce}")


@eager_op
def scatter(x, index, updates, overwrite=True):
    index = jnp.reshape(index, (-1,))
    if overwrite:
        return x.at[index].set(updates.astype(x.dtype))
    # overwrite=False: rows hit by index are zeroed then accumulated
    # (duplicate indices sum) — paddle scatter semantics.
    return x.at[index].set(0).at[index].add(updates.astype(x.dtype))


@eager_op
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates.astype(x.dtype))


@eager_op
def scatter_nd(index, updates, shape):
    zeros = jnp.zeros(_static_ints(shape), updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return zeros.at[idx].add(updates)


@eager_op
def index_select(x, index, axis=0):
    return jnp.take(x, jnp.reshape(index, (-1,)), axis=axis)


@eager_op
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@eager_op
def index_add(x, index, axis, value):
    index = jnp.reshape(index, (-1,))
    moved = jnp.moveaxis(x, axis, 0)
    vmoved = jnp.moveaxis(value, axis, 0)
    out = moved.at[index].add(vmoved.astype(x.dtype))
    return jnp.moveaxis(out, 0, axis)


@eager_op
def index_put(x, indices, value, accumulate=False):
    idx = tuple(i for i in indices)
    if accumulate:
        return x.at[idx].add(value.astype(x.dtype))
    return x.at[idx].set(jnp.broadcast_to(value, x[idx].shape).astype(x.dtype))


@eager_op
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@eager_op
def unbind(x, axis=0):
    n = x.shape[axis]
    return [jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis)]


@eager_op
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@eager_op
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@eager_op
def masked_select(x, mask):
    # dynamic-shape op: eager only (jit path will fail by design, like
    # the reference's dynamic-output ops do under to_static)
    return x[jnp.broadcast_to(mask, x.shape)]


@eager_op
def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


@eager_op
def where(condition, x=None, y=None):
    if x is None and y is None:
        return jnp.nonzero(condition)
    return jnp.where(condition, x, y)


@eager_op
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True):
    pads = _static_ints(pad)
    nd = x.ndim
    if len(pads) == 2 * nd:
        # full per-axis spec, paddle order: axis-major lo/hi
        width = [(pads[2 * i], pads[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec applies to trailing spatial dims; paddle packs
        # reversed (last axis first) like torch.nn.functional.pad
        k = len(pads) // 2
        width = [(0, 0)] * nd
        for i in range(k):
            axis = nd - 1 - i
            width[axis] = (pads[2 * i], pads[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, width, mode="constant", constant_values=value)
    return jnp.pad(x, width, mode=jmode)


@eager_op
def crop(x, shape=None, offsets=None):
    sh = _static_ints(shape)
    off = _static_ints(offsets) if offsets is not None else [0] * x.ndim
    sh = [x.shape[i] if s == -1 else s for i, s in enumerate(sh)]
    idx = tuple(jnp.s_[o:o + s] for o, s in zip(off, sh))
    return x[idx]


@eager_op
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    res = jnp.unique(x, return_index=return_index, return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    return res


@eager_op
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    arr = np.asarray(x)
    if axis is None:
        arr = arr.reshape(-1)
        keep = np.concatenate([[True], arr[1:] != arr[:-1]])
        out = arr[keep]
        results = [jnp.asarray(out)]
        if return_inverse:
            results.append(jnp.asarray(np.cumsum(keep) - 1))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.concatenate([idx, [arr.size]]))
            results.append(jnp.asarray(counts))
        return results[0] if len(results) == 1 else tuple(results)
    raise NotImplementedError("unique_consecutive with axis")


@eager_op
def rot90_(x, k=1):
    return jnp.rot90(x, k=k)


@eager_op
def view(x, shape_or_dtype):
    if isinstance(shape_or_dtype, (list, tuple)):
        return jnp.reshape(x, _static_ints(shape_or_dtype))
    from paddle_tpu.core.dtypes import to_jax
    return x.view(to_jax(shape_or_dtype)) if hasattr(x, "view") else \
        jax.lax.bitcast_convert_type(x, to_jax(shape_or_dtype))


@eager_op
def numel(x):
    return jnp.asarray(x.size, jnp.int64)


@eager_op
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    in_shard = (input >= lo) & (input < hi)
    return jnp.where(in_shard, input - lo, ignore_value)


# (the public __all__ is computed once at the end of the module)


# ---- long-tail structural ops (paddle.tensor manipulation parity) ----------

@eager_op
def atleast_1d(x):
    return jnp.atleast_1d(x)


@eager_op
def atleast_2d(x):
    return jnp.atleast_2d(x)


@eager_op
def atleast_3d(x):
    return jnp.atleast_3d(x)


@eager_op
def column_stack(x):
    return jnp.column_stack(x)


@eager_op
def row_stack(x):
    return jnp.vstack(x)


@eager_op
def hstack(x):
    return jnp.hstack(x)


@eager_op
def vstack(x):
    return jnp.vstack(x)


@eager_op
def dstack(x):
    return jnp.dstack(x)


@eager_op
def hsplit(x, num_or_indices):
    return tuple(jnp.hsplit(x, num_or_indices))


@eager_op
def vsplit(x, num_or_indices):
    return tuple(jnp.vsplit(x, num_or_indices))


@eager_op
def dsplit(x, num_or_indices):
    return tuple(jnp.dsplit(x, num_or_indices))


@eager_op
def tensor_split(x, num_or_indices, axis=0):
    return tuple(jnp.array_split(x, num_or_indices, axis=axis))


@eager_op
def block_diag(inputs):
    return jax.scipy.linalg.block_diag(*inputs)


@eager_op
def select_scatter(x, values, axis, index):
    import builtins  # the module-level paddle `slice` op shadows the builtin
    idx = [builtins.slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(values)


@eager_op
def slice_scatter(x, value, axes, starts, ends, strides=None):
    import builtins
    strides = strides or [1] * len(axes)
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(s, e, st)
    return x.at[tuple(idx)].set(value)


def rank(x):
    """Number of dimensions (paddle.rank parity, 0-d int)."""
    return unwrap(x).ndim


# recompute the public surface to include the long-tail block above


@eager_op
def masked_scatter(x, mask, value):
    """Fill True positions of `mask` with `value`'s leading elements in
    row-major order (reference tensor/manipulation.py masked_scatter).
    `value` must carry at least mask.sum() elements; shapes are static so
    the mapping compiles (position k of the mask takes value element
    rank(k) = number of True positions before it)."""
    m = jnp.broadcast_to(mask, x.shape)
    vflat = jnp.ravel(value)
    if (vflat.shape[0] < m.size
            and not any(isinstance(a, jax.core.Tracer) for a in (m, vflat))):
        # shape-only pre-check keeps the common value.size >= mask.size
        # case free of a device->host sync; only possibly-deficient calls
        # pay for materializing the count
        need = int(m.sum())
        if vflat.shape[0] < need:
            raise ValueError(
                f"masked_scatter: mask selects {need} elements but value "
                f"supplies only {vflat.shape[0]} "
                f"({need - vflat.shape[0]} short)")
    if vflat.shape[0] == 0:
        # a size-0 value is only legal with an all-False mask (checked
        # above in eager); the gather below cannot index a 0-size array
        return x
    # under tracing mask.sum() is dynamic: clamp (duplicating the last
    # element) rather than fail compilation — eager callers got the check
    order = jnp.cumsum(m.ravel().astype(jnp.int32)) - 1
    picked = vflat[jnp.clip(order, 0, vflat.shape[0] - 1)]
    return jnp.where(m, picked.reshape(x.shape), x)


@eager_op
def view_as(x, other):
    """Reshape x to other's shape (reference view_as — a view in paddle;
    functional arrays make it a reshape)."""
    return jnp.reshape(x, other.shape)


__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
