"""Tensor creation ops (parity: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtypes as _dtypes
from paddle_tpu.core import state as _state
from paddle_tpu.core.dispatch import eager_op, unwrap
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "logspace",
    "eye", "diag", "diagflat", "tril", "triu", "meshgrid", "assign",
    "clone", "tril_indices", "triu_indices", "complex", "polar",
]


def _resolve_dtype(dtype, default_float=True):
    if dtype is None:
        return _dtypes.to_jax(_state.get_default_dtype()) if default_float else None
    return _dtypes.to_jax(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype)
        t.stop_gradient = stop_gradient
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    return Tensor._wrap(jnp.zeros(_shape(shape), _resolve_dtype(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor._wrap(jnp.ones(_shape(shape), _resolve_dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fill = unwrap(fill_value)
    if dtype is None and isinstance(fill, (bool,)):
        dt = jnp.bool_
    elif dtype is None and isinstance(fill, int):
        dt = jnp.int64
    else:
        dt = _resolve_dtype(dtype)
    return Tensor._wrap(jnp.full(_shape(shape), fill, dt))


@eager_op
def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dtypes.to_jax(dtype))


@eager_op
def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=_dtypes.to_jax(dtype))


@eager_op
def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=_dtypes.to_jax(dtype))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


@eager_op
def empty_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dtypes.to_jax(dtype))


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if any(isinstance(v, float) or (hasattr(v, "dtype") and
               _dtypes.is_floating(v.dtype)) for v in (start, end, step)):
            dt = _resolve_dtype(None)
        else:
            dt = jnp.int64
    else:
        dt = _dtypes.to_jax(dtype)
    return Tensor._wrap(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor._wrap(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                                     dtype=_resolve_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor._wrap(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                                     base=unwrap(base), dtype=_resolve_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor._wrap(jnp.eye(int(num_rows),
                                None if num_columns is None else int(num_columns),
                                dtype=_resolve_dtype(dtype)))


@eager_op
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1:
        d = jnp.diag(x, k=offset)
        if padding_value != 0:
            mask = jnp.diag(jnp.ones(x.shape[0], dtype=bool), k=offset)
            d = jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
        return d
    return jnp.diag(x, k=offset)


@eager_op
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@eager_op
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@eager_op
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def meshgrid(*args, **kwargs):
    arrs = [unwrap(a) for a in (args[0] if len(args) == 1 and
            isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*arrs, indexing="ij")
    return [Tensor._wrap(o) for o in outs]


@eager_op
def assign(x, output=None):
    return jnp.asarray(x)


@eager_op
def clone(x):
    return x + jnp.zeros((), x.dtype)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor._wrap(jnp.stack([r, c]).astype(_dtypes.to_jax(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = jnp.triu_indices(row, k=offset, m=col or row)
    return Tensor._wrap(jnp.stack([r, c]).astype(_dtypes.to_jax(dtype)))


@eager_op(name="complex")
def complex(real, imag):
    return jax.lax.complex(real, imag)


@eager_op
def polar(abs, angle):
    return jax.lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))
