"""Linear algebra ops (parity: python/paddle/tensor/linalg.py; reference
matmul at linalg.py:139 → _C_ops.matmul).  On TPU these are THE MXU ops —
all lower straight to XLA dot_general/conv."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op


@eager_op
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@eager_op
def mm(x, y):
    return jnp.matmul(x, y)


@eager_op
def bmm(x, y):
    return jax.lax.batch_matmul(x, y)


@eager_op
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@eager_op
def mv(x, vec):
    return jnp.matmul(x, vec)


@eager_op
def dist(x, y, p=2.0):
    d = jnp.abs(x - y).ravel()
    if p == 0:
        return jnp.sum(d != 0).astype(x.dtype)
    if jnp.isinf(p):
        return jnp.max(d) if p > 0 else jnp.min(d)
    return jnp.sum(d ** p) ** (1.0 / p)


@eager_op
def norm(x, p=None, axis=None, keepdim=False):
    if isinstance(axis, (list, tuple)) and len(axis) == 1:
        axis = axis[0]
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2.0
    if p == "fro":
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(x)), axis=ax, keepdims=keepdim))
    if p == "nuc":
        s = jnp.linalg.svd(x, compute_uv=False)
        return jnp.sum(s, axis=-1, keepdims=keepdim)
    if isinstance(axis, (list, tuple)):
        return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)
    if axis is None:
        x = x.ravel()
        axis = 0
    import math
    if isinstance(p, (int, float)) and math.isinf(p):
        f = jnp.max if p > 0 else jnp.min
        return f(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@eager_op
def cross(x, y, axis=9):
    if axis == 9:  # paddle default: first axis with dim 3
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return jnp.cross(x, y, axis=axis)


@eager_op
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


@eager_op
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@eager_op
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.lax.linalg.triangular_solve(
        x, y, left_side=True, lower=not upper,
        transpose_a=transpose, unit_diagonal=unitriangular)


@eager_op
def solve(x, y):
    return jnp.linalg.solve(x, y)


@eager_op
def lstsq(x, y, rcond=None, driver=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@eager_op
def inverse(x):
    return jnp.linalg.inv(x)


@eager_op
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@eager_op
def det(x):
    return jnp.linalg.det(x)


@eager_op
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


@eager_op
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@eager_op
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@eager_op
def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


@eager_op
def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


@eager_op
def eig(x):
    # TPU/XLA has no nonsymmetric eig; compute on host (CPU callback-free:
    # eager-only op, like reference dynamic ops)
    import numpy as np
    w, v = np.linalg.eig(np.asarray(x))
    return jnp.asarray(w), jnp.asarray(v)


@eager_op
def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, symmetrize_input=True)


@eager_op
def eigvals(x):
    import numpy as np
    return jnp.asarray(np.linalg.eigvals(np.asarray(x)))


@eager_op
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x)


@eager_op
def lu(x, pivot=True, get_infos=False):
    lu_, piv, perm = jax.lax.linalg.lu(x)
    # pack piv 1-indexed like LAPACK/paddle
    pivots = piv + 1
    if get_infos:
        info = jnp.zeros(x.shape[:-2], jnp.int32)
        return lu_, pivots, info
    return lu_, pivots


@eager_op
def multi_dot(tensors):
    return jnp.linalg.multi_dot(list(tensors))


@eager_op
def histogram(input, bins=100, min=0, max=0, weight=None, density=False):
    if min == 0 and max == 0:
        lo, hi = jnp.min(input), jnp.max(input)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(input.ravel(), bins=bins, range=(lo, hi),
                            weights=None if weight is None else weight.ravel(),
                            density=density)
    return hist


@eager_op
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=None)


@eager_op
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@eager_op
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@eager_op
def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


@eager_op
def tensordot(x, y, axes=2):
    if hasattr(axes, "__len__") and not isinstance(axes, int):
        axes = tuple(tuple(a) if hasattr(a, "__len__") else a for a in axes)
    return jnp.tensordot(x, y, axes=axes)


@eager_op
def vecdot(x, y, axis=-1):
    """Vector dot along `axis` with broadcasting; conjugates x for
    complex inputs (reference python/paddle/tensor/linalg.py vecdot,
    array-API semantics)."""
    if jnp.iscomplexobj(x):
        x = jnp.conj(x)
    return jnp.sum(x * y, axis=axis)


@eager_op
def cartesian_prod(*tensors):
    """Cartesian product of 1-D tensors → [prod(n_i), len(tensors)]
    (reference tensor/math.py cartesian_prod)."""
    if len(tensors) == 1 and isinstance(tensors[0], (list, tuple)):
        tensors = tuple(tensors[0])
    if len(tensors) == 1:
        return jnp.reshape(tensors[0], (-1,))  # paddle: 1-D stays 1-D
    grids = jnp.meshgrid(*tensors, indexing="ij")
    return jnp.stack([g.reshape(-1) for g in grids], axis=-1)


@eager_op
def combinations(x, r=2, with_replacement=False):
    """r-length combinations of a 1-D tensor's elements (reference
    tensor/math.py combinations).  Indices are computed host-side
    (itertools) — the input length is static under tracing anyway."""
    import itertools
    import numpy as np
    n = x.shape[0]
    picker = itertools.combinations_with_replacement if with_replacement \
        else itertools.combinations
    idx = np.asarray(list(picker(range(n), r)), dtype=np.int32)
    if idx.size == 0:
        return jnp.zeros((0, r), x.dtype)
    return jnp.take(x, jnp.asarray(idx), axis=0)


# Public surface: only ops defined in this module (tape-aware wrappers carry
# __wrapped_pure__; plain helpers must be defined here, not imported).


@eager_op
def pdist(x, p=2.0):
    """Condensed pairwise distances of rows (reference tensor/linalg.py
    pdist): the upper-triangle (i < j) of cdist, flattened."""
    n = x.shape[0]
    d = jnp.sum(jnp.abs(x[:, None, :] - x[None, :, :]) ** p,
                axis=-1) ** (1.0 / p)
    iu, ju = jnp.triu_indices(n, k=1)
    return d[iu, ju]


@eager_op
def matrix_exp(x):
    """Matrix exponential (reference tensor/linalg.py matrix_exp)."""
    import jax.scipy.linalg as jsl
    return jsl.expm(x)


__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
