"""Search / sort / sampling-free selection ops
(parity: python/paddle/tensor/search.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op


@eager_op
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    from paddle_tpu.core.dtypes import to_jax
    if axis is None:
        x = x.ravel()
        axis = 0
    out = jnp.argmax(x, axis=int(axis), keepdims=keepdim)
    return out.astype(to_jax(dtype))


@eager_op
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    from paddle_tpu.core.dtypes import to_jax
    if axis is None:
        x = x.ravel()
        axis = 0
    out = jnp.argmin(x, axis=int(axis), keepdims=keepdim)
    return out.astype(to_jax(dtype))


@eager_op
def argsort(x, axis=-1, descending=False, stable=True):
    out = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return out.astype(jnp.int64)


@eager_op
def sort(x, axis=-1, descending=False, stable=True):
    return jnp.sort(x, axis=axis, stable=stable, descending=descending)


@eager_op
def topk(x, k, axis=None, largest=True, sorted=True):
    if axis is None:
        axis = -1
    axis = axis % x.ndim
    xm = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(xm, int(k))
    else:
        vals, idx = jax.lax.top_k(-xm, int(k))
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx, -1, axis).astype(jnp.int64))


@eager_op
def kthvalue(x, k, axis=-1, keepdim=False):
    axis = axis % x.ndim
    sorted_v = jnp.sort(x, axis=axis)
    sorted_i = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_v, k - 1, axis=axis)
    idx = jnp.take(sorted_i, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


@eager_op
def mode(x, axis=-1, keepdim=False):
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    # O(n^2) pairwise count — fine for the typical last-dim sizes; fully
    # static-shaped for XLA.
    eqmat = moved[..., :, None] == moved[..., None, :]
    counts = jnp.sum(eqmat, axis=-1)
    maxc = jnp.max(counts, axis=-1, keepdims=True)
    is_mode = counts == maxc
    big = jnp.where(is_mode, moved,
                    jnp.asarray(jnp.finfo(moved.dtype).max
                                if jnp.issubdtype(moved.dtype, jnp.floating)
                                else jnp.iinfo(moved.dtype).max, moved.dtype))
    vals = jnp.min(big, axis=-1)
    # paddle returns the LAST index of the modal value
    hits = moved == vals[..., None]
    rev_idx = jnp.argmax(jnp.flip(hits, axis=-1), axis=-1)
    idx = moved.shape[-1] - 1 - rev_idx
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


@eager_op
def nonzero(x, as_tuple=False):
    idx = jnp.nonzero(x)
    if as_tuple:
        return tuple(i.astype(jnp.int64) for i in idx)
    return jnp.stack(idx, axis=1).astype(jnp.int64)


@eager_op
def masked_argmax(x, mask, axis=None, keepdim=False):
    neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return jnp.argmax(jnp.where(mask, x, neg), axis=axis, keepdims=keepdim)


@eager_op
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        flat_seq = sorted_sequence.reshape(-1, sorted_sequence.shape[-1])
        flat_val = values.reshape(-1, values.shape[-1])
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            flat_seq, flat_val).reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@eager_op
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@eager_op
def index_fill(x, index, axis, value):
    index = jnp.reshape(index, (-1,))
    moved = jnp.moveaxis(x, axis, 0)
    out = moved.at[index].set(jnp.asarray(value, x.dtype))
    return jnp.moveaxis(out, 0, axis)


# Public surface: only ops defined in this module (tape-aware wrappers carry
# __wrapped_pure__; plain helpers must be defined here, not imported).
__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
