"""Random ops.

Eager calls draw from the process-global splitting key (core/state.py) so the
paddle-style API (`paddle.rand(shape)`) works; the jitted training path should
use the functional forms with explicit keys (`paddle_tpu.ops.random.*_p`) —
idiomatic JAX, and required for reproducible pjit programs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes as _dtypes
from paddle_tpu.core import state as _state
from paddle_tpu.core.dispatch import unwrap
from paddle_tpu.core.tensor import Tensor


def _shape(shape):
    import numpy as np
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) for s in shape)


def _dt(dtype):
    if dtype is None:
        return _dtypes.to_jax(_state.get_default_dtype())
    return _dtypes.to_jax(dtype)


def seed(s):
    return _state.seed(s)


def get_rng_state():
    return _state.get_rng_state()


def set_rng_state(st):
    _state.set_rng_state(st)


# ---- functional (key-explicit) forms: use these inside jit ----------------

def uniform_p(key, shape, dtype=jnp.float32, min=-1.0, max=1.0):
    return jax.random.uniform(key, shape, dtype, minval=min, maxval=max)


def normal_p(key, shape, dtype=jnp.float32, mean=0.0, std=1.0):
    return mean + std * jax.random.normal(key, shape, dtype)


def randint_p(key, low, high, shape, dtype=jnp.int32):
    return jax.random.randint(key, shape, low, high, dtype)


def bernoulli_p(key, p, shape, dtype=jnp.float32):
    return jax.random.bernoulli(key, p, shape).astype(dtype)


# ---- eager paddle-parity API ----------------------------------------------

def rand(shape, dtype=None, name=None):
    return Tensor._wrap(jax.random.uniform(_state.next_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor._wrap(jax.random.normal(_state.next_key(), _shape(shape), _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor) or shape is None:
        m = unwrap(mean)
        s = unwrap(std)
        out_shape = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor._wrap(m + s * jax.random.normal(
            _state.next_key(), out_shape, _dt(None)))
    return Tensor._wrap(mean + std * jax.random.normal(
        _state.next_key(), _shape(shape), _dt(None)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    return Tensor._wrap(jax.random.uniform(
        _state.next_key(), _shape(shape), _dt(dtype), minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = jnp.int64 if dtype is None else _dtypes.to_jax(dtype)
    return Tensor._wrap(jax.random.randint(
        _state.next_key(), _shape(shape), int(low), int(high), dt))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    arr = unwrap(x)
    if high is None:
        low, high = 0, low
    dt = arr.dtype if dtype is None else _dtypes.to_jax(dtype)
    out = jax.random.randint(_state.next_key(), arr.shape, int(low), int(high),
                             jnp.int32)
    return Tensor._wrap(out.astype(dt))


def randperm(n, dtype="int64", name=None):
    return Tensor._wrap(jax.random.permutation(_state.next_key(), int(n))
                        .astype(_dtypes.to_jax(dtype)))


def shuffle(x, axis=0):
    arr = unwrap(x)
    return Tensor._wrap(jax.random.permutation(_state.next_key(), arr, axis=axis,
                                               independent=False))


def bernoulli(x, name=None):
    arr = unwrap(x)
    return Tensor._wrap(jax.random.bernoulli(_state.next_key(), arr).astype(arr.dtype))


def poisson(x, name=None):
    arr = unwrap(x)
    return Tensor._wrap(jax.random.poisson(_state.next_key(), arr).astype(arr.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    arr = unwrap(x)
    logits = jnp.log(jnp.maximum(arr, 1e-30))
    if replacement:
        out = jax.random.categorical(_state.next_key(), logits,
                                     shape=(*arr.shape[:-1], num_samples) if arr.ndim > 1 else (num_samples,),
                                     axis=-1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(_state.next_key(),
                              arr.shape, logits.dtype)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor._wrap(out.astype(jnp.int64))


def rand_like(x, dtype=None):
    arr = unwrap(x)
    dt = arr.dtype if dtype is None else _dtypes.to_jax(dtype)
    return Tensor._wrap(jax.random.uniform(_state.next_key(), arr.shape, dt))


def randn_like(x, dtype=None, name=None):
    arr = unwrap(x)
    dt = arr.dtype if dtype is None else _dtypes.to_jax(dtype)
    return Tensor._wrap(jax.random.normal(_state.next_key(), arr.shape, dt))


def exponential_(x, lam=1.0, name=None):
    arr = unwrap(x)
    u = jax.random.uniform(_state.next_key(), arr.shape, arr.dtype,
                           minval=jnp.finfo(arr.dtype).tiny, maxval=1.0)
    out = -jnp.log(u) / lam
    if isinstance(x, Tensor):
        x._set_data(out)
        return x
    return Tensor._wrap(out)
