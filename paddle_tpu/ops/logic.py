"""Comparison / logical ops (parity: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op

equal = eager_op(name="equal")(lambda x, y: jnp.equal(x, y))
not_equal = eager_op(name="not_equal")(lambda x, y: jnp.not_equal(x, y))
greater_than = eager_op(name="greater_than")(lambda x, y: jnp.greater(x, y))
greater_equal = eager_op(name="greater_equal")(lambda x, y: jnp.greater_equal(x, y))
less_than = eager_op(name="less_than")(lambda x, y: jnp.less(x, y))
less_equal = eager_op(name="less_equal")(lambda x, y: jnp.less_equal(x, y))
logical_and = eager_op(name="logical_and")(lambda x, y: jnp.logical_and(x, y))
logical_or = eager_op(name="logical_or")(lambda x, y: jnp.logical_or(x, y))
logical_xor = eager_op(name="logical_xor")(lambda x, y: jnp.logical_xor(x, y))
logical_not = eager_op(name="logical_not")(lambda x: jnp.logical_not(x))
bitwise_and = eager_op(name="bitwise_and")(lambda x, y: jnp.bitwise_and(x, y))
bitwise_or = eager_op(name="bitwise_or")(lambda x, y: jnp.bitwise_or(x, y))
bitwise_xor = eager_op(name="bitwise_xor")(lambda x, y: jnp.bitwise_xor(x, y))
bitwise_not = eager_op(name="bitwise_not")(lambda x: jnp.bitwise_not(x))
bitwise_left_shift = eager_op(name="bitwise_left_shift")(lambda x, y: jnp.left_shift(x, y))
bitwise_right_shift = eager_op(name="bitwise_right_shift")(lambda x, y: jnp.right_shift(x, y))


@eager_op
def equal_all(x, y):
    return jnp.array_equal(x, y)


@eager_op(name="all")
def all(x, axis=None, keepdim=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.all(x, axis=ax, keepdims=keepdim)


@eager_op(name="any")
def any(x, axis=None, keepdim=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.any(x, axis=ax, keepdims=keepdim)


@eager_op
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@eager_op
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def is_tensor(x):
    from paddle_tpu.core.tensor import Tensor
    return isinstance(x, Tensor)


def is_floating_point(x):
    from paddle_tpu.core import dtypes
    from paddle_tpu.core.dispatch import unwrap
    return dtypes.from_jax(unwrap(x).dtype) in dtypes.FLOATING


def is_integer(x):
    from paddle_tpu.core import dtypes
    from paddle_tpu.core.dispatch import unwrap
    return dtypes.from_jax(unwrap(x).dtype) in dtypes.INTEGER


def is_complex(x):
    from paddle_tpu.core import dtypes
    from paddle_tpu.core.dispatch import unwrap
    return dtypes.from_jax(unwrap(x).dtype) in dtypes.COMPLEX


# Public surface: only ops defined in this module (tape-aware wrappers carry
# __wrapped_pure__; plain helpers must be defined here, not imported).
__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
