"""Comparison / logical ops (parity: python/paddle/tensor/logic.py).

The op wrappers are GENERATED from the schema (ops/gen/ops.yaml ->
ops/generated_math.py); this module re-exports the logic subset and keeps
the non-op type predicates.
"""

from __future__ import annotations

from paddle_tpu.ops.generated_math import (  # noqa: F401
    all, allclose, any, bitwise_and, bitwise_left_shift, bitwise_not,
    bitwise_or, bitwise_right_shift, bitwise_xor, equal, equal_all,
    greater_equal, greater_than, isclose, less_equal, less_than,
    logical_and, logical_not, logical_or, logical_xor, not_equal)


def is_tensor(x):
    from paddle_tpu.core.tensor import Tensor
    return isinstance(x, Tensor)


def is_floating_point(x):
    from paddle_tpu.core import dtypes
    from paddle_tpu.core.dispatch import unwrap
    return dtypes.from_jax(unwrap(x).dtype) in dtypes.FLOATING


def is_integer(x):
    from paddle_tpu.core import dtypes
    from paddle_tpu.core.dispatch import unwrap
    return dtypes.from_jax(unwrap(x).dtype) in dtypes.INTEGER


def is_complex(x):
    from paddle_tpu.core import dtypes
    from paddle_tpu.core.dispatch import unwrap
    return dtypes.from_jax(unwrap(x).dtype) in dtypes.COMPLEX


__all__ = [
    "all", "allclose", "any", "bitwise_and", "bitwise_left_shift",
    "bitwise_not", "bitwise_or", "bitwise_right_shift", "bitwise_xor",
    "equal", "equal_all", "greater_equal", "greater_than", "isclose",
    "is_complex", "is_floating_point", "is_integer", "is_tensor",
    "less_equal", "less_than", "logical_and", "logical_not", "logical_or",
    "logical_xor", "not_equal"]
