"""TensorArray ops (reference: python/paddle/tensor/array.py —
create_array/array_write/array_read/array_length over the C++
TensorArray type, phi/core/tensor_array.h).

TPU-native stance: in eager mode a TensorArray is a plain python list of
Tensors (the reference's dygraph branch does exactly this); inside jit,
loop-carried accumulation belongs to ``lax.scan``'s stacked outputs —
there is no dynamic-length device container under XLA's static shapes,
so traced writes at traced indices raise with that guidance instead of
miscompiling.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["create_array", "array_write", "array_read", "array_length"]


def _index(i) -> int:
    import jax
    raw = i._data if hasattr(i, "_data") else i
    if isinstance(raw, jax.core.Tracer):
        raise TypeError(
            "TensorArray indices must be concrete: under jit, accumulate "
            "with lax.scan (stacked outputs) instead of array_write at a "
            "traced index — XLA has no dynamic-length containers")
    idx = int(raw)
    if idx < 0:
        raise IndexError(f"TensorArray indices are non-negative positions, "
                         f"got {idx}")
    return idx


def create_array(dtype: str = "float32",
                 initialized_list: Optional[List] = None) -> List:
    """New TensorArray, optionally seeded (reference array.py:222)."""
    from paddle_tpu.core.tensor import Tensor
    out: List = []
    for v in (initialized_list or []):
        out.append(v if isinstance(v, Tensor) else Tensor(v))
    return out


def array_write(x, i, array: Optional[List] = None) -> List:
    """Write x at index i, growing the array as needed
    (reference array.py:141: i == len appends, i < len overwrites)."""
    from paddle_tpu.core.tensor import Tensor
    if array is None:
        array = []
    idx = _index(i)
    if idx > len(array):
        raise IndexError(f"array_write index {idx} beyond length "
                         f"{len(array)} (only append or overwrite)")
    x = x if isinstance(x, Tensor) else Tensor(x)
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def array_read(array: List, i):
    """Read array[i] (reference array.py:73)."""
    return array[_index(i)]


def array_length(array: List) -> int:
    """Length (reference array.py:24)."""
    return len(array)
