"""Statistics ops (parity: python/paddle/tensor/stat.py)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op


def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis


@eager_op
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@eager_op
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@eager_op
def median(x, axis=None, keepdim=False, mode="avg"):
    if mode == "avg":
        return jnp.median(x, axis=_axis(axis), keepdims=keepdim)
    # mode == 'min': lower of the two middle elements, plus its index
    ax = -1 if axis is None else int(axis)
    flat = x.ravel() if axis is None else x
    n = flat.shape[ax]
    k = (n - 1) // 2
    sorted_v = jnp.sort(flat, axis=ax)
    sorted_i = jnp.argsort(flat, axis=ax)
    vals = jnp.take(sorted_v, k, axis=ax)
    idx = jnp.take(sorted_i, k, axis=ax)
    if keepdim and axis is not None:
        vals = jnp.expand_dims(vals, ax)
        idx = jnp.expand_dims(idx, ax)
    return vals, idx.astype(jnp.int64)


@eager_op
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=_axis(axis), keepdims=keepdim)


@eager_op
def quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim,
                        method=interpolation)


@eager_op
def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.nanquantile(x, jnp.asarray(q), axis=_axis(axis),
                           keepdims=keepdim, method=interpolation)


@eager_op
def histogramdd(x, bins=10, ranges=None, density=False, weights=None):
    return jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                           weights=weights)


# Public surface: only ops defined in this module (tape-aware wrappers carry
# __wrapped_pure__; plain helpers must be defined here, not imported).
__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
