"""Flagship-config capacity planner: AOT-compile the REAL training configs
on a virtual device mesh and report per-device memory from XLA's buffer
assignment — proof that the 4-D recipe actually fits the target hardware,
not just that a small proxy trains.

Reference role: the fleet 4-D hybrid recipe
(fleet/base/topology.py:54 ``["data", "pipe", "sharding", "model"]`` +
fleet/meta_parallel/) plus the capacity arithmetic PaddleNLP users do by
hand.  TPU-native: the whole train step (1F1B pipeline + ZeRO gather/
scatter + tp-Megatron blocks + AdamW-with-master update) is ONE jitted
program, so ``jax.jit(step).lower(avals).compile().memory_analysis()``
yields the compiler's own per-device peak-memory figure for ANY mesh
shape — no hardware needed.  Params are never materialized: lowering runs
on ``jax.ShapeDtypeStruct`` avals with ``NamedSharding`` attached, and
the step function is the very same ``build_pipeline_step_fn`` product the
real ``PipelineTrainStep`` jits.

Caveat: the figure comes from this host's backend buffer assignment of
the SPMD-partitioned module (CPU when run on the virtual mesh).  Same
HLO, different scheduler than the TPU compiler — treat it as a capacity
estimate for "does 70B fit a v5p-64?" questions, not kB-accurate
accounting.

Usage::

    from paddle_tpu.distributed.planner import plan_llama, LLAMA3_8B
    report = plan_llama(LLAMA3_8B, pp=4, dp=2, fsdp=8, tp=1, seq=8192)
    assert report.fits(hbm_gb=95)   # v5p HBM

CLI (needs the virtual devices BEFORE jax init)::

    XLA_FLAGS=--xla_force_host_platform_device_count=64 \
        python -m paddle_tpu.distributed.planner \
        --config llama3-8b --pp 4 --dp 2 --fsdp 8 --tp 1
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = ["plan_llama", "plan_moe", "PlanReport", "estimate_peak_hbm",
           "LLAMA3_8B", "LLAMA3_70B", "DEEPSEEK_MOE_16B",
           "ERNIE45_21B_A3B", "CONFIGS"]


def estimate_peak_hbm(step_fn, shardings, mesh, *example_args,
                      batch_spec=None, donate=True) -> int:
    """Per-device peak-HBM estimate of one compiled step under a layout:
    XLA's own buffer assignment (arguments + temps) from an abstract
    ``jit(...).lower(avals).compile()`` — nothing is materialized.

    ``step_fn`` is either a ``jit.TrainStep``-style compiled step (its
    full fwd+bwd+update ``_step_impl`` and live param/opt-state shapes
    are lowered; pass one example batch) or a plain jit-able callable
    (``example_args`` are its arguments; ``shardings`` is then a
    matching pytree of PartitionSpecs, with ``None`` leaves replicated).
    ``shardings`` for a step is ``{param name → PartitionSpec |
    NamedSharding}``; opt-state leaves shaped like their param inherit
    its placement.  This is the AOT memory analysis the flagship-config
    CLI runs, factored out so the autoshard pruner (and anything else)
    can reject OOM layouts per candidate.  Same caveat as
    ``PlanReport``: the host backend's assignment is a capacity
    estimate, not kB-accurate TPU accounting.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def to_sharding(s):
        if s is None:
            return NamedSharding(mesh, P())
        if isinstance(s, NamedSharding):
            return s
        return NamedSharding(mesh, s)

    def aval_of(x, s):
        if not hasattr(x, "shape") or not hasattr(x, "dtype"):
            return x
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype,
                                    sharding=to_sharding(s))

    if hasattr(step_fn, "_step_impl"):           # a compiled train step
        step = step_fn
        sh = {n: to_sharding(shardings.get(n)) for n in step.params}
        p_avals = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype,
                                           sharding=sh[n])
                   for n, a in step.params.items()}
        opt_avals = {
            n: jax.tree.map(
                lambda a, _n=n: aval_of(
                    a, shardings.get(_n)
                    if tuple(getattr(a, "shape", ())) ==
                    tuple(step.params[_n].shape) else None),
                st)
            for n, st in step.opt_state.items()}
        batch = example_args[0] if example_args else {}
        batch_avals = jax.tree.map(lambda a: aval_of(a, batch_spec),
                                   batch,
                                   is_leaf=lambda t: hasattr(t, "_data"))
        lowered = jax.jit(
            step._step_impl,
            donate_argnums=(0, 1, 2) if donate else ()).lower(
            p_avals, opt_avals, jax.ShapeDtypeStruct((), jnp.int32),
            batch_avals, jax.ShapeDtypeStruct((2,), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.float32))
    else:
        # plain callable: flat positional args, shardings a matching
        # flat sequence of PartitionSpec/NamedSharding/None
        avals = tuple(aval_of(x, s)
                      for x, s in zip(example_args, shardings))
        lowered = jax.jit(step_fn).lower(*avals)
    ma = lowered.compile().memory_analysis()
    return int(ma.argument_size_in_bytes + ma.temp_size_in_bytes)


# -- configs (public architecture numbers) -----------------------------------

@dataclass(frozen=True)
class DenseConfig:
    name: str
    vocab: int
    d: int
    ffn: int
    layers: int
    heads: int
    kv_heads: int


@dataclass(frozen=True)
class MoEConfig:
    name: str
    vocab: int
    d: int
    layers: int
    heads: int
    n_experts: int          # routed (fine-grained) experts
    n_shared: int           # always-on shared experts
    top_k: int
    expert_ffn: int         # per-expert hidden size
    kv_heads: int = 0       # 0 → MHA (kv_heads == heads); else GQA
    # note: the planned stack is UNIFORM (lax.scan over layers) — a
    # first-k-dense layer (DeepSeek/ERNIE first_k_dense_replace=1) is
    # approximated as MoE, a <1% params overestimate on 28-layer configs


LLAMA3_8B = DenseConfig("llama3-8b", vocab=128256, d=4096, ffn=14336,
                        layers=32, heads=32, kv_heads=8)
LLAMA3_70B = DenseConfig("llama3-70b", vocab=128256, d=8192, ffn=28672,
                         layers=80, heads=64, kv_heads=8)
DEEPSEEK_MOE_16B = MoEConfig("deepseek-moe-16b", vocab=102400, d=2048,
                             layers=28, heads=16, n_experts=64, n_shared=2,
                             top_k=6, expert_ffn=1408)
# ERNIE-4.5-21B-A3B public shape (models/ernie.py ernie45_moe_config)
ERNIE45_21B_A3B = MoEConfig("ernie45-21b-a3b", vocab=103424, d=2560,
                            layers=28, heads=20, n_experts=64, n_shared=2,
                            top_k=6, expert_ffn=1536, kv_heads=4)
CONFIGS = {c.name: c for c in (LLAMA3_8B, LLAMA3_70B, DEEPSEEK_MOE_16B,
                               ERNIE45_21B_A3B)}


@dataclass
class PlanReport:
    """Per-device memory estimate for one (config, mesh) point.

    ``resident_bytes`` is exact-by-construction: XLA's buffer assignment
    for the arguments (sharded bf16 params + fp32 master/m/v optimizer
    state + batch) of the compiled SPMD program.  ``transient_bytes`` is
    an ANALYTIC estimate of the in-step working set (ZeRO weight gathers,
    pipeline boundary banks, grad accumulators, remat recompute buffers)
    — the host backend's own temp figure is also recorded but its
    scheduler differs too much from the TPU compiler's to assert against
    (it does not reuse scan-body buffers in the assignment accounting).
    """
    config: str
    mesh: dict
    n_devices: int
    params_total: int               # parameter count (global)
    resident_bytes: int             # XLA argument assignment, per device
    transient_bytes: int            # analytic working-set estimate
    host_temp_bytes: int            # host backend temp (diagnostic only)
    seq: int
    microbatch: int
    num_microbatches: int

    @property
    def peak_bytes_per_device(self) -> int:
        return self.resident_bytes + self.transient_bytes

    def fits(self, hbm_gb: float) -> bool:
        return self.peak_bytes_per_device < hbm_gb * (1 << 30)

    def summary(self) -> str:
        gb = 1 << 30
        return (f"{self.config} on {self.mesh} ({self.n_devices} devices): "
                f"{self.params_total / 1e9:.2f}B params, per-device "
                f"{self.peak_bytes_per_device / gb:.2f} GiB "
                f"(resident {self.resident_bytes / gb:.2f} + transient "
                f"{self.transient_bytes / gb:.2f}) "
                "[ESTIMATE: CPU-backend buffer assignment + analytic "
                "working set — re-verify against the real TPU compiler]")


# -- functional Llama pipeline spec ------------------------------------------
# Written directly against stacked per-stage param arrays (the layout
# PipelineTrainStep consumes), Megatron-style on local tp shards.

def _rmsnorm(x, w, eps=1e-5):
    import jax.numpy as jnp
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    inv = jnp.reciprocal(jnp.sqrt(var + eps)).astype(x.dtype)
    return x * inv * w


def _rope(x, theta=500000.0):
    """x [mb, s, h, hd] -> rotary-embedded, positions 0..s-1."""
    import jax.numpy as jnp
    s, hd = x.shape[1], x.shape[-1]
    half = hd // 2
    freqs = theta ** (-np.arange(0, half) / half)
    ang = jnp.arange(s)[:, None] * freqs[None, :]          # [s, half]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def _causal_attention_chunked(q, k, v, q_block=512):
    """Memory-bounded causal attention via a scan over q blocks (the TPU
    path uses the Pallas flash kernel; this blockwise form keeps the
    PLANNER's lowering honest about activation memory instead of
    materializing [mb, h, s, s]).  q,k,v: [mb, s, h, hd] with h already
    GQA-expanded local heads."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    mb, s, h, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    qb = min(q_block, s)
    nblk = (s + qb - 1) // qb
    pad = nblk * qb - s
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
    qr = jnp.moveaxis(q.reshape(mb, nblk, qb, h, hd), 1, 0)
    kT = k.swapaxes(1, 2)          # [mb, h, s, hd]
    vT = v.swapaxes(1, 2)

    def one_block(i, qi):
        qi = qi.swapaxes(1, 2)                     # [mb, h, qb, hd]
        scores = jnp.einsum("bhqd,bhkd->bhqk", qi, kT) * scale
        qpos = i * qb + jnp.arange(qb)[None, None, :, None]
        kpos = jnp.arange(s)[None, None, None, :]
        scores = jnp.where(kpos <= qpos, scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), -1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), vT)
        return i + 1, out

    _, outs = lax.scan(one_block, 0, qr)           # [nblk, mb, h, qb, hd]
    out = jnp.moveaxis(outs, 0, 3)                 # [mb, h, qb, nblk, hd]
    out = out.swapaxes(2, 3).reshape(mb, h, nblk * qb, hd)
    return out[:, :, :s].swapaxes(1, 2)            # [mb, s, h, hd]


def _llama_block(cfg: DenseConfig, x, lp):
    """One decoder block on LOCAL tp shards; psum over 'tp' on the two
    row-parallel projections (mpu contract)."""
    import jax
    import jax.numpy as jnp

    groups = cfg.heads // cfg.kv_heads
    h = _rmsnorm(x, lp["ln1"])
    q = _rope(jnp.einsum("bsd,dhk->bshk", h, lp["wq"]))
    k = _rope(jnp.einsum("bsd,dhk->bshk", h, lp["wk"]))
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    attn = _causal_attention_chunked(q, k, v)
    x = x + jax.lax.psum(jnp.einsum("bshk,hkd->bsd", attn, lp["wo"]), "tp")
    h2 = _rmsnorm(x, lp["ln2"])
    gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", h2, lp["w1"]))
    up = jnp.einsum("bsd,df->bsf", h2, lp["w3"])
    x = x + jax.lax.psum(jnp.einsum("bsf,fd->bsd", gate * up, lp["w2"]),
                         "tp")
    return x


def _llama_stage_fn(cfg: DenseConfig):
    def stage_fn(p, x):
        import jax
        from jax import lax

        from paddle_tpu.distributed.communication import vma_of as _vma_of
        from paddle_tpu.distributed.pipeline import _pvary_axes

        layers = jax.tree.map(lambda a: a[0], p)   # drop pp remnant axis
        # align the scan carry's varying-axes with the layer params' (the
        # block output inherits the params' pp/fsdp variance) — but NOT
        # tp: the Megatron contract keeps activations tp-invariant (every
        # tp-varying product is closed by an explicit psum in the block)
        axes = set()
        for v in jax.tree.leaves(layers):
            axes |= set(_vma_of(v) or ())
        axes -= {"tp"}
        x = _pvary_axes(x, axes - set(_vma_of(x) or ()))

        def blk(xc, lp):
            return _llama_block(cfg, xc, lp), None

        x, _ = lax.scan(blk, x, layers)            # scan over Lps layers
        return x
    return stage_fn


def _llama_first_fn(p, raw):
    return p["embed"][raw]


def vocab_parallel_ce(logits_local, labels, axis="tp"):
    """Cross-entropy over vocab-sharded logits (mpu ParallelCrossEntropy
    pattern, reused by the planner's tp-sharded head)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    vt = logits_local.shape[-1]
    lf = logits_local.astype(jnp.float32)
    # no size-1 shortcut: the psums below are no-ops then, and they also
    # clean the vma (a skipped collective would leave the loss marked
    # varying over a tp axis the cond's other branch never touches)
    off = lax.axis_index(axis) * vt
    # max is for numerical stability only — stop the gradient on the
    # INPUT (pmax has no differentiation rule, so it must see no tracer)
    mx = lax.pmax(jnp.max(lax.stop_gradient(lf), axis=-1), axis)
    se = lax.psum(jnp.sum(jnp.exp(lf - mx[..., None]), axis=-1), axis)
    lse = jnp.log(se) + mx
    local = (labels >= off) & (labels < off + vt)
    idx = jnp.clip(labels - off, 0, vt - 1)
    gold_l = jnp.where(local,
                       jnp.take_along_axis(lf, idx[..., None],
                                           axis=-1).squeeze(-1), 0.0)
    gold = lax.psum(gold_l, axis)
    return jnp.mean(lse - gold)


def _llama_last_fn(p, y, lab):
    import jax.numpy as jnp
    h = _rmsnorm(y, p["ln_f"])
    logits = jnp.einsum("bsd,dv->bsv", h, p["head"])
    return vocab_parallel_ce(logits, lab)


def llama_pipeline_avals(cfg: DenseConfig, S: int, dtype="bfloat16"):
    """(stage_avals, first_avals, last_avals, specs, first_specs,
    last_specs, n_params) — the stacked [S, Lps, ...] layout + 4-D specs
    the pipeline step consumes, as avals (nothing materialized)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if cfg.layers % S:
        raise ValueError(f"{cfg.layers} layers not divisible by pp={S}")
    L = cfg.layers // S
    hd = cfg.d // cfg.heads
    d, f, H, Hk, V = cfg.d, cfg.ffn, cfg.heads, cfg.kv_heads, cfg.vocab
    dt = jnp.dtype(dtype)
    mk = lambda *shape: jax.ShapeDtypeStruct((S, L) + shape, dt)
    stage = {
        "ln1": mk(d), "ln2": mk(d),
        "wq": mk(d, H, hd), "wk": mk(d, Hk, hd), "wv": mk(d, Hk, hd),
        "wo": mk(H, hd, d),
        "w1": mk(d, f), "w3": mk(d, f), "w2": mk(f, d),
    }
    specs = {
        "ln1": P("pp", None, None), "ln2": P("pp", None, None),
        "wq": P("pp", None, "fsdp", "tp", None),
        "wk": P("pp", None, "fsdp", "tp", None),
        "wv": P("pp", None, "fsdp", "tp", None),
        "wo": P("pp", None, "tp", None, "fsdp"),
        "w1": P("pp", None, "fsdp", "tp"),
        "w3": P("pp", None, "fsdp", "tp"),
        "w2": P("pp", None, "tp", "fsdp"),
    }
    first = {"embed": jax.ShapeDtypeStruct((V, d), dt)}
    first_specs = {"embed": P("fsdp", None)}
    last = {"head": jax.ShapeDtypeStruct((d, V), dt),
            "ln_f": jax.ShapeDtypeStruct((d,), dt)}
    last_specs = {"head": P("fsdp", "tp"), "ln_f": P()}
    n_params = (S * L * (2 * d + d * H * hd + 2 * d * Hk * hd + H * hd * d
                         + 3 * d * f) + 2 * V * d + d)
    return stage, first, last, specs, first_specs, last_specs, n_params


# -- the abstract-lowering harness -------------------------------------------

def _lower_pipeline_step(stage_fn, first_fn, last_fn, stage_avals,
                         first_avals, last_avals, specs, first_specs,
                         last_specs, mesh, M, optimizer, batch_shape, *,
                         scatter_grads_per_tick=True, remat=True):
    """Lower the exact PipelineTrainStep program on avals."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.distributed.pipeline import build_pipeline_step_fn

    flat_avals, flat_specs = {}, {}
    for n, a in stage_avals.items():
        flat_avals[n] = a
        flat_specs[n] = specs[n]
    for prefix, tree, tsp in (("first/", first_avals, first_specs),
                              ("last/", last_avals, last_specs)):
        for n, a in tree.items():
            flat_avals[prefix + n] = a
            flat_specs[prefix + n] = tsp[n]

    sh = lambda spec: NamedSharding(mesh, spec)
    p_avals = {n: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=sh(flat_specs[n]))
               for n, a in flat_avals.items()}
    opt_shapes = jax.eval_shape(
        optimizer.init_state_pytree,
        {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
         for n, a in flat_avals.items()})
    opt_avals = {
        n: jax.tree.map(
            lambda s, _n=n: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=sh(flat_specs[_n])
                if s.shape == flat_avals[_n].shape else sh(P())),
            st)
        for n, st in opt_shapes.items()}

    dp = "dp" if "dp" in mesh.axis_names else None
    fsdp = "fsdp" if "fsdp" in mesh.axis_names else None
    step = build_pipeline_step_fn(
        stage_fn, first_fn, last_fn, optimizer, mesh, M, flat_specs,
        pp_axis="pp", dp_axis=dp, fsdp_axis=fsdp, remat=remat,
        has_first=True, has_last=True,
        scatter_grads_per_tick=scatter_grads_per_tick)

    batch_sh = sh(P(None, tuple(a for a in (dp, fsdp) if a)))
    mb_aval = jax.ShapeDtypeStruct(batch_shape, jnp.int32,
                                   sharding=batch_sh)
    step_aval = jax.ShapeDtypeStruct((), jnp.int32)
    lr_aval = jax.ShapeDtypeStruct((), jnp.float32)
    return jax.jit(step, donate_argnums=(0, 1, 2)).lower(
        p_avals, opt_avals, step_aval, mb_aval, mb_aval, lr_aval)


def _make_mesh(pp, dp, fsdp, tp):
    import jax
    from jax.sharding import Mesh

    n = pp * dp * fsdp * tp
    # jax.devices() lists only the DEFAULT platform's devices; ask for the
    # virtual CPU platform explicitly (it exists even when a TPU plugin is
    # the default), falling back to whatever is available
    try:
        devs = jax.devices("cpu")
    except Exception:
        devs = jax.devices()
    if len(devs) < n:
        import os
        raise RuntimeError(
            f"need {n} devices for mesh pp={pp} dp={dp} fsdp={fsdp} "
            f"tp={tp}; have {len(devs)} — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n} before jax init "
            f"[debug: XLA_FLAGS={os.environ.get('XLA_FLAGS')!r} "
            f"platforms={jax.config.jax_platforms!r} "
            f"all={[d.platform for d in jax.devices()][:3]!r}]")
    arr = np.array(devs[:n]).reshape(pp, dp, fsdp, tp)
    return Mesh(arr, ("pp", "dp", "fsdp", "tp"))


def _report(cfg_name, mesh_dims, n_params, compiled, seq, mb, M,
            transient_bytes):
    ma = compiled.memory_analysis()
    return PlanReport(
        config=cfg_name, mesh=mesh_dims,
        n_devices=int(np.prod(list(mesh_dims.values()))),
        params_total=int(n_params),
        resident_bytes=int(ma.argument_size_in_bytes),
        transient_bytes=int(transient_bytes),
        host_temp_bytes=int(ma.temp_size_in_bytes),
        seq=seq, microbatch=mb, num_microbatches=M)


def _llama_transient_bytes(cfg: DenseConfig, pp, fsdp, tp, seq, mb_size,
                           scatter_grads_per_tick):
    """Analytic per-device working set of the 4-D step (see PlanReport):
    ZeRO-gathered stage weights (alive across the tick scan), gathered
    embed/head, pipeline boundary banks, fp32 grad accumulators, and the
    remat recompute buffer of one block."""
    d, f, V = cfg.d, cfg.ffn, cfg.vocab
    hd = d // cfg.heads
    block_params = cfg.layers * (2 * d + d * cfg.heads * hd
                                 + 2 * d * cfg.kv_heads * hd
                                 + cfg.heads * hd * d + 3 * d * f)
    gathered_stage = block_params // pp // tp * 2          # bf16
    gathered_embed = V * d * 2                             # fsdp-gathered
    gathered_head = V * d // tp * 2
    banks = (2 * pp + 2) * mb_size * seq * d * 2           # in/cot + wires
    grad_stage = block_params // pp // tp * 4
    if scatter_grads_per_tick:
        grad_stage //= fsdp
    grad_groups = (V * d + V * d // tp) * 4                # fp32, gathered
    remat = mb_size * seq * (6 * d + 2 * f) * 2
    attn_probs = mb_size * (cfg.heads // tp) * 512 * seq * 4
    return (gathered_stage + gathered_embed + gathered_head + banks
            + grad_stage + grad_groups + remat + attn_probs)


def plan_llama(cfg: DenseConfig, *, pp: int, dp: int = 1, fsdp: int = 1,
               tp: int = 1, seq: int = 8192, mb_size: int = 1,
               num_microbatches: Optional[int] = None,
               compute_dtype="bfloat16", learning_rate=3e-4,
               scatter_grads_per_tick=True) -> PlanReport:
    """AOT-compile cfg's full 4-D train step (1F1B + ZeRO + tp + AdamW
    master weights) and return the per-device memory report."""
    from paddle_tpu.optimizer import AdamW

    mesh = _make_mesh(pp, dp, fsdp, tp)
    M = num_microbatches or max(2 * pp, 2)
    (stage, first, last, specs, fsp, lsp,
     n_params) = llama_pipeline_avals(cfg, pp, compute_dtype)
    opt = AdamW(learning_rate=learning_rate, multi_precision=True)
    # per-data-shard microbatch: global microbatch = mb_size * dp * fsdp
    batch_shape = (M, mb_size * dp * fsdp, seq)
    lowered = _lower_pipeline_step(
        _llama_stage_fn(cfg), _llama_first_fn, _llama_last_fn,
        stage, first, last, specs, fsp, lsp, mesh, M, opt, batch_shape,
        scatter_grads_per_tick=scatter_grads_per_tick)
    compiled = lowered.compile()
    transient = _llama_transient_bytes(cfg, pp, fsdp, tp, seq, mb_size,
                                       scatter_grads_per_tick)
    return _report(cfg.name, {"pp": pp, "dp": dp, "fsdp": fsdp, "tp": tp},
                   n_params, compiled, seq, mb_size, M, transient)


# -- MoE plan (GSPMD path: dp x fsdp x ep, no pipeline) ----------------------

def moe_avals(cfg: MoEConfig, dtype="bfloat16"):
    """DeepSeekMoE-style stack: dense attention + shared experts +
    fine-grained routed experts, layers stacked for lax.scan."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    d, L, H, E, fe = cfg.d, cfg.layers, cfg.heads, cfg.n_experts, \
        cfg.expert_ffn
    Hk = cfg.kv_heads or H
    hd = d // H
    dt = jnp.dtype(dtype)
    mk = lambda *shape: jax.ShapeDtypeStruct((L,) + shape, dt)
    params = {
        "ln1": mk(d), "ln2": mk(d),
        "wq": mk(d, H, hd), "wk": mk(d, Hk, hd), "wv": mk(d, Hk, hd),
        "wo": mk(H, hd, d),
        "gate": mk(d, E),
        # routed experts: [L, E, ...] sharded over ep
        "ew1": mk(E, d, fe), "ew3": mk(E, d, fe), "ew2": mk(E, fe, d),
        # shared experts: always-on, fused into one ffn of width n_shared*fe
        "sw1": mk(d, cfg.n_shared * fe), "sw3": mk(d, cfg.n_shared * fe),
        "sw2": mk(cfg.n_shared * fe, d),
        "embed": jax.ShapeDtypeStruct((cfg.vocab, d), dt),
        "head": jax.ShapeDtypeStruct((d, cfg.vocab), dt),
        "ln_f": jax.ShapeDtypeStruct((d,), dt),
    }
    specs = {
        "ln1": P(), "ln2": P(),
        "wq": P(None, "fsdp", "tp", None), "wk": P(None, "fsdp", "tp", None),
        "wv": P(None, "fsdp", "tp", None), "wo": P(None, "tp", None, "fsdp"),
        "gate": P(None, "fsdp", None),
        "ew1": P(None, "ep", "fsdp", None), "ew3": P(None, "ep", "fsdp", None),
        "ew2": P(None, "ep", None, "fsdp"),
        "sw1": P(None, "fsdp", "tp"), "sw3": P(None, "fsdp", "tp"),
        "sw2": P(None, "tp", "fsdp"),
        "embed": P("fsdp", None),
        "head": P("fsdp", "tp"),
        "ln_f": P(),
    }
    n_params = (L * (2 * d + 2 * d * H * hd + 2 * d * Hk * hd + d * E
                     + 3 * E * d * fe + 3 * d * cfg.n_shared * fe)
                + 2 * cfg.vocab * d + d)
    return params, specs, n_params


def _moe_block(cfg: MoEConfig, x, lp):
    """Dense attention + DeepSeek-style MoE ffn (shared + routed top-k,
    dense einsum dispatch — GSPMD turns the [T,E,C] einsums into a2a)."""
    import jax
    import jax.numpy as jnp

    h = _rmsnorm(x, lp["ln1"])
    q = _rope(jnp.einsum("bsd,dhk->bshk", h, lp["wq"]), theta=10000.0)
    k = _rope(jnp.einsum("bsd,dhk->bshk", h, lp["wk"]), theta=10000.0)
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
    if k.shape[2] != q.shape[2]:            # GQA: repeat KV to q heads
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    attn = _causal_attention_chunked(q, k, v)
    x = x + jnp.einsum("bshk,hkd->bsd", attn, lp["wo"])

    h2 = _rmsnorm(x, lp["ln2"])
    B, s, d = h2.shape
    T = B * s
    ht = h2.reshape(T, d)
    # shared experts: plain ffn
    sh = jax.nn.silu(ht @ lp["sw1"]) * (ht @ lp["sw3"])
    shared_out = sh @ lp["sw2"]
    # routed: the LIBRARY's gating (distributed.moe.top_k_gating), so the
    # plan compiles the same dispatch program the shipped MoELayer runs
    from paddle_tpu.distributed.moe import top_k_gating

    E = cfg.n_experts
    C = max(1, int(2 * cfg.top_k * T // E))
    logits = (ht @ lp["gate"]).astype(jnp.float32)
    combine, dispatch, _aux = top_k_gating(logits, k=cfg.top_k, capacity=C)
    xe = jnp.einsum("tec,td->ecd", dispatch.astype(ht.dtype), ht)
    hh = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, lp["ew1"])) * \
        jnp.einsum("ecd,edf->ecf", xe, lp["ew3"])
    ye = jnp.einsum("ecf,efd->ecd", hh, lp["ew2"])        # [E, C, d]
    routed = jnp.einsum("tec,ecd->td", combine.astype(ht.dtype), ye)
    return x + (shared_out + routed).reshape(B, s, d)


def plan_moe(cfg: MoEConfig, *, dp: int = 1, fsdp: int = 1, ep: int = 8,
             tp: int = 1, seq: int = 4096, batch: int = 8,
             compute_dtype="bfloat16", learning_rate=3e-4) -> PlanReport:
    """AOT-compile the DeepSeekMoE train step on a (dp, fsdp, ep, tp)
    GSPMD mesh (expert parallelism via sharded [E, ...] einsum dispatch;
    XLA inserts the all_to_alls) and return the memory report."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.optimizer import AdamW

    n = dp * fsdp * ep * tp
    try:
        devs = jax.devices("cpu")
    except Exception:
        devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(f"need {n} devices, have {len(devs)}")
    mesh = Mesh(np.array(devs[:n]).reshape(dp, fsdp, ep, tp),
                ("dp", "fsdp", "ep", "tp"))
    params, specs, n_params = moe_avals(cfg, compute_dtype)
    opt = AdamW(learning_rate=learning_rate, multi_precision=True)

    def loss_fn(p, ids, labels):
        x = p["embed"][ids]

        def blk(xc, lp):
            return _moe_block(cfg, xc, lp), None

        x, _ = lax.scan(jax.checkpoint(blk), x,
                        {k: v for k, v in p.items()
                         if k not in ("embed", "head", "ln_f")})
        h = _rmsnorm(x, p["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", h, p["head"])
        lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), -1)
        gold = jnp.take_along_axis(logits.astype(jnp.float32),
                                   labels[..., None], -1).squeeze(-1)
        return jnp.mean(lse - gold)

    def step(p, opt_state, step_count, ids, labels, lr):
        loss, grads = jax.value_and_grad(loss_fn)(p, ids, labels)
        step_count = step_count + 1
        new_p, new_s = opt.apply_gradients(p, grads, opt_state, step_count,
                                           lr=lr)
        return loss, new_p, new_s, step_count

    sh = lambda spec: NamedSharding(mesh, spec)
    p_avals = {nme: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                         sharding=sh(specs[nme]))
               for nme, a in params.items()}
    opt_shapes = jax.eval_shape(
        opt.init_state_pytree,
        {nme: jax.ShapeDtypeStruct(a.shape, a.dtype)
         for nme, a in params.items()})
    opt_avals = {
        nme: jax.tree.map(
            lambda s, _n=nme: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=sh(specs[_n])
                if s.shape == params[_n].shape else sh(P())),
            st)
        for nme, st in opt_shapes.items()}
    ids_aval = jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                    sharding=sh(P(("dp", "fsdp"))))
    lowered = jax.jit(step, donate_argnums=(0, 1, 2)).lower(
        p_avals, opt_avals, jax.ShapeDtypeStruct((), jnp.int32),
        ids_aval, ids_aval, jax.ShapeDtypeStruct((), jnp.float32))
    compiled = lowered.compile()
    # analytic working set: fsdp-gathered weights of ONE layer (the scan
    # is checkpointed per layer), layer-boundary activations, the [T,E,C]
    # dispatch/combine buffers, and the fp32 grad shards
    d, fe, E = cfg.d, cfg.expert_ffn, cfg.n_experts
    hd = d // cfg.heads
    layer_params = (2 * d + 4 * d * cfg.heads * hd + d * E
                    + 3 * E * d * fe + 3 * d * cfg.n_shared * fe)
    b_local = max(1, batch // (dp * fsdp))
    T = b_local * seq
    C = max(1, int(2 * cfg.top_k * T // E))
    transient = (layer_params // ep * 2                      # gathered layer
                 + cfg.layers * b_local * seq * d * 2        # boundaries
                 + 3 * T * E * C * 4                         # disp/comb/pos
                 + 2 * (E // ep) * C * d * 4                 # expert io
                 + n_params // (dp * fsdp * ep) * 4)         # grad shards
    return _report(cfg.name, {"dp": dp, "fsdp": fsdp, "ep": ep, "tp": tp},
                   n_params, compiled, seq, batch, 1, transient)


def _main():
    import argparse
    import json

    # NOTE: no jax.config.update("jax_platforms", ...) here — the package
    # import above may already have initialized backends, and a platform
    # re-selection would re-create the CPU client AFTER the one-shot
    # XLA_FLAGS parse, silently dropping --xla_force_host_platform_
    # device_count (observed: 64 devices become 1).  _make_mesh targets
    # jax.devices("cpu") explicitly, which works under any default
    # platform.

    ap = argparse.ArgumentParser(description="flagship capacity planner")
    ap.add_argument("--config", required=True, choices=sorted(CONFIGS))
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--fsdp", type=int, default=8)
    ap.add_argument("--ep", type=int, default=8)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--hbm-gb", type=float, default=95.0,
                    help="per-chip HBM to check against (v5p: 95)")
    args = ap.parse_args()
    cfg = CONFIGS[args.config]
    if isinstance(cfg, MoEConfig):
        rep = plan_moe(cfg, dp=args.dp, fsdp=args.fsdp, ep=args.ep,
                       tp=args.tp, seq=args.seq)
    else:
        rep = plan_llama(cfg, pp=args.pp, dp=args.dp, fsdp=args.fsdp,
                         tp=args.tp, seq=args.seq)
    print(rep.summary())
    print(json.dumps({"fits": rep.fits(args.hbm_gb),
                      "peak_gib": rep.peak_bytes_per_device / (1 << 30)}))


if __name__ == "__main__":
    _main()
