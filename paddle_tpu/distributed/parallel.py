"""DataParallel facade.

Reference parity: ``paddle.DataParallel`` (distributed/parallel.py) — wraps a
Layer, and a C++ ``Reducer`` (fluid/imperative/reducer.cc, reducer.h:129)
buckets gradients into ~25MB groups and allreduces them asynchronously as
backward produces them; ``no_sync`` suppresses the sync for gradient
accumulation.

TPU-native design: gradient synchronisation is not a runtime concern — when
the batch is sharded on the ``dp`` mesh axis inside one jit'd step, XLA emits
a fused reduce of the grads (the exact thing the Reducer's bucketing
approximates by hand, but scheduled by the compiler and overlapped with the
backward automatically).  ``DataParallel`` therefore only records the batch
PartitionSpec and passes calls through.
"""

from __future__ import annotations

import contextlib

from paddle_tpu.nn.layer import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, dp_axis: str = "dp"):
        super().__init__()
        self._layers = layers
        self.dp_axis = dp_axis
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Grad-accumulation context (reference parallel.py no_sync).  With
        compiler-inserted reduction there is nothing to suppress: accumulate
        microbatch grads in the step function instead.  Kept for parity."""
        yield

    def batch_spec(self):
        from jax.sharding import PartitionSpec as P
        return P(self.dp_axis)

    # passthroughs so the wrapper is transparent, like the reference
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)
