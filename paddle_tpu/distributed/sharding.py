"""Parameter/optimizer sharding — ZeRO stages as GSPMD sharding plans.

Reference parity: ``paddle.distributed.sharding.group_sharded_parallel``
(distributed/sharding/group_sharded.py:37) and the stage machinery —
``DygraphShardingOptimizer`` (stage 1, dygraph_sharding_optimizer.py:29),
``GroupShardedStage2``+``GroupShardedOptimizerStage2`` (stage 2,
group_sharded_stage2.py:46), ``GroupShardedStage3`` (stage 3,
group_sharded_stage3.py:59 with allgather pre-hooks / release post-hooks).

TPU-native design: the reference implements ZeRO with grad-bucket
reduce-scatters, broadcast of updated shards, and forward allgather hooks —
all runtime machinery.  Under GSPMD every stage is just a *sharding choice*:

* stage 1 (``os``): optimizer state sharded on the sharding axis, params
  replicated.  XLA reduce-scatters grads into the update and all-gathers
  fresh params — the same comm volume as the hand-written stage 1.
* stage 2 (``os_g``): identical compiled form (grads never exist replicated
  inside a fused jit step; stage 1 vs 2 is a distinction about runtime
  buffers the compiler already avoids).
* stage 3 (``p_g_os``): params sharded **at rest** — FSDP.  XLA inserts the
  per-layer allgather/release schedule the reference builds with hooks
  (ForwardPostHooks, group_sharded_stage3.py:809).

``shard_plan`` returns the PartitionSpecs that TrainStep consumes.

Collective latency hiding (ISSUE 15): ``PADDLE_TPU_COLLECTIVE_OVERLAP``
opts the training path into expressing the per-layer FSDP weight
all-gathers as an explicit, layer-ordered prefetch chain that XLA's
async-collective scheduler can hide under the previous layer's compute
(``TrainStep._overlap_prefetch``), and flips the sequence-parallel ring
exchange to issue its ``ppermute`` before the fold it overlaps with.
This module owns the knob, the per-layer prefetch schedule
(:func:`prefetch_groups`), the gathered-layout helper
(:func:`gathered_spec`) and the shared trace-time path counter — the
autoshard cost model discounts collectives by the same knob (see
``analysis.passes.cost_model.default_overlap_fraction``).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence

__all__ = ["group_sharded_parallel", "shard_plan", "ShardingPlan",
           "overlap_enabled", "prefetch_groups", "gathered_spec",
           "spec_mentions_axis", "overlap_path_counter"]


def overlap_enabled() -> bool:
    """The PADDLE_TPU_COLLECTIVE_OVERLAP knob — default off, and off
    reproduces the exact previous jaxpr everywhere it is consulted."""
    return os.environ.get("PADDLE_TPU_COLLECTIVE_OVERLAP", "") \
        .strip().lower() in ("1", "true", "on", "yes")


def overlap_path_counter():
    """Trace-time telemetry shared by every overlap-expressed path
    (TrainStep FSDP prefetch, sequence-parallel ring exchange) — the
    same idiom as the fused-block path counter, surfaced in bench.py's
    ``detail.paths``."""
    from paddle_tpu.observability import default_registry
    return default_registry().counter(
        "paddle_tpu_collective_overlap_total",
        "collectives expressed overlap-friendly at trace time",
        labelnames=("path",))


_LAYER_RE = re.compile(r"(?:^|\.)layers?[._](\d+)\.")


def prefetch_groups(names: Sequence[str]) -> List[List[str]]:
    """Order parameter names into the per-layer prefetch schedule:
    non-layer params first (embeddings / final norm / lm head — wanted
    hot at the step's edges), then one group per decoder layer in layer
    order.  The schedule is the issue order of the prefetch chain: group
    k's gathers are chained after group k-1's, decoupled from their
    consumers, so layer i+1's gather streams under layer i's compute."""
    layers: Dict[int, List[str]] = {}
    rest: List[str] = []
    for n in names:
        m = _LAYER_RE.search(n)
        if m:
            layers.setdefault(int(m.group(1)), []).append(n)
        else:
            rest.append(n)
    out: List[List[str]] = [rest] if rest else []
    out.extend(layers[i] for i in sorted(layers))
    return out


def spec_mentions_axis(spec, axis: str) -> bool:
    for e in spec:
        if e == axis or (isinstance(e, tuple) and axis in e):
            return True
    return False


def gathered_spec(spec, axis: str):
    """``spec`` with ``axis`` removed — the layout of a ZeRO-3 weight
    AFTER its all-gather (what the forward consumes)."""
    from jax.sharding import PartitionSpec as P

    def drop(e):
        if e == axis:
            return None
        if isinstance(e, tuple):
            kept = tuple(a for a in e if a != axis)
            if not kept:
                return None
            return kept[0] if len(kept) == 1 else kept
        return e

    return P(*(drop(e) for e in spec))


class ShardingPlan:
    """param_specs / opt_state mode for a ZeRO level on a named mesh axis."""

    def __init__(self, level: str, axis: str,
                 param_specs: Dict[str, object],
                 shard_opt_state: bool):
        self.level = level
        self.axis = axis
        self.param_specs = param_specs
        self.shard_opt_state = shard_opt_state


def _largest_divisible_dim(shape, n: int) -> Optional[int]:
    """Pick the tensor dim to shard: largest dim divisible by axis size."""
    best = None
    for i, d in sorted(enumerate(shape), key=lambda t: -t[1]):
        if d % n == 0 and d >= n:
            best = i
            break
    return best


def shard_plan(model, level: str = "p_g_os", axis: str = "sharding",
               axis_size: Optional[int] = None,
               base_specs: Optional[Dict[str, object]] = None) -> ShardingPlan:
    """Compute PartitionSpecs implementing a ZeRO level.

    `base_specs` (e.g. TP specs from ``Model.partition_specs``) are
    composed with: stage-3 sharding uses a free (unsharded) dim of each
    weight, mirroring how the reference composes sharding with mp/pp.
    """
    from jax.sharding import PartitionSpec as P
    if axis_size is None:
        import jax
        axis_size = jax.device_count()
    base = dict(base_specs or {})
    specs: Dict[str, object] = {}
    if level in ("os", "os_g"):
        specs = {n: base.get(n, P())
                 for n in model.state_dict(keep_vars=True)}
    elif level == "p_g_os":
        for name, t in model.state_dict(keep_vars=True).items():
            spec = base.get(name, P())
            parts = list(spec) + [None] * (t.ndim - len(list(spec)))
            if axis in [p for p in parts if p is not None] or any(
                    isinstance(p, tuple) and axis in p for p in parts):
                specs[name] = spec
                continue
            free = [i for i, p in enumerate(parts) if p is None]
            shape = t.shape
            pick = None
            for i in sorted(free, key=lambda i: -shape[i]):
                if shape[i] % axis_size == 0 and shape[i] >= axis_size:
                    pick = i
                    break
            if pick is None:
                specs[name] = spec  # too small to shard — stays as-is
            else:
                parts[pick] = axis
                specs[name] = P(*parts)
    else:
        raise ValueError(f"unknown sharding level '{level}' "
                         "(expected os | os_g | p_g_os)")
    return ShardingPlan(level, axis, specs,
                        shard_opt_state=level in ("os", "os_g", "p_g_os"))


def group_sharded_parallel(model, optimizer, level: str = "p_g_os",
                           scaler=None, group=None, axis: str = "sharding",
                           axis_size: Optional[int] = None,
                           sync_buffers: bool = False,
                           buffer_max_size: int = 0, **_ignored):
    """API-parity entry (reference group_sharded.py:37).  Returns
    (model, optimizer, scaler) with the computed ``ShardingPlan`` attached
    as ``model._sharding_plan`` / ``optimizer._sharding_plan`` — feed
    ``plan.param_specs`` to ``TrainStep(mesh=..., param_specs=...)``."""
    plan = shard_plan(model, level=level, axis=axis, axis_size=axis_size)
    model._sharding_plan = plan
    if optimizer is not None:
        optimizer._sharding_plan = plan
    return model, optimizer, scaler
