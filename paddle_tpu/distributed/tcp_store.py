"""TCPStore — native rendezvous KV store (ctypes over csrc/store).

Reference parity: ``paddle.distributed`` TCPStore
(paddle/phi/core/distributed/store/tcp_store.h:120 — the bootstrap that
``init_parallel_env`` uses to exchange NCCL ids).  On TPU the heavy comm
setup is ``jax.distributed``; this store covers (a) pre-jax rendezvous —
electing/advertising the coordinator address — and (b) user control-plane
sync (barriers, small blobs) the reference exposes on its store.
"""

from __future__ import annotations

import ctypes
import os
import random
import threading
import time
import uuid
from typing import Optional

__all__ = ["TCPStore"]

# per-process op-id namespace for retry-safe adds: nonce makes tokens
# unique across unrelated processes, the sequence across calls
_ADD_NONCE = uuid.uuid4().hex[:12]
_ADD_SEQ = 0
_ADD_SEQ_LOCK = threading.Lock()


def _store_metrics():
    """Retry telemetry: a rising connect-retry counter during job start
    is the 'rank-0 store is slow' signature; op retries after that point
    mean the store host is struggling."""
    from paddle_tpu.observability import default_registry
    reg = default_registry()
    return {
        "connect_retries": reg.counter(
            "paddle_tpu_tcp_store_connect_retries_total",
            "TCPStore client connect attempts that failed and were "
            "retried with backoff"),
        "op_retries": reg.counter(
            "paddle_tpu_tcp_store_op_retries_total",
            "TCPStore operations that failed transiently and were "
            "retried", labelnames=("op",)),
    }


def _lib():
    from paddle_tpu.utils.cpp_extension import load_native
    # required_symbol names the NEWEST C entry point so a stale .so
    # (built before the idempotent-add protocol) triggers a rebuild
    lib = load_native("store", required_symbol="tcpstore_add_tok")
    lib.tcpstore_server_start.restype = ctypes.c_void_p
    lib.tcpstore_server_start.argtypes = [ctypes.c_int]
    lib.tcpstore_server_stop.argtypes = [ctypes.c_void_p]
    lib.tcpstore_connect.restype = ctypes.c_int
    lib.tcpstore_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_int]
    lib.tcpstore_set.restype = ctypes.c_int
    lib.tcpstore_set.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_get.restype = ctypes.c_int
    lib.tcpstore_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_add.restype = ctypes.c_int64
    lib.tcpstore_add.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_int64]
    lib.tcpstore_add_tok.restype = ctypes.c_int64
    lib.tcpstore_add_tok.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_int64, ctypes.c_char_p]
    lib.tcpstore_check.restype = ctypes.c_int
    lib.tcpstore_check.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.tcpstore_server_wait_clients.restype = ctypes.c_int
    lib.tcpstore_server_wait_clients.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_int, ctypes.c_int]
    lib.tcpstore_close.argtypes = [ctypes.c_int]
    return lib


class TCPStore:
    """API parity with the reference TCPStore: set/get/add/wait + barrier.

    is_master=True starts the native server in-process (host 0); every
    process (master included) connects a client."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0,
                 connect_timeout: Optional[float] = None):
        self._lib = _lib()
        self._server = None
        self.host = host
        self.port = port
        self.world_size = world_size
        self.timeout = timeout
        self._metrics = _store_metrics()
        # lazily-connected extra client sockets for get_many: parallel
        # bulk reads (peer state snapshots) pipeline the per-get round
        # trip; ctypes releases the GIL during the blocking C recv, so
        # python threads on separate fds genuinely overlap
        self._bulk_fds = []
        self._bulk_lock = threading.Lock()
        from paddle_tpu.observability.tracing import tracer
        # store ops get spans (root_eligible=False: a bare heartbeat
        # set() outside any trace must not crowd the slow-trace table)
        self._tracer = tracer()
        if is_master:
            self._server = self._lib.tcpstore_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
        # connect with exponential backoff + jitter: joining ranks beat a
        # slow-starting rank-0 store to the socket all the time — a
        # refused connection during the window is a retry, not a crash.
        # The master connecting to its own in-process server skips the
        # patience (a local refusal there is a real bug).
        budget = 0.5 if is_master else (
            timeout if connect_timeout is None else connect_timeout)
        deadline = time.monotonic() + budget
        delay = 0.05
        from paddle_tpu.robustness import fault_fires
        while True:
            fd = -2 if fault_fires("tcp_store.connect", host=host,
                                   port=port) else \
                self._lib.tcpstore_connect(
                    host.encode(), port,
                    int(max(0.05, deadline - time.monotonic()) * 1000))
            if fd >= 0:
                self._fd = fd
                break
            if time.monotonic() + delay > deadline:
                self._fd = -1
                raise RuntimeError(
                    f"TCPStore: cannot connect {host}:{port} after "
                    f"{budget:.1f}s of retries")
            self._metrics["connect_retries"].inc()
            time.sleep(delay * (1.0 + random.random() * 0.25))
            delay = min(delay * 2, 2.0)

    def _retry_op(self, op: str, attempt, attempts: int = 3):
        """Bounded retry with backoff for IDEMPOTENT ops.  ``add`` was
        historically excluded (a retried add whose first round-trip
        succeeded server-side but lost its response would double-count);
        it now rides an op-id idempotency token — the server dedups a
        resent token and replays the recorded result — so the same
        bounded retry covers it."""
        from paddle_tpu.robustness import fault_point
        delay = 0.02
        for i in range(attempts):
            try:
                fault_point("tcp_store.op", op=op, attempt=i)
                return attempt()
            except RuntimeError:
                if i == attempts - 1:
                    raise
                self._metrics["op_retries"].labels(op=op).inc()
                time.sleep(delay * (1.0 + random.random() * 0.25))
                delay *= 2

    def set(self, key: str, value):
        if isinstance(value, (bytearray, memoryview)):
            value = bytes(value)
        data = value if isinstance(value, bytes) else str(value).encode()

        def attempt():
            rc = self._lib.tcpstore_set(self._fd, key.encode(), data,
                                        len(data))
            if rc != 0:
                raise RuntimeError("TCPStore.set failed")
        with self._tracer.span("store.set", key=key,
                               root_eligible=False):
            self._retry_op("set", attempt)

    def get(self, key: str, wait: bool = True,
            max_bytes: int = 1 << 20) -> bytes:
        """Blocking get (reference semantics: waits for the key).  The
        span covers the whole wait — a control-plane stall shows up as
        one long ``store.get`` in the trace, not as unexplained gap.
        ``max_bytes`` sizes the receive buffer (bulk consumers — peer
        state snapshots — raise it to cut round trips)."""
        buf = ctypes.create_string_buffer(max_bytes)
        deadline = time.monotonic() + self.timeout
        with self._tracer.span("store.get", key=key, wait=wait,
                               root_eligible=False):
            while True:
                n = self._lib.tcpstore_get(self._fd, key.encode(), buf,
                                           len(buf))
                if n >= 0:
                    return buf.raw[:n]
                if n == -1:
                    raise RuntimeError("TCPStore.get failed")
                if not wait:
                    raise KeyError(key)
                if time.monotonic() > deadline:
                    raise TimeoutError(f"TCPStore.get({key}) timed out")
                time.sleep(0.01)

    def _add_once(self, key: str, amount: int, token: str) -> int:
        """One token-carrying add round-trip.  Resending the SAME token
        is safe: the server's dedup ledger replays the first
        application's result without re-adding (the double-count hazard
        a bare retried ``add`` had)."""
        v = self._lib.tcpstore_add_tok(self._fd, key.encode(), amount,
                                       token.encode())
        if v == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return int(v)

    def _get_on_fd(self, fd: int, key: str, max_bytes: int) -> bytes:
        """One non-waiting get on a specific client fd (bulk path)."""
        buf = ctypes.create_string_buffer(max_bytes)
        n = self._lib.tcpstore_get(fd, key.encode(), buf, len(buf))
        if n == -2:
            raise KeyError(key)
        if n < 0:
            raise RuntimeError(f"TCPStore.get({key}) failed")
        return buf.raw[:n]

    def _bulk_pool(self, n: int):
        with self._bulk_lock:
            while len(self._bulk_fds) < n:
                fd = self._lib.tcpstore_connect(
                    self.host.encode(), self.port,
                    int(min(self.timeout, 10.0) * 1000))
                if fd < 0:
                    break
                self._bulk_fds.append(fd)
            return list(self._bulk_fds)

    def get_many(self, keys, max_bytes: int = 1 << 20,
                 parallel: int = 4):
        """Fetch several present keys, overlapping round trips across a
        small pool of dedicated connections (the bulk-restore path for
        peer snapshots).  Returns values in key order; falls back to
        sequential gets when the pool can't be built."""
        keys = list(keys)
        if len(keys) < 2:
            return [self.get(k, wait=False, max_bytes=max_bytes)
                    for k in keys]
        fds = self._bulk_pool(min(parallel, len(keys)))
        if not fds:
            return [self.get(k, wait=False, max_bytes=max_bytes)
                    for k in keys]
        out = [None] * len(keys)

        def fetch(fd, i):
            out[i] = self._get_on_fd(fd, keys[i], max_bytes)
        self._bulk_run(fds, keys, fetch)
        return out

    def _bulk_run(self, fds, keys, fetch):
        errs = []

        def worker(slot: int):
            fd = fds[slot]
            for i in range(slot, len(keys), len(fds)):
                try:
                    fetch(fd, i)
                except Exception as e:  # noqa: BLE001 — re-raised below
                    errs.append(e)
                    return
        threads = [threading.Thread(target=worker, args=(s,))
                   for s in range(len(fds))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def _get_into_fd(self, fd: int, key: str, view) -> int:
        """Non-waiting get received DIRECTLY into a writable buffer
        slice — no intermediate ctypes buffer, no copy."""
        buf = (ctypes.c_char * len(view)).from_buffer(view)
        n = self._lib.tcpstore_get(fd, key.encode(), buf, len(view))
        if n == -2:
            raise KeyError(key)
        if n < 0:
            raise RuntimeError(f"TCPStore.get({key}) failed")
        return n

    def get_many_into(self, keys, views, parallel: int = 4):
        """Zero-copy bulk fetch: each present key's value lands in its
        (exactly-sized) writable view, round trips overlapped across
        the bulk connection pool.  The peer-snapshot restore path:
        parts land at their final offsets in one preallocated buffer.
        Returns the per-key byte counts."""
        keys, views = list(keys), list(views)
        counts = [0] * len(keys)
        fds = self._bulk_pool(min(parallel, len(keys))) or [self._fd]

        def fetch(fd, i):
            counts[i] = self._get_into_fd(fd, keys[i], views[i])
        self._bulk_run(fds, keys, fetch)
        return counts

    def add(self, key: str, amount: int = 1) -> int:
        """Atomic counter add, retry-safe: each call mints one op-id
        token reused across its bounded retries, so a lost response
        retried never double-counts.  ``amount=0`` (a pure read) skips
        the token — naturally idempotent, no ledger churn."""
        with self._tracer.span("store.add", key=key,
                               root_eligible=False):
            if amount == 0:
                def attempt_read():
                    v = self._lib.tcpstore_add(self._fd, key.encode(), 0)
                    if v == -(2 ** 63):
                        raise RuntimeError("TCPStore.add failed")
                    return int(v)
                return self._retry_op("add", attempt_read)
            global _ADD_SEQ
            with _ADD_SEQ_LOCK:
                _ADD_SEQ += 1
                seq = _ADD_SEQ
            token = f"{_ADD_NONCE}-{os.getpid()}-{seq}"
            return self._retry_op(
                "add", lambda: self._add_once(key, amount, token))

    def check(self, key: str) -> bool:
        def attempt():
            rc = self._lib.tcpstore_check(self._fd, key.encode())
            if rc < 0:
                raise RuntimeError("TCPStore.check failed")
            return bool(rc)
        with self._tracer.span("store.check", key=key,
                               root_eligible=False):
            return self._retry_op("check", attempt)

    def wait(self, keys, timeout: Optional[float] = None):
        if isinstance(keys, str):
            keys = [keys]
        deadline = time.monotonic() + (timeout or self.timeout)
        for k in keys:
            while not self.check(k):
                if time.monotonic() > deadline:
                    raise TimeoutError(f"TCPStore.wait({k}) timed out")
                time.sleep(0.01)

    def barrier(self, name: str = "barrier"):
        """All world_size processes rendezvous (reference barrier via
        counting key)."""
        with self._tracer.span("store.barrier", barrier=name,
                               root_eligible=False):
            n = self.add(f"__{name}_count", 1)
            target = self.world_size
            deadline = time.monotonic() + self.timeout
            while n < target:
                cur = self.add(f"__{name}_count", 0)
                if cur >= target:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError("barrier timed out")
                time.sleep(0.01)

    def close(self):
        with self._bulk_lock:
            for fd in self._bulk_fds:
                self._lib.tcpstore_close(fd)
            self._bulk_fds.clear()
        if self._fd is not None and self._fd >= 0:
            self._lib.tcpstore_close(self._fd)
            self._fd = -1
        if self._server:
            # drain peers first: a client whose final barrier poll is in
            # flight must get its response, not a reset connection.  Short
            # grace only — shutdown must not hang for the full rendezvous
            # timeout when workers are still alive (elastic error paths)
            grace_ms = int(min(self.timeout, 5.0) * 1000)
            self._lib.tcpstore_server_wait_clients(self._server, 0, grace_ms)
            self._lib.tcpstore_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
