"""TCPStore — native rendezvous KV store (ctypes over csrc/store).

Reference parity: ``paddle.distributed`` TCPStore
(paddle/phi/core/distributed/store/tcp_store.h:120 — the bootstrap that
``init_parallel_env`` uses to exchange NCCL ids).  On TPU the heavy comm
setup is ``jax.distributed``; this store covers (a) pre-jax rendezvous —
electing/advertising the coordinator address — and (b) user control-plane
sync (barriers, small blobs) the reference exposes on its store.
"""

from __future__ import annotations

import ctypes
import time
from typing import Optional

__all__ = ["TCPStore"]


def _lib():
    from paddle_tpu.utils.cpp_extension import load_native
    lib = load_native("store",
                      required_symbol="tcpstore_server_wait_clients")
    lib.tcpstore_server_start.restype = ctypes.c_void_p
    lib.tcpstore_server_start.argtypes = [ctypes.c_int]
    lib.tcpstore_server_stop.argtypes = [ctypes.c_void_p]
    lib.tcpstore_connect.restype = ctypes.c_int
    lib.tcpstore_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_int]
    lib.tcpstore_set.restype = ctypes.c_int
    lib.tcpstore_set.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_get.restype = ctypes.c_int
    lib.tcpstore_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_add.restype = ctypes.c_int64
    lib.tcpstore_add.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_int64]
    lib.tcpstore_check.restype = ctypes.c_int
    lib.tcpstore_check.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.tcpstore_server_wait_clients.restype = ctypes.c_int
    lib.tcpstore_server_wait_clients.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_int, ctypes.c_int]
    lib.tcpstore_close.argtypes = [ctypes.c_int]
    return lib


class TCPStore:
    """API parity with the reference TCPStore: set/get/add/wait + barrier.

    is_master=True starts the native server in-process (host 0); every
    process (master included) connects a client."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0):
        self._lib = _lib()
        self._server = None
        self.world_size = world_size
        self.timeout = timeout
        if is_master:
            self._server = self._lib.tcpstore_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
        self._fd = self._lib.tcpstore_connect(
            host.encode(), port, int(timeout * 1000))
        if self._fd < 0:
            raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")

    def set(self, key: str, value):
        data = value if isinstance(value, bytes) else str(value).encode()
        rc = self._lib.tcpstore_set(self._fd, key.encode(), data, len(data))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str, wait: bool = True) -> bytes:
        """Blocking get (reference semantics: waits for the key)."""
        buf = ctypes.create_string_buffer(1 << 20)
        deadline = time.monotonic() + self.timeout
        while True:
            n = self._lib.tcpstore_get(self._fd, key.encode(), buf,
                                       len(buf))
            if n >= 0:
                return buf.raw[:n]
            if n == -1:
                raise RuntimeError("TCPStore.get failed")
            if not wait:
                raise KeyError(key)
            if time.monotonic() > deadline:
                raise TimeoutError(f"TCPStore.get({key}) timed out")
            time.sleep(0.01)

    def add(self, key: str, amount: int = 1) -> int:
        v = self._lib.tcpstore_add(self._fd, key.encode(), amount)
        if v == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return int(v)

    def check(self, key: str) -> bool:
        rc = self._lib.tcpstore_check(self._fd, key.encode())
        if rc < 0:
            raise RuntimeError("TCPStore.check failed")
        return bool(rc)

    def wait(self, keys, timeout: Optional[float] = None):
        if isinstance(keys, str):
            keys = [keys]
        deadline = time.monotonic() + (timeout or self.timeout)
        for k in keys:
            while not self.check(k):
                if time.monotonic() > deadline:
                    raise TimeoutError(f"TCPStore.wait({k}) timed out")
                time.sleep(0.01)

    def barrier(self, name: str = "barrier"):
        """All world_size processes rendezvous (reference barrier via
        counting key)."""
        n = self.add(f"__{name}_count", 1)
        target = self.world_size
        deadline = time.monotonic() + self.timeout
        while n < target:
            cur = self.add(f"__{name}_count", 0)
            if cur >= target:
                break
            if time.monotonic() > deadline:
                raise TimeoutError("barrier timed out")
            time.sleep(0.01)

    def close(self):
        if self._fd is not None and self._fd >= 0:
            self._lib.tcpstore_close(self._fd)
            self._fd = -1
        if self._server:
            # drain peers first: a client whose final barrier poll is in
            # flight must get its response, not a reset connection.  Short
            # grace only — shutdown must not hang for the full rendezvous
            # timeout when workers are still alive (elastic error paths)
            grace_ms = int(min(self.timeout, 5.0) * 1000)
            self._lib.tcpstore_server_wait_clients(self._server, 0, grace_ms)
            self._lib.tcpstore_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
