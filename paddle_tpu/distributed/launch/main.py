"""Launcher entry: ``python -m paddle_tpu.distributed.launch [opts] script.py``.

Reference parity: launch/main.py → ``Context`` + ``CollectiveController``
(launch/controllers/collective.py) spawning a local Pod of per-device worker
processes with PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT env and an
HTTP/etcd master for rendezvous (controllers/master.py:65,175).

TPU-native: ``--nnodes`` hosts each run ONE process driving all local chips;
rendezvous is ``jax.distributed.initialize`` against ``--master``.  For
single-machine testing, ``--nproc_per_node N`` spawns N processes with a
shared local coordinator (the reference's N-procs-on-one-host test pattern,
SURVEY.md §4).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys

__all__ = ["main"]


def _parse():
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="multi-host/process launcher")
    p.add_argument("--master", default=None,
                   help="coordinator host:port (rendezvous)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of hosts in the job")
    p.add_argument("--rank", type=int, default=None,
                   help="this host's index (default: from env or 0)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="local processes to spawn (testing/emulation)")
    p.add_argument("--log_dir", default=None,
                   help="write per-rank logs to <log_dir>/workerlog.N")
    p.add_argument("--devices", default=None,
                   help="ignored on TPU (chips are slice-assigned); parity")
    p.add_argument("--elastic", action="store_true",
                   help="watch heartbeats and relaunch on worker failure "
                        "(reference fleet/elastic/manager.py role)")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="elastic: generations to retry before giving up")
    p.add_argument("--elastic_timeout", type=float, default=30.0,
                   help="elastic: heartbeat staleness limit in seconds")
    p.add_argument("--elastic_store", default=None,
                   help="multi-node elastic: shared TCPStore host:port "
                        "(the etcd analog; one agent passes --host_store)")
    p.add_argument("--host_store", action="store_true",
                   help="this agent hosts the shared elastic store")
    p.add_argument("--elastic_nnodes", default=None,
                   help="multi-node elastic node count: N or MIN:MAX "
                        "(e.g. '2' or '1:4')")
    p.add_argument("--node_host", default="127.0.0.1",
                   help="address peers can reach this node at")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def main():
    args = _parse()
    nproc = args.nproc_per_node

    if args.elastic:
        if args.elastic_store or args.elastic_nnodes or args.host_store:
            # multi-node: one agent per host against the shared store
            if not args.elastic_store:
                sys.exit("--elastic_nnodes needs --elastic_store host:port")
            spec = args.elastic_nnodes or "1"
            lo, _, hi = spec.partition(":")
            min_nodes = int(lo)
            max_nodes = int(hi) if hi else min_nodes
            from paddle_tpu.distributed.elastic import MultiNodeElasticAgent
            agent = MultiNodeElasticAgent(
                [sys.executable, args.script, *args.script_args],
                store_addr=args.elastic_store, host_store=args.host_store,
                nproc=max(1, nproc), min_nodes=min_nodes,
                max_nodes=max_nodes, max_restarts=args.max_restarts,
                heartbeat_timeout=args.elastic_timeout,
                node_host=args.node_host, log_dir=args.log_dir)
            try:
                sys.exit(agent.run())
            finally:
                agent.close()
        if args.nnodes > 1 or args.master:
            sys.exit("single-node --elastic cannot rendezvous via "
                     "--master; for multi-host elasticity pass "
                     "--elastic_store/--elastic_nnodes (shared-store "
                     "agents), or run one launcher per host")
        from paddle_tpu.distributed.elastic import ElasticManager
        mgr = ElasticManager(
            [sys.executable, args.script, *args.script_args],
            nproc=max(1, nproc), max_restarts=args.max_restarts,
            heartbeat_timeout=args.elastic_timeout, log_dir=args.log_dir)
        try:
            sys.exit(mgr.run())
        finally:
            mgr.close()

    if nproc <= 1 and args.nnodes <= 1:
        # degenerate: exec in place
        os.execv(sys.executable, [sys.executable, args.script,
                                  *args.script_args])

    master = args.master or "127.0.0.1:12355"
    total = args.nnodes * nproc
    node_rank = args.rank
    if node_rank is None:
        node_rank = int(os.environ.get("PADDLE_NODE_RANK", "0"))

    procs = []
    log_files = []
    for local_rank in range(nproc):
        rank = node_rank * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_MASTER": master,
            "COORDINATOR_ADDRESS": master,
            "PADDLE_TRAINERS_NUM": str(total),
            "NUM_PROCESSES": str(total),
            "PADDLE_TRAINER_ID": str(rank),
            "PROCESS_ID": str(rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
        })
        stdout = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            stdout = open(os.path.join(args.log_dir,
                                       f"workerlog.{rank}"), "w")
            log_files.append(stdout)
        procs.append(subprocess.Popen(
            [sys.executable, args.script, *args.script_args],
            env=env, stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None))

    def _kill(signum, frame):
        for p in procs:
            p.terminate()
    signal.signal(signal.SIGTERM, _kill)
    signal.signal(signal.SIGINT, _kill)

    rc = 0
    try:
        for p in procs:
            p.wait()
            rc = rc or p.returncode
            if p.returncode not in (0, None):
                # fail fast like the reference watcher: kill the pod
                for q in procs:
                    if q.poll() is None:
                        q.terminate()
    finally:
        for f in log_files:
            f.close()
    sys.exit(rc)


if __name__ == "__main__":
    main()
