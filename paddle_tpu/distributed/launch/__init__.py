"""python -m paddle_tpu.distributed.launch — multi-host launcher.

Reference parity: ``python -m paddle.distributed.launch``
(launch/main.py → controllers/collective.py): builds a Pod of per-GPU worker
processes with PADDLE_TRAINER_ID / endpoints env, HTTP/etcd rendezvous.

TPU-native: one process per HOST (not per chip) — each process calls
``jax.distributed.initialize`` against the coordinator and drives all local
chips; emulation mode (``--nproc_per_node`` on one machine) spawns N
processes that each see a slice of CPU devices for testing multi-process
code paths.
"""
