from paddle_tpu.distributed.launch.main import main

main()
