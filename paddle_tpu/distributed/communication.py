"""Collective communication API.

Reference parity: ``python/paddle/distributed/communication/*.py``
(all_reduce / all_gather / all_to_all / reduce_scatter / broadcast / scatter /
reduce / send / recv / barrier) backed there by NCCL ProcessGroups
(paddle/fluid/distributed/collective/process_group.h:53).

TPU-native design: collectives are **compiler-scheduled XLA ops over ICI**,
not runtime calls on a comm stream.  Each function here therefore has two
behaviours:

* **Traced inside ``shard_map``** (an axis name is in scope): lowers to the
  matching ``jax.lax`` collective (``psum``/``all_gather``/``all_to_all``/
  ``psum_scatter``/``ppermute``).  This is the hot path — SPMD code that the
  reference writes as explicit NCCL calls is written here as shard_map'd
  functions using these same names.
* **Eager, single-controller**: operates on the global view (an all_reduce of
  a fully-replicated array is the identity; with 1 process it is a no-op),
  matching how a single-controller runtime sees already-global arrays.

``wait``/``sync_op``/``use_calc_stream`` knobs from the reference are
accepted and ignored: XLA's dataflow ordering replaces stream/event
synchronisation.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "ReduceOp", "all_reduce", "all_gather", "all_to_all", "reduce_scatter",
    "broadcast", "reduce", "scatter", "send", "recv", "barrier", "ppermute",
    "new_group", "get_group", "Group", "shift", "shard_map",
    "axis_size", "vma_of",
]

_SHARD_MAP = None


def _resolve_shard_map():
    """The jax shard_map entry point, wherever this jax version keeps
    it: ``jax.shard_map`` (0.6+) first, then the long-lived
    ``jax.experimental.shard_map.shard_map`` (0.4.x) — on 0.4.37
    ``from jax import shard_map`` raises ImportError, which used to
    take the whole sequence-parallel/MoE/pipeline family down with
    it."""
    global _SHARD_MAP
    if _SHARD_MAP is None:
        try:
            from jax import shard_map as sm          # jax >= 0.6
        except ImportError:
            from jax.experimental.shard_map import shard_map as sm
        _SHARD_MAP = sm
    return _SHARD_MAP


def shard_map(fn, mesh=None, in_specs=None, out_specs=None, **kwargs):
    """Version-compat ``shard_map``: identical signature to jax's, so
    every SPMD call site in this package (and user code) routes through
    one resolver instead of guessing the import path per jax release.

    ``legacy_check_rep=False`` relaxes the 0.4.x replication checker
    ONLY (newer jax tracks varying-manual-axes precisely via pvary, so
    its check stays on): the old static inference cannot see through a
    pipelined-backward psum, and rejects out_specs whose values are
    replicated by construction."""
    impl = _resolve_shard_map()
    legacy = kwargs.pop("legacy_check_rep", None)
    if legacy is not None and "experimental" in getattr(impl,
                                                        "__module__", ""):
        kwargs.setdefault("check_rep", legacy)
    return impl(fn, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, **kwargs)


def vma_of(x):
    """The varying-manual-axes set of ``x`` under shard_map tracing, or
    None on jax builds without ``jax.typeof`` (0.4.x has no vma
    tracking; on newer jax the bare attribute access RAISES through the
    deprecation machinery, so every caller must come through here)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    return getattr(typeof(x), "vma", None)


def pvary(x, axis_name: str):
    """Mark `x` as device-varying over `axis_name` — needed for scan carries
    inside shard_map whose value becomes varying (e.g. after a ppermute)."""
    vma = vma_of(x)
    if vma is not None and axis_name in vma:
        return x  # already varying over this axis
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, (axis_name,))
    return x


def pvary_like(x, ref, fallback_axes=()):
    """Vary `x` over every manual axis `ref` is varying over — the right
    seed for a scan accumulator that will be combined with `ref` inside a
    shard_map spanning MULTIPLE mesh axes (e.g. ring attention on an
    (sp, tp) mesh: the kv blocks vary over both axes, so the running
    o/m/l must too, or the scan carry types diverge).  On jax builds
    without ``jax.typeof`` the ref's axes can't be inspected —
    ``fallback_axes`` (the axes the caller KNOWS are in play) keep the
    old pvary behavior there."""
    if getattr(jax, "typeof", None) is None:
        missing = tuple(fallback_axes)
    else:
        want = vma_of(ref)
        have = vma_of(x)
        if not want:
            return x
        missing = tuple(a for a in want if have is None or a not in have)
    if not missing:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, missing, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, missing)
    return x


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _unwrap(x):
    return x._data if hasattr(x, "_data") else x


def _wrap_like(x, ref):
    if hasattr(ref, "_data"):
        from paddle_tpu.core.tensor import Tensor
        return Tensor(x)
    return x


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis.  ``lax.axis_size`` where this
    jax has it (0.6+); on 0.4.x a ``psum`` of a python scalar constant-
    folds to the axis size (and raises NameError when the axis is
    unbound — same contract)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def _in_trace(axis_name) -> bool:
    """True when `axis_name` is bound by an enclosing shard_map/pmap."""
    if axis_name is None:
        return False
    try:
        axis_size(axis_name)
        return True
    except (NameError, KeyError, ValueError):
        return False


# -- collectives -------------------------------------------------------------

def all_reduce(tensor, op: str = ReduceOp.SUM, group=None,
               sync_op: bool = True, axis_name: Optional[str] = None):
    """SUM/MAX/MIN/PROD across an axis.  Inside shard_map → lax.psum/pmax/…;
    eager single-process → identity (global arrays are already reduced)."""
    axis_name = axis_name or (group.axis_name if group else None)
    x = _unwrap(tensor)
    if _in_trace(axis_name):
        fn = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
              ReduceOp.MIN: lax.pmin,
              ReduceOp.AVG: lambda v, a: lax.pmean(v, a)}.get(op)
        if fn is None and op == ReduceOp.PROD:
            fn = lambda v, a: jnp.exp(lax.psum(jnp.log(v), a))
        out = fn(x, axis_name)
        return _wrap_like(out, tensor)
    return tensor


def all_gather(tensor_or_list, tensor=None, group=None, sync_op=True,
               axis_name: Optional[str] = None, axis: int = 0,
               tiled: bool = True):
    """Gather shards along `axis`.  Paddle's list-out signature
    (``all_gather(out_list, tensor)``) and the functional form
    (``y = all_gather(x)``) are both supported."""
    out_list = None
    if tensor is None:
        tensor = tensor_or_list
    else:
        out_list = tensor_or_list
    axis_name = axis_name or (group.axis_name if group else None)
    x = _unwrap(tensor)
    if _in_trace(axis_name):
        out = lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    else:
        out = x
    if out_list is not None:
        n = axis_size(axis_name) if _in_trace(axis_name) else 1
        for piece in jnp.split(out, n, axis=axis):
            out_list.append(_wrap_like(piece, tensor))
        return None
    return _wrap_like(out, tensor)


def all_to_all(out_or_in, tensor=None, group=None, sync_op=True,
               axis_name: Optional[str] = None,
               split_axis: int = 0, concat_axis: int = 0):
    """MoE-style all-to-all (reference: global_scatter/global_gather ops,
    paddle/fluid/operators/collective/global_scatter_op.cu.cc).  Inside
    shard_map → lax.all_to_all on the expert axis."""
    if tensor is not None:
        out_or_in = tensor  # ignore the out-list form's first arg
    axis_name = axis_name or (group.axis_name if group else None)
    x = _unwrap(out_or_in)
    if _in_trace(axis_name):
        out = lax.all_to_all(x, axis_name, split_axis=split_axis,
                             concat_axis=concat_axis, tiled=True)
        return _wrap_like(out, out_or_in)
    return out_or_in


def reduce_scatter(tensor, op: str = ReduceOp.SUM, group=None, sync_op=True,
                   axis_name: Optional[str] = None, scatter_dimension=0):
    """ZeRO-2 grad primitive (reference: GroupShardedStage2's on-the-fly
    reduce-scatter, fleet/meta_parallel/sharding/group_sharded_stage2.py:46).
    Inside shard_map → lax.psum_scatter."""
    axis_name = axis_name or (group.axis_name if group else None)
    x = _unwrap(tensor)
    if _in_trace(axis_name):
        out = lax.psum_scatter(x, axis_name,
                               scatter_dimension=scatter_dimension,
                               tiled=True)
        return _wrap_like(out, tensor)
    return tensor


def broadcast(tensor, src: int = 0, group=None, sync_op=True,
              axis_name: Optional[str] = None):
    """Select rank `src`'s value on every rank of the axis."""
    axis_name = axis_name or (group.axis_name if group else None)
    x = _unwrap(tensor)
    if _in_trace(axis_name):
        # mask-to-src then psum: the SPMD spelling of a one-to-all
        # (ppermute needs unique sources, so it can't express broadcast)
        idx = lax.axis_index(axis_name)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        out = lax.psum(masked, axis_name)
        return _wrap_like(out, tensor)
    return tensor


def reduce(tensor, dst: int = 0, op: str = ReduceOp.SUM, group=None,
           sync_op=True, axis_name: Optional[str] = None):
    """psum then mask: only `dst` keeps the reduced value (others keep
    their input, matching NCCL reduce semantics loosely)."""
    axis_name = axis_name or (group.axis_name if group else None)
    x = _unwrap(tensor)
    if _in_trace(axis_name):
        summed = _unwrap(all_reduce(x, op=op, axis_name=axis_name))
        idx = lax.axis_index(axis_name)
        out = jnp.where(idx == dst, summed, x)
        return _wrap_like(out, tensor)
    return tensor


def scatter(tensor, tensor_list=None, src: int = 0, group=None, sync_op=True,
            axis_name: Optional[str] = None, axis: int = 0):
    """Each rank takes its slice of src's concatenated input."""
    axis_name = axis_name or (group.axis_name if group else None)
    x = _unwrap(tensor)
    if _in_trace(axis_name):
        n = axis_size(axis_name)
        idx = lax.axis_index(axis_name)
        full = lax.psum(jnp.where(idx == src, x, jnp.zeros_like(x)),
                        axis_name)
        piece = full.shape[axis] // n
        out = lax.dynamic_slice_in_dim(full, idx * piece, piece, axis=axis)
        return _wrap_like(out, tensor)
    return tensor


def ppermute(tensor, perm: Sequence, axis_name: Optional[str] = None,
             group=None):
    """Raw collective-permute — the ICI point-to-point primitive that
    replaces the reference's p2p send/recv
    (fleet/meta_parallel/pp_utils/p2p_communication.py)."""
    axis_name = axis_name or (group.axis_name if group else None)
    x = _unwrap(tensor)
    out = lax.ppermute(x, axis_name, list(perm))
    return _wrap_like(out, tensor)


def shift(tensor, offset: int = 1, axis_name: Optional[str] = None,
          group=None):
    """Rotate values around the axis ring by `offset` (ring-attention /
    pipeline microbatch rotation primitive)."""
    axis_name = axis_name or (group.axis_name if group else None)
    n = axis_size(axis_name)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return ppermute(tensor, perm, axis_name=axis_name)


def send(tensor, dst: int, group=None, sync_op=True,
         axis_name: Optional[str] = None):
    """Point-to-point send: under SPMD this is half of a ppermute; the
    matching recv must use the same (src,dst) pair.  Provided for API parity
    — prefer `ppermute`/`shift` which express both halves at once."""
    raise NotImplementedError(
        "SPMD send/recv must be expressed as a paired ppermute: use "
        "paddle_tpu.distributed.ppermute(x, [(src, dst)], axis_name=...) "
        "which is the XLA collective-permute both ends compile into.")


def recv(tensor, src: int, group=None, sync_op=True,
         axis_name: Optional[str] = None):
    raise NotImplementedError(
        "see paddle_tpu.distributed.send — use ppermute([(src, dst)]).")


def barrier(group=None):
    """Block the host until all queued device work is complete.  XLA's gang
    schedule makes a device-side barrier implicit; the host-side analog is
    draining the dispatch queue."""
    (jnp.zeros(()) + 0).block_until_ready()


# -- groups ------------------------------------------------------------------

class Group:
    """Named communication group = a mesh axis (reference: runtime NCCL
    group, python/paddle/distributed/communication/group.py)."""

    def __init__(self, ranks: List[int], gid: int,
                 axis_name: Optional[str] = None):
        self.ranks = list(ranks)
        self.id = gid
        self.axis_name = axis_name or f"group{gid}"

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis_name}, " \
               f"ranks={self.ranks})"


_GROUPS: dict = {}
_NEXT_GID = [1]


def new_group(ranks: Optional[List[int]] = None, backend: str = "xla",
              axis_name: Optional[str] = None) -> Group:
    """Create a group handle.  Reference parity:
    ``paddle.distributed.new_group`` (distributed/collective.py:175).  On TPU
    a 'group' is a name used in shard_map collectives, not a runtime object;
    creating one is free and requires no rendezvous."""
    import jax
    if ranks is None:
        ranks = list(range(jax.device_count()))
    gid = _NEXT_GID[0]
    _NEXT_GID[0] += 1
    g = Group(ranks, gid, axis_name)
    _GROUPS[gid] = g
    return g


def get_group(gid: int) -> Optional[Group]:
    return _GROUPS.get(gid)
