"""Distributed checkpoint: per-shard sharded save/load + cross-mesh re-slicing.

Reference parity (SURVEY.md §5.4): per-rank shard saves
(``PipelineLayer.save_state_dict`` pp_layers.py:794), auto-parallel
``DistributedSaver`` (static/dist_saver.py) and the ``Converter``
(static/converter.py) that re-slices checkpoints when mesh/sharding change;
auto-checkpoint epoch-resume (fluid/incubate/checkpoint/auto_checkpoint.py).

TPU-native design — NEVER-GLOBAL:
  * save: each process writes ONLY its addressable shards (replica 0 of
    each global piece), one ``.npy`` file per shard, plus a per-process
    JSON index recording each shard's global offsets.  No global array is
    ever materialized — a 70B optimizer state streams out shard-by-shard.
  * load: ``jax.make_array_from_callback`` against the NEW mesh/specs; the
    callback assembles exactly the requested region from the (mmapped)
    shard files that overlap it.  Re-sharding across mesh/layout changes —
    the reference Converter's job — is therefore free at load time and
    still never builds the global tensor on any single host.
  * async save (the orbax pattern): snapshot addressable shards to host
    synchronously (cheap D2H), write files on a background thread so the
    train loop never blocks on disk.

Format 2 layout (format 1 = one global .npy per tensor remains loadable):

    path/
      index.0.json            # per-process shard index
      index.1.json
      <name>.shard.<o0a-o0b>_<o1a-o1b>.npy   # one file per unique shard
      checkpoint_meta.json    # sentinel, written last by process 0
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict",
           "validate_checkpoint", "Converter", "AutoCheckpoint"]

_SENTINEL = "checkpoint_meta.json"

_log = logging.getLogger("paddle_tpu.robustness.checkpoint")

_DURATION_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1, 5, 15, 60, 300, 900)


def _ckpt_metrics():
    """Save/restore telemetry (observability tentpole).  Durations are
    per-process wall time of the local shard I/O — the number an
    operator watches drift as checkpoints grow."""
    from paddle_tpu.observability import default_registry
    reg = default_registry()
    return {
        "saves": reg.counter("paddle_tpu_checkpoint_saves_total",
                             "checkpoint save operations (this process's "
                             "shard write, sync or async)"),
        "restores": reg.counter("paddle_tpu_checkpoint_restores_total",
                                "checkpoint load operations"),
        "save_s": reg.histogram("paddle_tpu_checkpoint_save_seconds",
                                "wall time writing this process's shards",
                                buckets=_DURATION_BUCKETS),
        "restore_s": reg.histogram(
            "paddle_tpu_checkpoint_restore_seconds",
            "wall time assembling this process's regions",
            buckets=_DURATION_BUCKETS),
    }


def _unwrap(arr):
    return arr._data if hasattr(arr, "_data") else arr


def _norm_offsets(index: Tuple, shape) -> List[List[int]]:
    """Tuple-of-slices → [[start, stop], ...] with Nones resolved."""
    out = []
    for dim, sl in zip(shape, index):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _shard_fname(name: str, offsets: List[List[int]]) -> str:
    safe = name.replace("/", "__")
    if not offsets:
        return f"{safe}.shard.npy"
    tag = "_".join(f"{a}-{b}" for a, b in offsets)
    return f"{safe}.shard.{tag}.npy"


def _file_digest(path: str) -> Dict[str, Any]:
    """Integrity metadata for one written shard file: byte length +
    crc32 always (cheap — zlib streams at GB/s), sha256 additionally when
    ``PADDLE_TPU_CKPT_DIGEST=sha256`` (collision-resistant, for storage
    you genuinely distrust).  Computed over the final FILE bytes, so the
    validator re-reads exactly what a load would."""
    crc = 0
    sha = hashlib.sha256() if \
        os.environ.get("PADDLE_TPU_CKPT_DIGEST") == "sha256" else None
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            n += len(chunk)
            crc = zlib.crc32(chunk, crc)
            if sha is not None:
                sha.update(chunk)
    out: Dict[str, Any] = {"bytes": n, "crc32": crc & 0xFFFFFFFF}
    if sha is not None:
        out["sha256"] = sha.hexdigest()
    return out


def _verify_shard_file(path: str, entry: dict) -> Optional[str]:
    """None when the on-disk file matches the index entry's digests;
    otherwise a human-readable reason.  Entries from pre-digest
    checkpoints (no ``bytes``/``crc32`` keys) verify trivially."""
    if "bytes" in entry:
        actual = os.path.getsize(path)
        if actual != entry["bytes"]:
            return (f"{os.path.basename(path)}: size {actual} != recorded "
                    f"{entry['bytes']} (truncated/torn write)")
    if "crc32" in entry or "sha256" in entry:
        crc = 0
        sha = hashlib.sha256() if "sha256" in entry else None
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
                if sha is not None:
                    sha.update(chunk)
        if "crc32" in entry and (crc & 0xFFFFFFFF) != entry["crc32"]:
            return (f"{os.path.basename(path)}: crc32 mismatch "
                    f"(bit rot / partial overwrite)")
        if sha is not None and sha.hexdigest() != entry["sha256"]:
            return f"{os.path.basename(path)}: sha256 mismatch"
    return None


def _snapshot_shards(state_dict: Dict[str, Any],
                     coordinator_rank: int = 0) -> Dict[str, dict]:
    """Device → host, addressable shards only (replica 0 of each piece).

    Returns {name: {global_shape, dtype, shards: [(offsets, np_data)]}}.
    Host memory touched = this process's shards, never the global array.
    Host-only (non-jax.Array) values are written by `coordinator_rank`.
    """
    import jax
    plan: Dict[str, dict] = {}
    proc = jax.process_index()
    for name, arr in state_dict.items():
        a = _unwrap(arr)
        if isinstance(a, jax.Array):
            shards = []
            for sh in a.addressable_shards:
                if sh.replica_id != 0:
                    continue  # replicated piece: exactly one writer globally
                offsets = _norm_offsets(sh.index, a.shape)
                shards.append((offsets, np.asarray(sh.data)))
            plan[name] = {"global_shape": list(a.shape),
                          "dtype": str(a.dtype), "shards": shards}
        else:
            np_arr = np.asarray(a)
            shards = []
            if proc == coordinator_rank:  # host-only values: written once
                offsets = [[0, d] for d in np_arr.shape]
                shards = [(offsets, np_arr)]
            plan[name] = {"global_shape": list(np_arr.shape),
                          "dtype": str(np_arr.dtype), "shards": shards}
    return plan


def _purge_stale(path: str):
    """Remove any previous checkpoint artifacts so a re-save under a
    different sharding cannot leave stale offset-tagged shard files that
    a later load would merge with the new ones — including orphaned
    ``*.tmp.*`` files from saves interrupted between write and rename."""
    for pattern in ("index.*.json", "*.shard.npy", "*.shard.*.npy",
                    "*.tmp.*"):
        for f in glob.glob(os.path.join(glob.escape(path), pattern)):
            os.remove(f)
    sentinel = os.path.join(path, _SENTINEL)
    if os.path.exists(sentinel):
        os.remove(sentinel)


def _write_shard(path: str, fname: str, data: np.ndarray) -> dict:
    """Atomic shard publish: write to a pid-tagged tmp file, digest it,
    rename into place.  A crash at ANY point leaves either no file or a
    ``.tmp.*`` orphan (purged by the next save / validator-invisible) —
    never a half-written file under the final name.  Returns the digest
    entry fields for the index."""
    from paddle_tpu.robustness import fault_fires, fault_point
    final = os.path.join(path, fname)
    tmp = final + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:  # handle, not path: np.save must not
        np.save(f, data)        # append ".npy" to the tmp name
    digest = _file_digest(tmp)
    # chaos: crash-before-publish — the tmp orphan must be invisible to
    # loads and cleaned by the next save's purge
    fault_point("checkpoint.shard_write", file=fname)
    if fault_fires("checkpoint.torn_shard", file=fname):
        # chaos: torn write / silent corruption — the recorded digest is
        # of the INTENDED bytes, so validation must catch the mismatch
        with open(tmp, "r+b") as f:
            f.truncate(max(1, digest["bytes"] // 2))
    os.replace(tmp, final)
    return digest


def _write_plan(plan: Dict[str, dict], path: str, barrier: bool = True):
    """Write this process's shards + index; process 0 purges stale
    artifacts first and writes the sentinel last (with cross-process
    barriers when running multi-controller)."""
    from paddle_tpu.observability import flight_recorder
    from paddle_tpu.observability.tracing import tracer
    t0 = time.perf_counter()
    recorder = flight_recorder()
    recorder.record("checkpoint.save_begin", path=path,
                    tensors=len(plan), barrier=barrier)
    try:
        with tracer().span("checkpoint.save", path=path,
                           tensors=len(plan), root_eligible=False):
            _write_plan_inner(plan, path, barrier)
    except BaseException as e:
        recorder.record("checkpoint.save_failed", path=path,
                        error=type(e).__name__)
        raise
    m = _ckpt_metrics()
    m["saves"].inc()
    m["save_s"].observe(time.perf_counter() - t0)
    recorder.record("checkpoint.save_end", path=path,
                    seconds=time.perf_counter() - t0)


def _write_plan_inner(plan: Dict[str, dict], path: str,
                      barrier: bool = True):
    import jax
    proc, nprocs = jax.process_index(), jax.process_count()
    os.makedirs(path, exist_ok=True)
    # Purge previous artifacts so a re-save under a different sharding
    # can't leave stale shard files.  Multi-controller async saves skip
    # the purge entirely (no barrier is possible off the main thread, so
    # purging could race peers' writes) — async callers must use fresh
    # step dirs, which AutoCheckpoint always does.
    if proc == 0 and (nprocs == 1 or barrier):
        _purge_stale(path)
    if nprocs > 1 and barrier:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt_purge:{path}")
    from paddle_tpu.observability.tracing import tracer
    tr = tracer()
    index = {}
    for name, tmeta in plan.items():
        entries = []
        for offsets, data in tmeta["shards"]:
            fname = _shard_fname(name, offsets)
            # per-shard span: which tensor's write is the slow one (or
            # the one a fault fired in) reads straight off the trace
            with tr.span("checkpoint.shard", file=fname,
                         bytes=int(data.nbytes), root_eligible=False):
                digest = _write_shard(path, fname, data)
            entries.append({"file": fname, "offsets": offsets, **digest})
        index[name] = {"global_shape": tmeta["global_shape"],
                       "dtype": tmeta["dtype"], "shards": entries}
    _atomic_json(os.path.join(path, f"index.{proc}.json"),
                 {"tensors": index, "process": proc})
    if nprocs > 1 and barrier:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt_save:{path}")
    if proc == 0:
        _atomic_json(os.path.join(path, _SENTINEL),
                     {"format": 2, "nprocs": nprocs})


def _atomic_json(path: str, obj):
    """tmp+rename JSON write: a crash mid-dump must not leave a
    truncated (unparseable) index/sentinel under the final name."""
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0):
    """Write {name: array} to `path/`, one file per addressable shard.
    Every process participates and writes only what it owns;
    `coordinator_rank` selects the writer for host-only (non-jax.Array)
    values.  `process_group` is accepted for reference-API compatibility
    (sharding already determines ownership under SPMD)."""
    _write_plan(_snapshot_shards(state_dict, coordinator_rank), path)


def _merge_indexes(path: str, expected_nprocs: Optional[int] = None
                   ) -> Dict[str, dict]:
    idx_files = sorted(glob.glob(os.path.join(glob.escape(path),
                                              "index.*.json")))
    if expected_nprocs is not None and len(idx_files) != expected_nprocs:
        raise ValueError(
            f"checkpoint has {len(idx_files)} index files but was written "
            f"by {expected_nprocs} processes — a writer crashed mid-save; "
            "tensors it owned would silently vanish, refusing to load")
    merged: Dict[str, dict] = {}
    for idx_file in idx_files:
        with open(idx_file) as f:
            tensors = json.load(f)["tensors"]
        for name, tmeta in tensors.items():
            if name not in merged:
                merged[name] = {"global_shape": tmeta["global_shape"],
                                "dtype": tmeta["dtype"], "shards": []}
            merged[name]["shards"].extend(tmeta["shards"])
    return merged


def _tile_region(shards: List[dict], want: List[List[int]]):
    """For the shard entries overlapping region `want`, return
    [(shard, src_slices, dst_slices)] after verifying they tile the region
    EXACTLY — disjoint (duplicates/stale files must not mask a hole) and
    fully covering.  Raises ValueError otherwise.  Shared by the real read
    path (_read_region) and the metadata-only validator so the two can
    never disagree on what a complete checkpoint is."""
    covered, placed, out = 0, [], []
    for sh in shards:
        src_sl, dst_sl, empty = [], [], False
        for (wa, wb), (sa, sb) in zip(want, sh["offsets"]):
            lo, hi = max(wa, sa), min(wb, sb)
            if lo >= hi:
                empty = True
                break
            src_sl.append(slice(lo - sa, hi - sa))
            dst_sl.append(slice(lo - wa, hi - wa))
        if empty:
            continue
        dst_rng = [(s.start, s.stop) for s in dst_sl]
        for prev in placed:
            if all(a < pb and pa < b
                   for (a, b), (pa, pb) in zip(dst_rng, prev)):
                raise ValueError(
                    f"checkpoint shards overlap within region {want} — "
                    "duplicate or stale shard files from a previous save")
        placed.append(dst_rng)
        out.append((sh, tuple(src_sl), tuple(dst_sl)))
        covered += int(np.prod([b - a for a, b in dst_rng]))
    size = int(np.prod([b - a for a, b in want]))
    if covered != size:
        raise ValueError(
            f"checkpoint region {want} is under-covered by shard files "
            f"({covered}/{size} elements) — missing/partial shards "
            "(peer crashed mid-write?)")
    return out


def _check_0d(shards: List[dict]):
    if not shards:
        raise ValueError("checkpoint 0-d tensor is under-covered: its "
                         "single shard file is missing (owner process "
                         "crashed mid-write?)")
    if len(shards) > 1:
        raise ValueError("checkpoint 0-d tensor has duplicate shard "
                         "files — stale artifacts from a previous save")


def _read_region(path: str, tmeta: dict, index: Tuple,
                 cache: Optional[dict] = None) -> np.ndarray:
    """Assemble exactly the requested global region from the shard files
    that overlap it.  Files are mmapped so only the overlapping bytes are
    read — loading a [vocab,d] slice never touches the rest of the file.
    `cache` (per-tensor) keeps memmaps open across the one-callback-per-
    device-region calls make_array_from_callback issues."""
    gshape = tmeta["global_shape"]
    dtype = np.dtype(tmeta["dtype"])
    if not gshape:  # 0-d
        _check_0d(tmeta["shards"])
        return np.load(os.path.join(path, tmeta["shards"][0]["file"]))
    want = _norm_offsets(index, gshape) if index else [[0, d] for d in gshape]
    out = np.empty([b - a for a, b in want], dtype)
    if cache is None:
        cache = {}
    for sh, src_sl, dst_sl in _tile_region(tmeta["shards"], want):
        data = cache.get(sh["file"])
        if data is None:
            data = np.load(os.path.join(path, sh["file"]), mmap_mode="r")
            cache[sh["file"]] = data
        out[dst_sl] = data[src_sl]
    return out


def load_state_dict(path: str, mesh=None,
                    specs: Optional[Dict[str, Any]] = None,
                    dtype=None) -> Dict[str, Any]:
    """Load a checkpoint; if `mesh`+`specs` are given, each array is built
    directly into its NamedSharding via make_array_from_callback — this IS
    the reference Converter: a checkpoint written under any previous
    parallel layout loads into any new one, and no host ever holds more
    than the shards its devices need."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.observability import flight_recorder
    from paddle_tpu.observability.tracing import tracer
    t0 = time.perf_counter()
    restore_span = tracer().start_span("checkpoint.restore", path=path,
                                       root_eligible=False)
    with open(os.path.join(path, _SENTINEL)) as f:
        meta = json.load(f)
    if meta.get("format", 1) < 2:  # legacy: one global .npy per tensor
        out1 = _load_format1(path, meta["tensors"], mesh, specs, dtype)
        m = _ckpt_metrics()
        m["restores"].inc()
        m["restore_s"].observe(time.perf_counter() - t0)
        restore_span.set_attribute("tensors", len(out1))
        restore_span.end()
        return out1
    tensors = _merge_indexes(path, expected_nprocs=meta.get("nprocs"))
    out = {}
    for name, tmeta in tensors.items():
        gshape = tuple(tmeta["global_shape"])
        tgt_dtype = np.dtype(tmeta["dtype"])
        if dtype is not None and np.issubdtype(tgt_dtype, np.floating):
            tgt_dtype = np.dtype(dtype)

        mmap_cache: dict = {}

        def cb(index, _tm=tmeta, _dt=tgt_dtype, _cache=mmap_cache):
            region = _read_region(path, _tm, index, cache=_cache)
            return region.astype(_dt, copy=False)

        if mesh is not None and specs is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            sharding = NamedSharding(mesh, specs.get(name, P()))
            out[name] = jax.make_array_from_callback(gshape, sharding, cb)
        else:
            out[name] = jnp.asarray(cb(()))
    m = _ckpt_metrics()
    m["restores"].inc()
    m["restore_s"].observe(time.perf_counter() - t0)
    restore_span.set_attribute("tensors", len(out))
    restore_span.end()
    flight_recorder().record("checkpoint.restore", path=path,
                             tensors=len(out),
                             seconds=time.perf_counter() - t0)
    return out


def _load_format1(path, tensors, mesh, specs, dtype):
    import jax
    import jax.numpy as jnp
    out = {}
    for name, info in tensors.items():
        arr = np.load(os.path.join(path, info["file"]))
        if dtype is not None and np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(dtype)
        if mesh is not None and specs is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            spec = specs.get(name, P())
            out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
        else:
            out[name] = jnp.asarray(arr)
    return out


def validate_checkpoint(path: str,
                        verify_digests: Optional[bool] = None) -> bool:
    """Global integrity check: sentinel + all per-process index files
    present and parseable, every referenced shard file on disk, every
    tensor's FULL global region exactly tiled by its shard entries, and
    (by default) every shard file's size + crc32/sha256 matching the
    digests recorded at save time — so a torn write or bit rot fails
    validation instead of surfacing as a crash (or silent corruption) at
    load.  ``verify_digests=False`` (or ``PADDLE_TPU_CKPT_VERIFY=meta``)
    skips the content re-read for very large checkpoints.

    Returns False with a logged reason on ANY defect — truncated or
    unparseable index/sentinel included — never raises.  Because every
    process reads the same shared-storage artifacts, every process
    reaches the SAME verdict, which is what lets multi-controller
    ``restore_latest`` agree on a resume step."""
    if verify_digests is None:
        verify_digests = os.environ.get(
            "PADDLE_TPU_CKPT_VERIFY", "digest") != "meta"

    def invalid(reason: str) -> bool:
        _log.warning("invalid checkpoint at %s: %s", path, reason)
        try:
            from paddle_tpu.observability import flight_recorder
            flight_recorder().record("checkpoint.validate_failed",
                                     path=path, reason=reason[:200])
        except Exception:
            pass
        return False

    try:
        with open(os.path.join(path, _SENTINEL)) as f:
            meta = json.load(f)
        if meta.get("format", 1) < 2:
            for i in meta["tensors"].values():
                if not os.path.exists(os.path.join(path, i["file"])):
                    return invalid(f"missing tensor file {i['file']}")
            return True
        tensors = _merge_indexes(path, expected_nprocs=meta.get("nprocs"))
        for name, tmeta in tensors.items():
            shards = tmeta["shards"]
            for sh in shards:
                fpath = os.path.join(path, sh["file"])
                if not os.path.exists(fpath):
                    return invalid(f"{name}: missing shard {sh['file']}")
                if verify_digests:
                    reason = _verify_shard_file(fpath, sh)
                    if reason is not None:
                        return invalid(f"{name}: {reason}")
            gshape = tmeta["global_shape"]
            if not gshape:
                _check_0d(shards)  # raises → caught below
            else:
                _tile_region(shards, [[0, d] for d in gshape])
        return True
    except (ValueError, OSError, KeyError, json.JSONDecodeError) as e:
        return invalid(f"{type(e).__name__}: {e}")


class _AsyncSave:
    """Handle for an in-flight background save.  The writer's exception
    (disk full, permissions) is captured and re-raised from ``wait()`` —
    a checkpoint that silently failed to write is worse than a crash."""

    def __init__(self, target, args, kwargs):
        self.error: Optional[BaseException] = None
        # explicit trace-context handoff: the writer thread's spans
        # (checkpoint.save / per-shard) parent under whatever span the
        # train loop was in when it kicked off the save
        from paddle_tpu.observability.tracing import tracer
        tr = tracer()
        ctx = tr.current_context()

        def run():
            try:
                with tr.attach(ctx):
                    target(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self.error = e

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()

    def wait(self):
        self.thread.join()
        if self.error is not None:
            raise self.error

    def done(self):
        return not self.thread.is_alive()


def async_save_state_dict(state_dict: Dict[str, Any], path: str,
                          coordinator_rank: int = 0) -> _AsyncSave:
    """Snapshot this process's shards to host synchronously (cheap D2H),
    write them on a background thread (the orbax async pattern).  Host
    memory cost = local shards only, never the global state.

    Multi-controller note: the background thread skips the cross-process
    barrier (collectives must not run off the main thread), so the
    sentinel may appear before slow peers finish.  Call ``.wait()`` on
    every process and then barrier on the main thread before treating the
    checkpoint as globally complete — ``AutoCheckpoint.maybe_save`` does
    exactly this for the previous in-flight save at the next interval."""
    plan = _snapshot_shards(state_dict, coordinator_rank)
    return _AsyncSave(_write_plan, (plan, path), {"barrier": False})


class Converter:
    """Reference static/converter.py parity: re-slice a checkpoint between
    parallel strategies.  On TPU both directions are mechanical because the
    stored artifact is an offset-indexed set of shards:

      merge:  shard files + offsets → any requested region (lazy, mmapped)
      slice:  ``make_array_from_callback`` against the new mesh on load
    """

    def __init__(self, checkpoint_path: str):
        self.path = checkpoint_path

    def convert(self, mesh, specs, dtype=None) -> Dict[str, Any]:
        return load_state_dict(self.path, mesh=mesh, specs=specs,
                               dtype=dtype)

    @staticmethod
    def merge_with_dist_attr(shards, dist_attr) -> np.ndarray:
        """Reassemble a global array from per-rank shard arrays.
        `dist_attr`: {"dims_mapping": [tensor_dim → mesh_axis or -1],
        "process_shape": [mesh dims], "process_group": [ranks]} — the
        reference's TensorDistAttr JSON shape."""
        dims_mapping = dist_attr["dims_mapping"]
        process_shape = dist_attr["process_shape"]
        ranks = dist_attr["process_group"]
        first = np.asarray(shards[0])
        # global shape: multiply sharded dims by their mesh-axis size
        gshape = list(first.shape)
        for tdim, maxis in enumerate(dims_mapping):
            if maxis >= 0:
                gshape[tdim] *= process_shape[maxis]
        out = np.zeros(gshape, first.dtype)
        for rank, shard in zip(ranks, shards):
            # coordinates of this rank in the process mesh
            coord = []
            rem = rank
            for dim in reversed(process_shape):
                coord.append(rem % dim)
                rem //= dim
            coord = coord[::-1]
            index = []
            for tdim, maxis in enumerate(dims_mapping):
                if maxis >= 0:
                    size = np.asarray(shard).shape[tdim]
                    start = coord[maxis] * size
                    index.append(slice(start, start + size))
                else:
                    index.append(slice(None))
            out[tuple(index)] = np.asarray(shard)
        return out

    @staticmethod
    def slice_with_dist_attr(global_arr: np.ndarray, dist_attr):
        """Global array → list of per-rank shards (inverse of merge)."""
        dims_mapping = dist_attr["dims_mapping"]
        process_shape = dist_attr["process_shape"]
        ranks = dist_attr["process_group"]
        shards = []
        for rank in ranks:
            coord = []
            rem = rank
            for dim in reversed(process_shape):
                coord.append(rem % dim)
                rem //= dim
            coord = coord[::-1]
            index = []
            for tdim, maxis in enumerate(dims_mapping):
                if maxis >= 0:
                    size = global_arr.shape[tdim] // process_shape[maxis]
                    start = coord[maxis] * size
                    index.append(slice(start, start + size))
                else:
                    index.append(slice(None))
            shards.append(np.asarray(global_arr[tuple(index)]))
        return shards


class AutoCheckpoint:
    """Checkpoint-restart orchestration (reference auto_checkpoint.py +
    elastic §5.3 re-thought for TPU: XLA jobs are gang-scheduled, so fault
    tolerance = frequent async snapshots + resume-from-latest, not live
    rescale)."""

    def __init__(self, directory: str, keep: int = 3,
                 save_interval_steps: int = 1000):
        self.dir = directory
        self.keep = keep
        self.interval = save_interval_steps
        self._pending: Optional[_AsyncSave] = None
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{step:012d}")

    def _complete_steps(self) -> List[int]:
        """Steps whose checkpoints pass the metadata validator, newest
        first.  The sentinel alone is not proof — an async multi-controller
        save cut down mid-write leaves a sentinel over missing shards."""
        return sorted(
            (s for s in (int(n[5:]) for n in os.listdir(self.dir)
                         if n.startswith("step_"))
             if validate_checkpoint(self._step_dir(s))), reverse=True)

    def latest_step(self) -> Optional[int]:
        """Newest step restore_latest would actually restore — callers
        pairing `latest_step()` with `restore_latest()` stay consistent."""
        steps = self._complete_steps()
        return steps[0] if steps else None

    def maybe_save(self, step: int, state_dict: Dict[str, Any]):
        if step % self.interval:
            return None
        import jax
        if self._pending is not None:
            self._pending.wait()  # backpressure: one in flight
            if jax.process_count() > 1:
                # all writer threads have finished locally; barrier on the
                # MAIN thread so the previous checkpoint is globally
                # complete before we start (and before _gc could touch it)
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("ckpt_prev_complete")
        step_dir = self._step_dir(step)
        # A crash-leftover dir at this step (possibly under a different
        # sharding) must be purged before the async writer starts.  The
        # purge+barrier sequence runs UNCONDITIONALLY: gating it on a
        # per-process os.path.exists over shared storage is racy (process 0
        # could rmtree and enter the barrier before a slower peer stats the
        # dir, which then skips the barrier and strands process 0).  rmtree
        # on a missing dir is a no-op, so the deterministic form costs one
        # barrier per save and can never deadlock.
        import shutil
        if jax.process_index() == 0:
            shutil.rmtree(step_dir, ignore_errors=True)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"ckpt_fresh:{step}")
        self._pending = async_save_state_dict(state_dict, step_dir)
        self._gc(step)
        return self._pending

    def restore_latest(self, mesh=None, specs=None):
        """Restore from the newest VALID checkpoint (digest-verified),
        falling back step by step past corrupted ones.  A checkpoint the
        validator passed can still fail to load (storage fault between
        validate and read); that too falls back to the next-older valid
        step rather than failing the whole resume.  Both the validator
        and the loader are deterministic over shared storage, so every
        process picks the SAME step.  Only when NO candidate loads does
        the last error propagate — silently restarting from step 0 would
        let subsequent saves + GC destroy the surviving good checkpoints."""
        steps = self._complete_steps()
        if not steps:
            return None, None
        last_err = None
        for step in steps:
            try:
                return step, load_state_dict(self._step_dir(step),
                                             mesh=mesh, specs=specs)
            except Exception as e:  # noqa: BLE001 — re-raised when all fail
                last_err = e
                _log.warning("checkpoint step %d validated but failed to "
                             "load (%s: %s); falling back to next-older",
                             step, type(e).__name__, e)
                from paddle_tpu.observability import flight_recorder
                flight_recorder().record("checkpoint.restore_fallback",
                                         step=step,
                                         error=type(e).__name__)
        raise last_err

    def save_now(self, step: int, state_dict: Dict[str, Any]) -> str:
        """SYNCHRONOUS save for the preemption drain path: wait out any
        in-flight async save, then write `step` to durable storage before
        returning — the caller is about to exit and must not leave the
        final checkpoint on a daemon thread."""
        import jax
        if self._pending is not None:
            self._pending.wait()
            self._pending = None
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("ckpt_prev_complete")
        step_dir = self._step_dir(step)
        import shutil
        if jax.process_index() == 0:
            shutil.rmtree(step_dir, ignore_errors=True)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"ckpt_fresh:{step}")
        save_state_dict(state_dict, step_dir)
        self._gc(step)
        return step_dir

    def _gc(self, current_step: int):
        """Keep the newest `keep-1` COMPLETE checkpoints (the in-flight
        `current_step` save will make `keep`); incomplete leftovers (a
        crashed save — validator-failing, same definition restore uses)
        are useless and always removed."""
        import shutil
        complete, partial = [], []
        for n in os.listdir(self.dir):
            if not n.startswith("step_"):
                continue
            s = int(n[5:])
            if s >= current_step:
                continue
            if validate_checkpoint(self._step_dir(s)):
                complete.append(s)
            else:
                partial.append(s)
        complete.sort()
        doomed = partial + (
            complete[:-(self.keep - 1)] if self.keep > 1 else complete)
        for s in doomed:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
