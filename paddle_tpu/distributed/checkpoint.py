"""Distributed checkpoint: sharded save/load + cross-mesh re-slicing.

Reference parity (SURVEY.md §5.4): per-rank shard saves
(``PipelineLayer.save_state_dict`` pp_layers.py:794), auto-parallel
``DistributedSaver`` (static/dist_saver.py) and the ``Converter``
(static/converter.py) that re-slices checkpoints when mesh/sharding change;
auto-checkpoint epoch-resume (fluid/incubate/checkpoint/auto_checkpoint.py).

TPU-native design: under single-controller SPMD every jax.Array is GLOBAL —
a checkpoint saves the global view (fetched shard-by-shard via
``.addressable_shards``), so "conversion" between parallel layouts happens
for free at load: ``device_put`` against the NEW mesh/specs re-slices.
Async save (the orbax pattern) snapshots device arrays to host then writes
on a background thread so the train loop never blocks on disk.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict",
           "Converter", "AutoCheckpoint"]

_SENTINEL = "checkpoint_meta.json"


def _to_host(arr) -> np.ndarray:
    """Device → host.  Multi-host jax.Arrays are not fully addressable, so
    np.asarray would raise; gather the global value across processes first
    (every process participates — the coordinator gets the full array)."""
    if hasattr(arr, "_data"):
        arr = arr._data
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0):
    """Write {name: array} to `path/` (one .npy per tensor + metadata).
    Multi-host: only process 0 writes (arrays are global; for giant arrays
    pass through async_save to overlap)."""
    import jax
    if jax.process_index() != coordinator_rank:
        return
    os.makedirs(path, exist_ok=True)
    meta = {}
    for name, arr in state_dict.items():
        np_arr = _to_host(arr)
        fname = name.replace("/", "__") + ".npy"
        np.save(os.path.join(path, fname), np_arr)
        meta[name] = {"file": fname, "shape": list(np_arr.shape),
                      "dtype": str(np_arr.dtype)}
    with open(os.path.join(path, _SENTINEL), "w") as f:
        json.dump({"tensors": meta, "format": 1}, f)


def load_state_dict(path: str, mesh=None,
                    specs: Optional[Dict[str, Any]] = None,
                    dtype=None) -> Dict[str, Any]:
    """Load a checkpoint; if `mesh`+`specs` are given, each array is placed
    with its NamedSharding — this IS the reference Converter: a checkpoint
    written under any previous parallel layout loads into any new one."""
    import jax
    import jax.numpy as jnp
    with open(os.path.join(path, _SENTINEL)) as f:
        meta = json.load(f)["tensors"]
    out = {}
    for name, info in meta.items():
        arr = np.load(os.path.join(path, info["file"]))
        if dtype is not None and np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(dtype)
        if mesh is not None and specs is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            spec = specs.get(name, P())
            out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
        else:
            out[name] = jnp.asarray(arr)
    return out


class _AsyncSave:
    def __init__(self, thread):
        self.thread = thread

    def wait(self):
        self.thread.join()

    def done(self):
        return not self.thread.is_alive()


def async_save_state_dict(state_dict: Dict[str, Any], path: str,
                          coordinator_rank: int = 0) -> _AsyncSave:
    """Snapshot to host memory synchronously (cheap: D2H over PCIe/DMA),
    write to disk on a background thread (the orbax async pattern).

    Multi-host: all processes participate in the snapshot only for arrays
    that need a cross-process gather; otherwise non-coordinator ranks skip
    the host copy entirely (no wasted host memory)."""
    import jax
    if jax.process_count() > 1 and jax.process_index() != coordinator_rank:
        # participate in collective gathers for non-addressable arrays,
        # drop the result immediately
        for arr in state_dict.values():
            a = arr._data if hasattr(arr, "_data") else arr
            if not getattr(a, "is_fully_addressable", True):
                _to_host(a)
        t = threading.Thread(target=lambda: None, daemon=True)
        t.start()
        return _AsyncSave(t)
    host_copy = {name: _to_host(arr) for name, arr in state_dict.items()}
    t = threading.Thread(target=save_state_dict, args=(host_copy, path),
                         daemon=True)
    t.start()
    return _AsyncSave(t)


class Converter:
    """Reference static/converter.py parity: re-slice a checkpoint between
    parallel strategies.  On TPU both directions are mechanical because the
    stored artifact is the global tensor:

      merge:  per-shard files + dist attrs → global (``merge_with_dist_attr``)
      slice:  global → per-device shards    (``device_put`` on load)
    """

    def __init__(self, checkpoint_path: str):
        self.path = checkpoint_path

    def convert(self, mesh, specs, dtype=None) -> Dict[str, Any]:
        return load_state_dict(self.path, mesh=mesh, specs=specs,
                               dtype=dtype)

    @staticmethod
    def merge_with_dist_attr(shards, dist_attr) -> np.ndarray:
        """Reassemble a global array from per-rank shard arrays.
        `dist_attr`: {"dims_mapping": [tensor_dim → mesh_axis or -1],
        "process_shape": [mesh dims], "process_group": [ranks]} — the
        reference's TensorDistAttr JSON shape."""
        dims_mapping = dist_attr["dims_mapping"]
        process_shape = dist_attr["process_shape"]
        ranks = dist_attr["process_group"]
        first = np.asarray(shards[0])
        # global shape: multiply sharded dims by their mesh-axis size
        gshape = list(first.shape)
        for tdim, maxis in enumerate(dims_mapping):
            if maxis >= 0:
                gshape[tdim] *= process_shape[maxis]
        out = np.zeros(gshape, first.dtype)
        for rank, shard in zip(ranks, shards):
            # coordinates of this rank in the process mesh
            coord = []
            rem = rank
            for dim in reversed(process_shape):
                coord.append(rem % dim)
                rem //= dim
            coord = coord[::-1]
            index = []
            for tdim, maxis in enumerate(dims_mapping):
                if maxis >= 0:
                    size = np.asarray(shard).shape[tdim]
                    start = coord[maxis] * size
                    index.append(slice(start, start + size))
                else:
                    index.append(slice(None))
            out[tuple(index)] = np.asarray(shard)
        return out

    @staticmethod
    def slice_with_dist_attr(global_arr: np.ndarray, dist_attr):
        """Global array → list of per-rank shards (inverse of merge)."""
        dims_mapping = dist_attr["dims_mapping"]
        process_shape = dist_attr["process_shape"]
        ranks = dist_attr["process_group"]
        shards = []
        for rank in ranks:
            coord = []
            rem = rank
            for dim in reversed(process_shape):
                coord.append(rem % dim)
                rem //= dim
            coord = coord[::-1]
            index = []
            for tdim, maxis in enumerate(dims_mapping):
                if maxis >= 0:
                    size = global_arr.shape[tdim] // process_shape[maxis]
                    start = coord[maxis] * size
                    index.append(slice(start, start + size))
                else:
                    index.append(slice(None))
            shards.append(np.asarray(global_arr[tuple(index)]))
        return shards


class AutoCheckpoint:
    """Checkpoint-restart orchestration (reference auto_checkpoint.py +
    elastic §5.3 re-thought for TPU: XLA jobs are gang-scheduled, so fault
    tolerance = frequent async snapshots + resume-from-latest, not live
    rescale)."""

    def __init__(self, directory: str, keep: int = 3,
                 save_interval_steps: int = 1000):
        self.dir = directory
        self.keep = keep
        self.interval = save_interval_steps
        self._pending: Optional[_AsyncSave] = None
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{step:012d}")

    def latest_step(self) -> Optional[int]:
        steps = []
        for name in os.listdir(self.dir):
            if name.startswith("step_") and os.path.exists(
                    os.path.join(self.dir, name, _SENTINEL)):
                steps.append(int(name[5:]))
        return max(steps) if steps else None

    def maybe_save(self, step: int, state_dict: Dict[str, Any]):
        if step % self.interval:
            return None
        if self._pending is not None:
            self._pending.wait()  # backpressure: one in flight
        self._pending = async_save_state_dict(state_dict,
                                              self._step_dir(step))
        self._gc(step)
        return self._pending

    def restore_latest(self, mesh=None, specs=None):
        step = self.latest_step()
        if step is None:
            return None, None
        return step, load_state_dict(self._step_dir(step), mesh=mesh,
                                     specs=specs)

    def _gc(self, current_step: int):
        steps = sorted(s for s in (
            int(n[5:]) for n in os.listdir(self.dir)
            if n.startswith("step_")) if s < current_step)
        import shutil
        for s in steps[:-(self.keep - 1)] if self.keep > 1 else steps:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
