"""fleet — the hybrid-parallel front door.

Reference parity: ``paddle.distributed.fleet`` — ``Fleet.init``
(fleet/fleet.py:167) builds ``HybridCommunicateGroup`` process groups from
``DistributedStrategy.hybrid_configs`` (fleet/base/distributed_strategy.py:
1353); ``distributed_model`` (fleet/model.py:30) wraps the Layer in
PipelineParallel/TensorParallel/ShardingParallel/DataParallel;
``distributed_optimizer`` (fleet.py:1057) wraps the optimizer in
``HybridParallelOptimizer``.

TPU-native design: ``fleet.init`` builds ONE ``jax.sharding.Mesh`` whose axes
are the hybrid degrees (dp, sharding→fsdp, mp→tp[, pp]); ``distributed_model``
attaches the mesh + a sharding plan (model TP specs composed with the ZeRO
plan); the 'distributed optimizer' is the same optimizer — its state simply
inherits the parameter shardings inside the jit'd TrainStep.  All collective
scheduling is GSPMD's job.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from paddle_tpu.distributed.topology import (CommunicateTopology,
                                             HybridCommunicateGroup)
from paddle_tpu.distributed.sharding import shard_plan

__all__ = ["DistributedStrategy", "init", "fleet", "get_hybrid_communicate_group",
           "distributed_model", "distributed_optimizer", "build_mesh",
           "worker_index", "worker_num"]


@dataclasses.dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1  # sequence parallel (new capability, no ref analog)


class DistributedStrategy:
    """Typed config with the reference's knob surface
    (framework/distributed_strategy.proto exposed at
    fleet/base/distributed_strategy.py).  One schema, every knob."""

    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.sharding_configs: Dict[str, Any] = {"stage": 1,
                                                 "offload": False}
        self.amp = False
        self.amp_configs: Dict[str, Any] = {"init_loss_scaling": 2.0 ** 15,
                                            "use_pure_bf16": False}
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1,
                                                 "schedule_mode": "1F1B"}
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1}
        self.fuse_all_reduce_ops = True  # no-op: XLA fuses
        self.find_unused_parameters = False

    def to_hybrid(self) -> HybridConfig:
        hc = self.hybrid_configs
        return HybridConfig(
            dp_degree=int(hc.get("dp_degree", 1)),
            mp_degree=int(hc.get("mp_degree", 1)),
            pp_degree=int(hc.get("pp_degree", 1)),
            sharding_degree=int(hc.get("sharding_degree", 1)),
            sep_degree=int(hc.get("sep_degree", 1)))


def build_mesh(hybrid: HybridConfig, devices=None):
    """One Mesh for the whole 4-D (+sep) strategy.  Axis order follows the
    reference topology order ["data","pipe","sharding","sep","model"]
    (fleet/base/topology.py:56) so rank placement matches: pp outermost
    after dp (pp stages may span hosts — DCN), mp innermost (rides ICI)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    dims = {"dp": hybrid.dp_degree, "pp": hybrid.pp_degree,
            "sharding": hybrid.sharding_degree, "sep": hybrid.sep_degree,
            "mp": hybrid.mp_degree}
    used = {k: v for k, v in dims.items() if v > 1}
    if not used:
        used = {"dp": 1}
    total = int(np.prod(list(used.values())))
    if total > len(devices):
        raise ValueError(f"strategy needs {total} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:total]).reshape(tuple(used.values()))
    return Mesh(arr, tuple(used.keys()))


class _Fleet:
    """Singleton facade (reference Fleet object, fleet/fleet.py).

    Usable both as the object (``fleet.init(...)``) and, paddle-style, as a
    stand-in for the module (``fleet.DistributedStrategy()``) — the class
    attribute below covers the common ``import ... fleet as fleet`` idiom."""

    DistributedStrategy = None  # filled in after class definition

    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._mesh = None

    def init(self, role_maker=None, is_collective: bool = True,
             strategy: Optional[DistributedStrategy] = None, log_level=None):
        self._strategy = strategy or DistributedStrategy()
        hybrid = self._strategy.to_hybrid()
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "sep", "model"],
            [hybrid.dp_degree, hybrid.pp_degree, hybrid.sharding_degree,
             hybrid.sep_degree, hybrid.mp_degree])
        from paddle_tpu.distributed.env import get_rank
        self._hcg = HybridCommunicateGroup(topo, global_rank=get_rank())
        self._mesh = build_mesh(hybrid)
        return self

    @property
    def mesh(self):
        if self._mesh is None:
            self.init()
        return self._mesh

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        if self._hcg is None:
            self.init()
        return self._hcg

    def distributed_model(self, model):
        """Attach mesh + composed sharding plan to the model.

        Reference (fleet/model.py:30) wraps in PipelineParallel/
        TensorParallel/…; here every strategy is expressed in the specs:
        TP from the model's own ``partition_specs`` / per-param
        ``partition_spec`` annotations, ZeRO from sharding_configs.stage,
        DP as the batch spec."""
        from jax.sharding import PartitionSpec as P

        base: Dict[str, Any] = {}
        # per-parameter annotations (mpu layers set .partition_spec)
        for name, t in model.state_dict(keep_vars=True).items():
            spec = getattr(t, "partition_spec", None)
            if spec is not None:
                base[name] = spec
        # model-level rules (e.g. LlamaForCausalLM.partition_specs)
        if hasattr(type(model), "partition_specs") and hasattr(model, "config"):
            hybrid = self._strategy.to_hybrid()
            rules = type(model).partition_specs(
                model.config, tp_axis="mp",
                fsdp_axis="sharding" if hybrid.sharding_degree > 1 else None)
            for n in model.state_dict(keep_vars=True):
                if n not in base:
                    base[n] = type(model).spec_for(n, rules)

        stage = int(self._strategy.sharding_configs.get("stage", 1))
        hybrid = self._strategy.to_hybrid()
        if hybrid.sharding_degree > 1:
            level = {1: "os", 2: "os_g", 3: "p_g_os"}[stage]
            plan = shard_plan(model, level=level, axis="sharding",
                              axis_size=hybrid.sharding_degree,
                              base_specs=base)
            specs = plan.param_specs
        else:
            specs = {n: base.get(n, P())
                     for n in model.state_dict(keep_vars=True)}

        batch_axes = tuple(a for a, d in (
            ("dp", hybrid.dp_degree), ("sharding", hybrid.sharding_degree))
            if d > 1)
        model._mesh = self._mesh
        model._param_specs = specs
        model._batch_spec = P(batch_axes) if batch_axes else P()
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """Reference wraps in HybridParallelOptimizer (global-norm clip
        allreduced across mp/pp/sharding groups,
        dygraph_optimizer/hybrid_parallel_optimizer.py:238).  Under SPMD the
        global grad norm is computed on the global view inside jit — the
        optimizer's clip already sees true global norms.  Pass-through."""
        optimizer._fleet = self
        return optimizer

    def worker_index(self) -> int:
        from paddle_tpu.distributed.env import get_rank
        return get_rank()

    def worker_num(self) -> int:
        from paddle_tpu.distributed.env import get_world_size
        return get_world_size()

    def barrier_worker(self):
        from paddle_tpu.distributed.communication import barrier
        barrier()


_Fleet.DistributedStrategy = DistributedStrategy
fleet = _Fleet()


def init(role_maker=None, is_collective: bool = True, strategy=None,
         log_level=None):
    return fleet.init(role_maker, is_collective, strategy, log_level)


def get_hybrid_communicate_group():
    return fleet.get_hybrid_communicate_group()


def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def worker_index():
    return fleet.worker_index()


def worker_num():
    return fleet.worker_num()
