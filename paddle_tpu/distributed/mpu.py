"""Model-parallel (tensor-parallel) layers + TP-aware RNG.

Reference parity: ``fleet/layers/mpu/mp_layers.py`` — ``VocabParallelEmbedding``
(:35), ``ColumnParallelLinear`` (:173), ``RowParallelLinear`` (:343),
``ParallelCrossEntropy`` (:524 → c_softmax_with_cross_entropy op) — plus the
RNG tracker (``mpu/random.py:34,88``).

TPU-native design: the reference manually slices weights per rank and calls
``c_identity``/``mp_allreduce`` collectives (mpu/mp_ops.py).  Here each layer
keeps the FULL logical weight and records a **PartitionSpec** on it
(``weight.partition_spec``); under ``jit`` over a mesh, GSPMD shards the
weight and inserts exactly the Megatron collectives (allreduce after
row-parallel, none after column-parallel) — compiler-scheduled over ICI.
The math is identical to the serial layer, which is what makes
parallel==serial parity tests trivial and is the entire point of SPMD.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer import Layer

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy",
           "RNGStatesTracker", "get_rng_state_tracker", "constrain"]

MP_AXIS = "mp"


def _spec(*names):
    from jax.sharding import PartitionSpec as P
    return P(*names)


def constrain(x, spec, mesh=None):
    """with_sharding_constraint when a mesh is active; identity otherwise.
    The activation-sharding hints GSPMD uses in place of the reference's
    explicit c_identity/allreduce calls."""
    data = x._data if hasattr(x, "_data") else x
    try:
        if mesh is not None:
            from jax.sharding import NamedSharding
            data = jax.lax.with_sharding_constraint(
                data, NamedSharding(mesh, spec))
        else:
            data = jax.lax.with_sharding_constraint(data, spec)
    except (ValueError, RuntimeError):
        return x  # no mesh in scope — serial run
    if hasattr(x, "_data"):
        from paddle_tpu.core.tensor import Tensor
        out = Tensor(data)
        out.stop_gradient = x.stop_gradient
        return out
    return data


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded on the mp axis
    (reference mp_layers.py:35: per-rank vocab range + masked lookup +
    allreduce; here: weight sharded P("mp", None), GSPMD turns the gather
    into the same masked-lookup + psum)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, mp_axis: str = MP_AXIS, name=None):
        super().__init__()
        self.mp_axis = mp_axis
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0) if weight_attr is None
            else None)
        self.weight.partition_spec = _spec(mp_axis, None)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """Linear with output dim sharded on mp (Megatron column-parallel;
    reference mp_layers.py:173).  gather_output=True adds a constraint that
    forces GSPMD to all_gather the activation back to replicated."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, mp_group=None,
                 mp_axis: str = MP_AXIS, fuse_matmul_bias=False, name=None):
        super().__init__()
        self.mp_axis = mp_axis
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.weight.partition_spec = _spec(None, mp_axis)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.partition_spec = _spec(mp_axis)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = constrain(out, _spec())  # replicated
        return out


class RowParallelLinear(Layer):
    """Linear with input dim sharded on mp (Megatron row-parallel; reference
    mp_layers.py:343).  The partial matmul results need a sum over mp —
    GSPMD inserts the psum the reference issues as mp_allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, mp_group=None,
                 mp_axis: str = MP_AXIS, fuse_matmul_bias=False, name=None):
        super().__init__()
        self.mp_axis = mp_axis
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.weight.partition_spec = _spec(mp_axis, None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.partition_spec = _spec()  # replicated: added post-psum
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class ParallelCrossEntropy(Layer):
    """Softmax-CE over vocab-sharded logits (reference mp_layers.py:524 →
    ``c_softmax_with_cross_entropy``: per-rank max/sum exchanged by
    allreduce).  Under GSPMD the standard logsumexp-based CE on sharded
    logits compiles to the same two small psums — no custom kernel needed;
    we add the constraint that keeps logits sharded on vocab so the
    compiler doesn't materialise a replicated [tokens, vocab] buffer."""

    def __init__(self, mp_group=None, mp_axis: str = MP_AXIS, name=None,
                 ignore_index: int = -100):
        super().__init__()
        self.mp_axis = mp_axis
        self.ignore_index = ignore_index

    def forward(self, logits, label):
        logits = constrain(logits, _spec(None, self.mp_axis))
        return F.cross_entropy(logits, label, reduction="none",
                               ignore_index=self.ignore_index)


# -- TP-aware RNG (reference mpu/random.py) ----------------------------------

class RNGStatesTracker:
    """Named RNG streams so dropout inside TP regions can draw either a
    mp-local or a global pattern (reference ``RNGStatesTracker``
    mpu/random.py:34: CUDA rng state save/restore; here: named PRNG keys —
    functional, trace-safe)."""

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name: str, seed: int):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = [jax.random.key(seed), 0]

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = states

    def next_key(self, name: str):
        entry = self.states_[name]
        key = jax.random.fold_in(entry[0], entry[1])
        entry[1] += 1
        return key

    @contextlib.contextmanager
    def rng_state(self, name: str = "model_parallel_rng"):
        """Run the body with this named stream driving paddle_tpu's global
        RNG (used by Dropout in mp regions, reference mp_layers usage)."""
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        import paddle_tpu.core.state as state
        key = self.next_key(name)
        old = state.get_rng_state()
        state.set_rng_state(jax.random.key_data(key))
        try:
            yield
        finally:
            state.set_rng_state(old)


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    """Reference: ``fleet.meta_parallel.get_rng_state_tracker``
    (mpu/random.py:84)."""
    return _TRACKER


def model_parallel_random_seed(seed: int = 2023, mp_rank: int = 0):
    """Seed the tracker with (global, local=global+offset) streams
    (reference mpu/random.py:88)."""
    _TRACKER.reset()
    _TRACKER.add("global_seed", seed)
    _TRACKER.add("model_parallel_rng", seed + 1024 + mp_rank)
