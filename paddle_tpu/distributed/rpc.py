"""paddle_tpu.distributed.rpc — remote python-function invocation.

Reference parity: ``paddle.distributed.rpc``
(python/paddle/distributed/rpc/rpc.py — init_rpc/rpc_sync/rpc_async/
shutdown/get_worker_info over a C++ brpc agent,
fluid/distributed/rpc/rpc_agent.cc).  TPU-native translation: the control
plane that brpc provided is a per-process threaded TCP server speaking
length-prefixed pickled frames, with worker discovery through the native
TCPStore (csrc/store) — the same store that bootstraps rendezvous.  RPC
is CONTROL traffic (eval loops, metric aggregation, dataset brokering);
tensor traffic belongs to the compiled collectives over ICI, never here.

Security note (same stance as the reference): frames are pickled python —
use only inside a trusted cluster network, like the NCCL/gloo ports.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Optional

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info", "WorkerInfo"]

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_TIMEOUT = float(os.environ.get("PADDLE_RPC_TIMEOUT", "120"))

_state: Dict[str, Any] = {
    "server": None, "store": None, "workers": {}, "self": None,
    "pool": None,
}


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed mid-frame")
        buf += chunk
    return buf


def _send_frame(sock, payload: bytes):
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_frame(sock) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


class _Server:
    """Per-process executor: accepts connections, runs pickled calls on a
    thread pool, streams back (ok, result-or-exception)."""

    def __init__(self, port_hint: int = 0, max_workers: int = 8):
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port_hint))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="pt_rpc")
        self._running = True
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            with self._conn_lock:
                # registration and the stop() drain both run under the
                # lock: a connection accepted while stop() is in flight
                # must be closed here, never submitted to a shut pool
                if not self._running:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    break
                self._conns.add(conn)
            try:
                self._pool.submit(self._serve, conn)
            except RuntimeError:  # pool already shut down
                try:
                    conn.close()
                except OSError:
                    pass
                break

    def _serve(self, conn):
        try:
            with conn:
                while True:
                    try:
                        frame = _recv_frame(conn)
                    except (ConnectionError, OSError):
                        return
                    try:
                        fn, args, kwargs = pickle.loads(frame)
                        result, ok = fn(*args, **(kwargs or {})), True
                    except BaseException as e:  # noqa: BLE001 — shipped back
                        result, ok = e, False
                    try:
                        _send_frame(conn, pickle.dumps((ok, result)))
                    except Exception:
                        # unpicklable result: ship the repr as an error
                        _send_frame(conn, pickle.dumps(
                            (False, RuntimeError(
                                f"rpc result not picklable: {result!r}"))))
        except Exception:
            pass  # connection torn down mid-serve
        finally:
            with self._conn_lock:
                self._conns.discard(conn)

    def stop(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass
        # unblock serve threads parked in recv on live connections —
        # ThreadPoolExecutor threads are non-daemon and joined at
        # interpreter exit, so a hung peer must not hang OUR exit.
        # _running is already False, so under the lock the accept loop
        # can no longer register new connections behind this drain.
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._pool.shutdown(wait=False)


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Start this process's RPC agent and rendezvous with the others.

    Reference signature (rpc.py:73).  rank/world_size/master default from
    the launcher env (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
    PADDLE_MASTER).  Worker infos are exchanged through the native
    TCPStore at ``master_endpoint``."""
    from paddle_tpu.distributed.tcp_store import TCPStore
    if _state["server"] is not None:
        raise RuntimeError("init_rpc called twice (call shutdown() first)")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:12600")
    host, port = master_endpoint.rsplit(":", 1)

    # store FIRST: a failed rendezvous must not leak the agent's
    # listening socket / accept thread across init retries
    store = TCPStore(host, int(port), is_master=(rank == 0),
                     world_size=world_size, timeout=_DEFAULT_TIMEOUT)
    server = _Server()
    try:
        ip = socket.gethostbyname(socket.gethostname()) \
            if host not in ("127.0.0.1", "localhost") else "127.0.0.1"
        info = WorkerInfo(name, rank, ip, server.port)
        store.set(f"rpc_worker_{rank}", pickle.dumps(tuple(info)))
        store.barrier("rpc_init")
        workers = {}
        for r in range(world_size):
            wi = WorkerInfo(*pickle.loads(store.get(f"rpc_worker_{r}")))
            workers[wi.name] = wi
        if len(workers) != world_size:
            raise RuntimeError("rpc worker names must be unique per process")
    except BaseException:
        server.stop()
        store.close()
        raise
    _state.update(server=server, store=store, workers=workers, self=info,
                  pool=ThreadPoolExecutor(max_workers=8,
                                          thread_name_prefix="pt_rpc_cli"))


def _connect(to: str, timeout: float):
    workers = _state["workers"]
    if to not in workers:
        raise ValueError(f"unknown rpc worker {to!r}; known: "
                         f"{sorted(workers)}")
    wi = workers[to]
    sock = socket.create_connection((wi.ip, wi.port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _call(to, fn, args, kwargs, timeout):
    sock = _connect(to, timeout)
    try:
        _send_frame(sock, pickle.dumps((fn, tuple(args or ()),
                                        dict(kwargs or {}))))
        sock.settimeout(timeout)
        ok, result = pickle.loads(_recv_frame(sock))
    finally:
        sock.close()
    if not ok:
        raise result
    return result


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout: float = _DEFAULT_TIMEOUT):
    """Run ``fn(*args, **kwargs)`` on worker ``to``; return its result
    (reference rpc.py:141).  Remote exceptions re-raise here."""
    if _state["server"] is None:
        raise RuntimeError("rpc not initialized; call init_rpc first")
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout: float = _DEFAULT_TIMEOUT) -> Future:
    """Like rpc_sync but returns a Future (reference rpc.py:179 —
    ``.wait()`` parity is via ``concurrent.futures.Future.result``, and a
    ``wait`` alias is attached for drop-in use)."""
    if _state["server"] is None:
        raise RuntimeError("rpc not initialized; call init_rpc first")
    fut = _state["pool"].submit(_call, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle Future API parity
    return fut


def shutdown():
    """Barrier with every worker, then stop the agent (reference
    rpc.py:270).  The barrier is what makes this graceful — every
    worker's issued calls have returned before anyone stops; after it,
    stop() force-closes any connection a crashed/hung peer left open so
    local interpreter exit can never hang on a serve thread."""
    store = _state["store"]
    if store is not None:
        try:
            store.barrier("rpc_shutdown")
        except Exception:
            pass  # a crashed peer must not block local teardown
    server = _state["server"]
    if server is not None:
        server.stop()
    pool = _state["pool"]
    if pool is not None:
        pool.shutdown(wait=False)
    if store is not None:
        store.close()
    _state.update(server=None, store=None, workers={}, self=None,
                  pool=None)


def get_worker_info(name: str) -> WorkerInfo:
    return _state["workers"][name]


def get_all_worker_infos():
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    if _state["self"] is None:
        raise RuntimeError("rpc not initialized")
    return _state["self"]
