"""paddle_tpu.distributed — TPU-native distributed training.

Replaces the reference's distributed stack (python/paddle/distributed/:
ProcessGroups+NCCL, fleet 4-D hybrid parallel, auto_parallel planner) with
ONE substrate: a ``jax.sharding.Mesh`` + GSPMD sharding annotations + XLA
compiler-scheduled collectives over ICI/DCN.  See SURVEY.md §2.4/§2.5 for
the strategy-by-strategy mapping.
"""

from paddle_tpu.distributed.env import (  # noqa: F401
    ParallelEnv, device_count, get_rank, get_world_size, init_parallel_env,
    is_initialized)
from paddle_tpu.distributed.communication import (  # noqa: F401
    Group, ReduceOp, all_gather, all_reduce, all_to_all, barrier, broadcast,
    get_group, new_group, ppermute, recv, reduce, reduce_scatter, scatter,
    send, shard_map, shift)
from paddle_tpu.distributed.auto_parallel import (  # noqa: F401
    Partial, ProcessMesh, Replicate, Shard, dtensor_from_fn, get_mesh,
    reshard, set_mesh, shard_layer, shard_op, shard_tensor)
from paddle_tpu.distributed.topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup)
from paddle_tpu.distributed.parallel import DataParallel  # noqa: F401
from paddle_tpu.distributed.sharding import (  # noqa: F401
    ShardingPlan, group_sharded_parallel, shard_plan)
from paddle_tpu.distributed import fleet as _fleet_mod  # noqa: F401
from paddle_tpu.distributed.fleet import (  # noqa: F401
    DistributedStrategy, fleet)
from paddle_tpu.distributed import mpu  # noqa: F401
from paddle_tpu.distributed.pipeline import (  # noqa: F401
    LayerDesc, PipelineLayer, PipelineTrainStep, SegmentLayers,
    SharedLayerDesc, build_1f1b_schedule, build_interleaved_schedule,
    pipeline_1f1b, pipeline_interleaved, spmd_pipeline, stack_stage_params)
from paddle_tpu.distributed.moe import (  # noqa: F401
    ExpertFFN, GShardGate, MoELayer, NaiveGate, SwitchGate,
    moe_forward_a2a, moe_forward_index, moe_forward_ragged,
    moe_shard_a2a, moe_shard_index_a2a, top_k_gating)
from paddle_tpu.distributed.sequence_parallel import (  # noqa: F401
    make_ring_attention, make_striped_ring_attention, make_ulysses_attention,
    ring_attention, ring_flash_enabled, striped_ring_attention,
    ulysses_attention)
from paddle_tpu.distributed import checkpoint  # noqa: F401
from paddle_tpu.distributed.elastic import (  # noqa: F401
    ElasticAgent, ElasticManager)
from paddle_tpu.distributed import rpc  # noqa: F401
from paddle_tpu.distributed.checkpoint import (  # noqa: F401
    AutoCheckpoint, Converter, async_save_state_dict, load_state_dict,
    save_state_dict, validate_checkpoint)

__all__ = [
    "ParallelEnv", "init_parallel_env", "get_rank", "get_world_size",
    "device_count", "is_initialized",
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce",
    "all_gather", "all_to_all", "reduce_scatter", "broadcast", "reduce",
    "scatter", "send", "recv", "barrier", "ppermute", "shift",
    "ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
    "reshard", "shard_layer", "shard_op", "dtensor_from_fn", "get_mesh",
    "set_mesh",
    "CommunicateTopology", "HybridCommunicateGroup",
    "DataParallel", "group_sharded_parallel", "shard_plan", "ShardingPlan",
    "fleet", "DistributedStrategy", "mpu",
    "LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer",
    "spmd_pipeline", "stack_stage_params",
    "MoELayer", "ExpertFFN", "NaiveGate", "SwitchGate", "GShardGate",
    "top_k_gating",
    "ring_attention", "striped_ring_attention", "ulysses_attention",
    "make_ring_attention", "make_striped_ring_attention",
    "make_ulysses_attention", "ring_flash_enabled",
    "checkpoint", "save_state_dict", "load_state_dict",
    "async_save_state_dict", "validate_checkpoint", "Converter",
    "AutoCheckpoint",
    "ElasticAgent", "ElasticManager",
]
