"""Process/rank environment — the TPU-native analog of the reference's
``init_parallel_env`` bootstrap (python/paddle/distributed/parallel.py:915:
env parsing → TCPStore → ProcessGroup creation).

On TPU there is no ProcessGroup runtime to build: JAX is single-controller
SPMD, collectives are compiled into the executable and ride ICI.  What remains
of the reference's bootstrap is (a) multi-host rendezvous —
``jax.distributed.initialize`` plays the TCPStore role — and (b) a rank/world
facade (``ParallelEnv``) so fleet-style code keeps working.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
    "device_count", "is_initialized",
]

_INITIALIZED = False


def is_initialized() -> bool:
    return _INITIALIZED


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None):
    """Bootstrap multi-host execution.

    Reference parity: ``paddle.distributed.init_parallel_env``
    (python/paddle/distributed/parallel.py:915).  There the per-rank process
    parses ``PADDLE_TRAINER_ID``/``PADDLE_CURRENT_ENDPOINT`` and creates a
    TCPStore + NCCL ProcessGroup.  Here each *host* process calls
    ``jax.distributed.initialize`` (rendezvous at ``coordinator_address``),
    after which ``jax.devices()`` spans every chip in the slice and compiled
    collectives handle all cross-chip traffic.

    Single-process (1 host, N local devices) needs no initialization at all;
    this function is then a no-op and only records state.
    """
    global _INITIALIZED
    import jax

    if coordinator_address is None:
        coordinator_address = os.environ.get("PADDLE_MASTER",
                                             os.environ.get(
                                                 "COORDINATOR_ADDRESS"))
    if num_processes is None:
        n = os.environ.get("PADDLE_TRAINERS_NUM",
                           os.environ.get("NUM_PROCESSES"))
        num_processes = int(n) if n else None
    if process_id is None:
        r = os.environ.get("PADDLE_TRAINER_ID", os.environ.get("PROCESS_ID"))
        process_id = int(r) if r else None

    if coordinator_address and (num_processes or 0) > 1:
        # multi-process on the CPU backend (launch tests, local sims)
        # needs an explicit cross-process collectives implementation —
        # the default 'none' raises "Multiprocess computations aren't
        # implemented on the CPU backend" at the first collective.  Must
        # be set BEFORE the backend initializes; harmless elsewhere.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    _INITIALIZED = True
    return ParallelEnv()


def get_rank() -> int:
    """Host-process index (reference: ``paddle.distributed.get_rank``)."""
    import jax
    return jax.process_index()


def get_world_size() -> int:
    """Number of host processes (reference: ``get_world_size``).

    Note the unit: the reference counts one rank per GPU; under JAX one
    process drives many chips, so device-level parallelism is
    ``device_count()`` and world_size is the process count."""
    import jax
    return jax.process_count()


def device_count() -> int:
    import jax
    return jax.device_count()


class ParallelEnv:
    """Rank/world facade, parity with ``paddle.distributed.ParallelEnv``."""

    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return 0

    @property
    def dev_id(self) -> int:
        return 0
