"""Auto-parallel annotation API: ProcessMesh + shard_tensor + placements.

Reference parity: ``paddle.distributed.auto_parallel`` — ``ProcessMesh``
(auto_parallel/process_mesh.py), ``shard_tensor``/``shard_op`` annotation
(auto_parallel/interface.py), and behind them the Completer/Partitioner/
Resharder machinery (static/completion.py:107, partitioner.py:40,
reshard.py:1010) that propagates dist attrs and inserts comm ops.

TPU-native design: that entire planning pipeline IS GSPMD.  ``ProcessMesh``
wraps ``jax.sharding.Mesh``; ``shard_tensor`` attaches a ``NamedSharding``;
propagation, partitioning, and resharding happen inside XLA during ``jit``
compilation.  ``reshard`` is ``jax.device_put`` with a new sharding.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "reshard", "shard_layer", "dtensor_from_fn", "get_mesh",
           "set_mesh", "shard_op"]


# -- placements (reference: paddle.distributed.{Shard,Replicate,Partial}) ----

class Placement:
    pass


class Shard(Placement):
    """Shard tensor dim `dim` across the corresponding mesh axis."""

    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def is_replicated(self):
        return True


class Partial(Placement):
    """Pending-reduction placement.  GSPMD tracks partial sums internally;
    at the annotation API level we accept it and treat it as Replicate
    (the compiler decides when to materialise the reduction)."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """Cartesian mesh of devices with named axes.

    Reference: ``paddle.distributed.ProcessMesh(mesh, dim_names)``
    (auto_parallel/process_mesh.py).  Wraps ``jax.sharding.Mesh`` — the
    object GSPMD plans over."""

    def __init__(self, mesh: Union[Sequence, np.ndarray],
                 dim_names: Optional[List[str]] = None, _devices=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        assert arr.ndim == len(dim_names)
        self._shape = arr.shape
        self._dim_names = list(dim_names)
        self._process_ids = arr.reshape(-1).tolist()
        self._jax_mesh = None
        self._devices = _devices

    # reference-parity properties
    @property
    def shape(self):
        return list(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return list(self._process_ids)

    @property
    def ndim(self):
        return len(self._shape)

    def get_dim_size(self, name: str) -> int:
        return self._shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, name, pid):
        coord = np.argwhere(
            np.asarray(self._process_ids).reshape(self._shape) == pid)
        return int(coord[0][self._dim_names.index(name)])

    # jax bridge
    @property
    def jax_mesh(self):
        if self._jax_mesh is None:
            import jax
            from jax.sharding import Mesh
            devs = self._devices
            if devs is None:
                all_devs = jax.devices()
                devs = [all_devs[i] for i in self._process_ids]
            self._jax_mesh = Mesh(
                np.asarray(devs).reshape(self._shape), self._dim_names)
        return self._jax_mesh

    def __enter__(self):
        set_mesh(self)
        return self

    def __exit__(self, *exc):
        set_mesh(None)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


_CURRENT_MESH: List[Optional[ProcessMesh]] = [None]


def set_mesh(mesh: Optional[ProcessMesh]):
    _CURRENT_MESH[0] = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _CURRENT_MESH[0]


def _placements_to_spec(placements: Sequence[Placement], ndim: int,
                        dim_names: List[str]):
    """[Placement per mesh axis] → PartitionSpec over tensor dims."""
    from jax.sharding import PartitionSpec as P
    per_dim: List[Any] = [None] * ndim
    for axis_i, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim if pl.dim >= 0 else pl.dim + ndim
            if per_dim[d] is None:
                per_dim[d] = dim_names[axis_i]
            elif isinstance(per_dim[d], tuple):
                per_dim[d] = per_dim[d] + (dim_names[axis_i],)
            else:
                per_dim[d] = (per_dim[d], dim_names[axis_i])
    return P(*per_dim)


def shard_tensor(tensor, mesh: ProcessMesh,
                 placements: Sequence[Placement],
                 stop_gradient: Optional[bool] = None):
    """Place `tensor` on `mesh` with `placements` (one per mesh dim).

    Reference: ``paddle.distributed.shard_tensor``
    (auto_parallel/interface.py).  Returns the same Tensor type with its
    array device_put under the induced NamedSharding — downstream jit'd
    computation inherits the sharding and GSPMD propagates it."""
    import jax
    from jax.sharding import NamedSharding

    data = tensor._data if hasattr(tensor, "_data") else tensor
    spec = _placements_to_spec(placements, data.ndim, mesh.dim_names)
    sharded = jax.device_put(data, NamedSharding(mesh.jax_mesh, spec))
    if hasattr(tensor, "_data"):
        from paddle_tpu.core.tensor import Tensor
        out = Tensor(sharded)
        if stop_gradient is not None:
            out.stop_gradient = stop_gradient
        else:
            out.stop_gradient = tensor.stop_gradient
        return out
    return sharded


def reshard(tensor, mesh: ProcessMesh, placements: Sequence[Placement]):
    """Change a tensor's distribution — reference Resharder
    (static/reshard.py:1010) inserted all_gather/all_to_all/slice ops;
    here ``jax.device_put`` with the new sharding compiles to the same
    collectives."""
    return shard_tensor(tensor, mesh, placements)


def shard_layer(layer, mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Shard every parameter of `layer` via `shard_fn(name, layer, mesh)`;
    default fully replicates (reference: paddle.distributed.shard_layer)."""
    for name, param in layer.named_parameters():
        if shard_fn is not None:
            placements = shard_fn(name, layer, mesh)
        else:
            placements = [Replicate() for _ in range(mesh.ndim)]
        if placements is not None:
            sharded = shard_tensor(param, mesh, placements)
            param._set_data(sharded._data)
    return layer


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    """Build a sharded tensor from a creation fn without materialising the
    replicated intermediate (reference: dtensor_from_fn)."""
    import jax
    from jax.sharding import NamedSharding

    sample = jax.eval_shape(lambda: fn(*args, **kwargs)._data
                            if hasattr(fn(*args, **kwargs), "_data")
                            else fn(*args, **kwargs))
    spec = _placements_to_spec(placements, len(sample.shape),
                               mesh.dim_names)
    sharding = NamedSharding(mesh.jax_mesh, spec)
    make = lambda: fn(*args, **kwargs)
    out = jax.jit(lambda: make()._data if hasattr(make(), "_data")
                  else make(), out_shardings=sharding)()
    from paddle_tpu.core.tensor import Tensor
    return Tensor(out)


def shard_op(op_fn, mesh: ProcessMesh = None, in_placements=None,
             out_placements=None):
    """Annotate an op's output sharding (reference: shard_op).  Under GSPMD
    this is `with_sharding_constraint` on the result."""
    def wrapped(*args, **kwargs):
        out = op_fn(*args, **kwargs)
        if mesh is not None and out_placements is not None:
            import jax
            from jax.sharding import NamedSharding
            data = out._data if hasattr(out, "_data") else out
            spec = _placements_to_spec(out_placements, data.ndim,
                                       mesh.dim_names)
            data = jax.lax.with_sharding_constraint(
                data, NamedSharding(mesh.jax_mesh, spec))
            if hasattr(out, "_data"):
                from paddle_tpu.core.tensor import Tensor
                return Tensor(data)
            return data
        return out
    return wrapped
