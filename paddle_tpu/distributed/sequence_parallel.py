"""Sequence / context parallelism: ring attention + Ulysses (DeepSpeed-style).

**New capability — no reference port.** SURVEY.md §5.7 verified the reference
has NO sequence parallelism (grep over the snapshot); its long-context story
is flash attention + recompute.  This module is designed TPU-first:

* **Ring attention** (`ring_attention`): the sequence dim is sharded on the
  ``sp`` mesh axis; each device keeps its Q shard and rotates K/V shards
  around the ring with ``lax.ppermute`` (one ICI hop per step), folding each
  incoming block into a running online-softmax — so peak memory is
  O(seq/sp) and the N² score matrix never materialises anywhere.
* **Ulysses** (`ulysses_attention`): ``all_to_all`` swaps the head dim for
  the sequence dim (heads must divide sp), runs dense/flash attention on
  full sequences of the local heads, and swaps back.  Two all_to_alls per
  layer vs sp ppermutes — better when heads ≥ sp and ICI all_to_all
  bandwidth is good (within a pod).

Both are plain differentiable JAX (ppermute/all_to_all have transposes), so
jax.grad through a shard_map'd call gives the distributed backward.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.distributed.communication import axis_size as _axis_size

__all__ = ["ring_attention", "ulysses_attention", "make_ring_attention",
           "make_ulysses_attention"]

_NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Blockwise ring attention INSIDE shard_map.

    q, k, v: local shards [batch, seq_local, heads, head_dim]; the global
    sequence is the concatenation over the sp axis in rank order.
    Returns the local output shard [batch, seq_local, heads, head_dim].
    """
    sp = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    # GQA: broadcast kv heads
    if k.shape[2] != h:
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    qf = q.astype(jnp.float32)
    q_pos = idx * s + jnp.arange(s)                    # global q positions

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # async ring exchange (ISSUE 15): with PADDLE_TPU_COLLECTIVE_OVERLAP
    # the rotation is issued BEFORE the fold — the ppermute has no data
    # dependency on this step's softmax/matmuls, so an async-collective
    # scheduler streams the next K/V shard in under the current fold's
    # compute instead of paying the ICI hop at the step boundary.
    # Trace-time routing: knob off keeps the exact previous program.
    from paddle_tpu.distributed.sharding import (overlap_enabled,
                                                 overlap_path_counter)
    overlap = overlap_enabled()
    if overlap:
        overlap_path_counter().labels(path="ring_exchange").inc()

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src = (idx - i) % sp                           # owner of current kv
        if overlap:
            # issue the rotation first: comm rides under the fold below
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k_cur.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * s + jnp.arange(s)
            mask = q_pos[:, None] >= k_pos[None, :]    # [sq, sk]
            scores = jnp.where(mask[None, None], scores, _NEG_INF)
        m_cur = jnp.max(scores, axis=-1, keepdims=True)   # [b,h,q,1]
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32))
        o_new = o * corr + pv
        if not overlap:
            # rotate kv to the next rank (skip after the last fold)
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    from paddle_tpu.distributed.communication import pvary_like
    # accumulators must vary over EVERY manual axis the kv blocks vary
    # over (not just the ring axis) — on an (sp, tp) mesh the heads are
    # tp-sharded and the carry types must agree across scan steps
    o0 = pvary_like(jnp.zeros((b, h, s, d), jnp.float32), qf,
                    fallback_axes=(axis_name,))
    m0 = pvary_like(jnp.full((b, h, s, 1), _NEG_INF, jnp.float32), qf,
                    fallback_axes=(axis_name,))
    l0 = pvary_like(jnp.zeros((b, h, s, 1), jnp.float32), qf,
                    fallback_axes=(axis_name,))
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(sp))
    safe_l = jnp.where(l > 0, l, 1.0)
    out = (o / safe_l).astype(q.dtype)                 # [b,h,s,d]
    return jnp.swapaxes(out, 1, 2)                     # [b,s,h,d]


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                      scale: Optional[float] = None,
                      attn_fn=None):
    """Ulysses sequence parallelism INSIDE shard_map.

    q, k, v: local shards [batch, seq_local, heads, head_dim]; heads must be
    divisible by the sp axis size.  all_to_all to [batch, seq_global,
    heads_local, head_dim], run full attention per local head, swap back.
    `attn_fn(q, k, v, causal, scale)` defaults to the XLA sdpa; pass the
    flash kernel for long sequences.
    """
    sp = _axis_size(axis_name)
    b, s, h, d = q.shape
    if h % sp:
        raise ValueError(f"heads {h} not divisible by sp={sp}")

    def swap_in(x):   # [b, s_l, h, d] -> [b, s_g, h_l, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def swap_out(x):  # [b, s_g, h_l, d] -> [b, s_l, h, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = swap_in(q), swap_in(k), swap_in(v)
    if attn_fn is None:
        from paddle_tpu.nn.functional.attention import _sdpa_reference
        out = _sdpa_reference(qg, kg, vg, is_causal=causal, scale=scale)
    else:
        out = attn_fn(qg, kg, vg, causal=causal, scale=scale)
    return swap_out(out)


def _wrap_shard_map(fn, mesh, axis_name, seq_axis=1):
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.communication import shard_map
    spec = [None, None, None, None]
    spec[seq_axis] = axis_name
    spec = P(*spec)
    # every operand is sp-sharded (nothing replicated → no auto-psum to
    # lose); 0.4.x's rep checker trips on the pvary-less scan carry, so
    # relax it there only
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, legacy_check_rep=False)


def make_ring_attention(mesh, axis_name: str = "sp", causal: bool = False,
                        scale=None):
    """Top-level entry: global [b, seq, h, d] arrays sharded on `axis_name`
    → shard_map'd ring attention."""
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, scale=scale)
    return _wrap_shard_map(lambda q, k, v: fn(q, k, v), mesh, axis_name)


def make_ulysses_attention(mesh, axis_name: str = "sp",
                           causal: bool = False, scale=None, attn_fn=None):
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal, scale=scale, attn_fn=attn_fn)
    return _wrap_shard_map(lambda q, k, v: fn(q, k, v), mesh, axis_name)
