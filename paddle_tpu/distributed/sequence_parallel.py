"""Sequence / context parallelism: ring attention + Ulysses (DeepSpeed-style).

**New capability — no reference port.** SURVEY.md §5.7 verified the reference
has NO sequence parallelism (grep over the snapshot); its long-context story
is flash attention + recompute.  This module is designed TPU-first:

* **Ring attention** (`ring_attention`): the sequence dim is sharded on the
  ``sp`` mesh axis; each device keeps its Q shard and rotates K/V shards
  around the ring with ``lax.ppermute`` (one ICI hop per step), folding each
  incoming block into a running online-softmax — so peak memory is
  O(seq/sp) and the N² score matrix never materialises anywhere.  Two
  per-hop folds are available: the dense online-softmax math (default —
  exact at any head_dim) and a **flash-backed** fold (``impl="flash"`` or
  ``PADDLE_TPU_RING_FLASH=1``) that runs the flash-attention Pallas kernel
  on each incoming K/V shard and merges hops by log-sum-exp, so the local
  score matrix never materialises either — O(seq/sp) total memory, which is
  what lets seq ≫ 2048 train across chips.  Causal hops resolve by ring
  position (``lax.switch``): the diagonal hop runs the kernel's causal
  path, earlier shards run full attention, later shards are skipped.
* **Striped ring attention** (`striped_ring_attention`): tokens are laid
  out round-robin (local slot j ↔ global j·sp + rank), so under a causal
  mask every hop carries an (almost) equal triangle of work instead of
  rank 0 idling — the Striped Attention load-balance fix.  The per-hop
  causal mask reduces to ``j_q >= j_k`` (diagonal-inclusive when
  rank ≥ source, strict otherwise); fully-masked rows are guarded so the
  fold never folds ``exp(0)`` garbage.
* **Ulysses** (`ulysses_attention`): ``all_to_all`` swaps the head dim for
  the sequence dim (heads must divide sp), runs dense/flash attention on
  full sequences of the local heads, and swaps back.  Two all_to_alls per
  layer vs sp ppermutes — better when heads ≥ sp and ICI all_to_all
  bandwidth is good (within a pod).

Masking is dtype-aware (:func:`mask_value`: half of ``finfo.min`` for the
score dtype, so two masked scores can never sum past the representable
range) and the fold guards rows that have seen no real key yet —
``exp(mask - mask) == 1`` used to pollute the accumulator whenever a hop
was fully masked before any real hop, which plain causal ring ordering
happens to avoid (hop 0 is always the diagonal) but striped layouts and
padded tails do not.

Both are plain differentiable JAX (ppermute/all_to_all have transposes), so
jax.grad through a shard_map'd call gives the distributed backward.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.distributed.communication import axis_size as _axis_size

__all__ = ["ring_attention", "striped_ring_attention", "ulysses_attention",
           "make_ring_attention", "make_striped_ring_attention",
           "make_ulysses_attention", "ring_flash_enabled", "mask_value"]

_NEG_INF = -1e30   # legacy floor; real masking routes through mask_value()


def mask_value(dtype=jnp.float32) -> float:
    """Dtype-aware large-negative mask score: half of ``finfo.min`` for
    the dtype the scores are computed in, so the sum of two masked
    scores (or mask + finite score) stays representable — ``-1e30``
    overflows to ``-inf`` the moment bf16/fp16 score math touches it."""
    return float(jnp.finfo(jnp.dtype(dtype)).min) / 2


def ring_flash_enabled() -> bool:
    """``PADDLE_TPU_RING_FLASH=1`` makes the flash-backed per-hop fold
    the default ``ring_attention`` implementation."""
    raw = os.environ.get("PADDLE_TPU_RING_FLASH")
    return raw is not None and raw.strip().lower() in ("1", "true", "yes",
                                                       "on")


def _overlap_state():
    """(overlap_enabled, counter_inc) — PR 15's ppermute-before-fold
    trace-time routing, shared by every ring variant."""
    from paddle_tpu.distributed.sharding import (overlap_enabled,
                                                 overlap_path_counter)
    on = overlap_enabled()
    if on:
        overlap_path_counter().labels(path="ring_exchange").inc()
    return on


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None,
                   impl: Optional[str] = None):
    """Blockwise ring attention INSIDE shard_map.

    q, k, v: local shards [batch, seq_local, heads, head_dim]; the global
    sequence is the concatenation over the sp axis in rank order.
    ``impl``: "dense" (online-softmax fold, exact at any head_dim),
    "flash" (per-hop flash-attention kernel + lse merge — O(seq/sp)
    memory, needs flash-legal shapes), or None → the
    PADDLE_TPU_RING_FLASH knob (off → dense, the previous program).
    Returns the local output shard [batch, seq_local, heads, head_dim].
    """
    if impl is None:
        impl = "flash" if ring_flash_enabled() else "dense"
    if impl not in ("dense", "flash"):
        raise ValueError(f"unknown ring impl {impl!r}")
    if impl == "flash":
        return _ring_flash(q, k, v, axis_name=axis_name, causal=causal,
                           scale=scale)
    return _ring_dense(q, k, v, axis_name=axis_name, causal=causal,
                       scale=scale, striped=False)


def striped_ring_attention(q, k, v, axis_name: str = "sp",
                           causal: bool = True,
                           scale: Optional[float] = None):
    """Striped ring attention INSIDE shard_map: local slot j holds
    global token ``j * sp + rank`` (callers stripe the sequence:
    ``x[:, rank::sp]``), which balances the causal triangle across hops
    — with the contiguous layout, hop i attends src > rank to nothing
    while rank sp-1 does full work.  The per-hop mask is
    ``j_q >= j_k + (rank < src)``: diagonal-inclusive when the query
    rank is at or past the source rank, strict otherwise."""
    return _ring_dense(q, k, v, axis_name=axis_name, causal=causal,
                       scale=scale, striped=True)


def _ring_dense(q, k, v, *, axis_name, causal, scale, striped):
    sp = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    # GQA: broadcast kv heads
    if k.shape[2] != h:
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    qf = q.astype(jnp.float32)
    neg = mask_value(jnp.float32)                      # scores are fp32
    local = jnp.arange(s)
    q_pos = idx * s + local                            # global q positions

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    from paddle_tpu.robustness import fault_point
    # dead-ring-peer drill: fires as the K/V rotation is laid out — the
    # trace fails loudly (never a silent wrong answer) and nothing is
    # cached, so clearing the fault restores the path on the next call
    fault_point("sp.ring_peer", axis=axis_name, sp=int(sp), impl="dense")

    # async ring exchange (ISSUE 15): with PADDLE_TPU_COLLECTIVE_OVERLAP
    # the rotation is issued BEFORE the fold — the ppermute has no data
    # dependency on this step's softmax/matmuls, so an async-collective
    # scheduler streams the next K/V shard in under the current fold's
    # compute instead of paying the ICI hop at the step boundary.
    # Trace-time routing: knob off keeps the exact previous program.
    overlap = _overlap_state()

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src = (idx - i) % sp                           # owner of current kv
        if overlap:
            # issue the rotation first: comm rides under the fold below
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k_cur.astype(jnp.float32)) * scale
        if causal:
            if striped:
                # local slot j is global j*sp + rank: strict triangle
                # against sources this rank has not yet passed
                strict = (idx < src).astype(local.dtype)
                mask = local[:, None] >= (local[None, :] + strict)
            else:
                k_pos = src * s + local
                mask = q_pos[:, None] >= k_pos[None, :]    # [sq, sk]
            scores = jnp.where(mask[None, None], scores, neg)
        m_cur = jnp.max(scores, axis=-1, keepdims=True)   # [b,h,q,1]
        m_new = jnp.maximum(m, m_cur)
        # rows that have seen no real key keep m_new at the mask floor;
        # without the guard exp(mask - mask) == 1 folds garbage rows in
        # (plain causal ordering dodges this — hop 0 is the diagonal —
        # striped layouts and padded tails do not)
        alive = m_new > neg * 0.5
        p = jnp.where(alive, jnp.exp(scores - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32))
        o_new = o * corr + pv
        if not overlap:
            # rotate kv to the next rank (skip after the last fold)
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    from paddle_tpu.distributed.communication import pvary_like
    # accumulators must vary over EVERY manual axis the kv blocks vary
    # over (not just the ring axis) — on an (sp, tp) mesh the heads are
    # tp-sharded and the carry types must agree across scan steps
    o0 = pvary_like(jnp.zeros((b, h, s, d), jnp.float32), qf,
                    fallback_axes=(axis_name,))
    m0 = pvary_like(jnp.full((b, h, s, 1), neg, jnp.float32), qf,
                    fallback_axes=(axis_name,))
    l0 = pvary_like(jnp.zeros((b, h, s, 1), jnp.float32), qf,
                    fallback_axes=(axis_name,))
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(sp))
    safe_l = jnp.where(l > 0, l, 1.0)
    out = (o / safe_l).astype(q.dtype)                 # [b,h,s,d]
    return jnp.swapaxes(out, 1, 2)                     # [b,s,h,d]


def _flash_blocks(s: int) -> int:
    """Largest flash block that tiles the local sequence."""
    for c in (128, 64, 32, 16, 8):
        if s % c == 0 and s >= c:
            return c
    return s


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_hop_core(q, k, v, scale, causal, blk, interpret):
    """(out, lse) of one flash hop ([b, h, s, d] operands), with a VJP
    that accepts cotangents for BOTH outputs — the ring fold weights
    each hop by its lse, so dlse is structurally nonzero (the raw
    pallas_call has no autodiff rule, and the stock flash custom VJP
    discards lse)."""
    return _flash_hop_fwd(q, k, v, scale, causal, blk, interpret)[0]


def _flash_hop_fwd(q, k, v, scale, causal, blk, interpret):
    from paddle_tpu.ops.pallas.flash_attention import _fwd_pallas
    o, lse = _fwd_pallas(q, k, v, scale=scale, causal=causal,
                         block_q=blk, block_k=blk, interpret=interpret)
    o = o.astype(jnp.float32)
    return (o, lse), (q, k, v, o, lse)


def _flash_hop_bwd(scale, causal, blk, interpret, res, ct):
    # softmax-through-lse backward: with p = exp(s - lse) the combined
    # cotangent is ds = p ⊙ (dp − delta + dlse·1ᵀ) — the dlse term is
    # exactly the softmax jacobian of the log-normalizer.  Recomputes
    # the [s, s] score block per hop in fp32 (same memory class as the
    # dense ring backward).
    q, k, v, o, lse = res
    do, dlse = ct
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    p = jnp.exp(s - lse[..., None])
    if causal:
        n = q.shape[2]
        pos = jnp.arange(n)
        mask = pos[:, None] >= pos[None, :]
        p = jnp.where(mask[None, None], p, 0.0)
    delta = jnp.sum(dof * o, axis=-1)                  # [b, h, s]
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    ds = p * (dp - delta[..., None] + dlse[..., None])
    dq = scale * jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    dk = scale * jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_hop_core.defvjp(_flash_hop_fwd, _flash_hop_bwd)


def _lse_fold(o1, l1, o2, l2):
    """Merge two normalized attention partials by log-sum-exp:
    ``o = o1·exp(l1-l) + o2·exp(l2-l)`` with ``l = logaddexp(l1, l2)``.
    ``-inf`` lse (a skipped/fully-masked partial) contributes exactly
    zero weight — guarded so ``-inf - -inf`` never makes a NaN."""
    l_new = jnp.logaddexp(l1, l2)
    safe = jnp.where(jnp.isfinite(l_new), l_new, 0.0)
    w1 = jnp.where(jnp.isfinite(l1), jnp.exp(l1 - safe), 0.0)
    w2 = jnp.where(jnp.isfinite(l2), jnp.exp(l2 - safe), 0.0)
    return o1 * w1[..., None] + o2 * w2[..., None], l_new


def _ring_flash(q, k, v, *, axis_name, causal, scale):
    """Per-hop flash fold: each incoming K/V shard runs through the
    flash-attention Pallas kernel (out + lse) and hops merge by
    log-sum-exp — the [s_local, s_local] score matrix never exists, so
    ring memory is O(seq/sp) end to end.  Causal hops route by ring
    position: the diagonal hop (src == rank) is the kernel's causal
    path (local positions align), earlier shards (src < rank) are fully
    visible, later shards are skipped without touching the MXU."""
    sp = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if k.shape[2] != h:
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    interpret = jax.default_backend() != "tpu"
    blk = _flash_blocks(s)
    qT = jnp.swapaxes(q, 1, 2)                         # [b, h, s, d]

    def flash_hop(k_cur, v_cur, hop_causal):
        return _flash_hop_core(qT, jnp.swapaxes(k_cur, 1, 2),
                               jnp.swapaxes(v_cur, 1, 2), scale,
                               hop_causal, blk, interpret)

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    from paddle_tpu.robustness import fault_point
    fault_point("sp.ring_peer", axis=axis_name, sp=int(sp), impl="flash")
    overlap = _overlap_state()

    def step(carry, i):
        o, l, k_cur, v_cur = carry
        src = (idx - i) % sp
        if overlap:
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
        if causal:
            def diag(k_, v_):
                return flash_hop(k_, v_, True)

            def full(k_, v_):
                return flash_hop(k_, v_, False)

            def skip(k_, v_):
                return jnp.zeros_like(o), jnp.full_like(l, -jnp.inf)

            case = jnp.where(src == idx, 0, jnp.where(src < idx, 1, 2))
            o_h, l_h = lax.switch(case, (diag, full, skip), k_cur, v_cur)
        else:
            o_h, l_h = flash_hop(k_cur, v_cur, False)
        o_new, l_new = _lse_fold(o, l, o_h, l_h)
        if not overlap:
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, l_new, k_nxt, v_nxt), None

    from paddle_tpu.distributed.communication import pvary_like
    o0 = pvary_like(jnp.zeros((b, h, s, d), jnp.float32), q,
                    fallback_axes=(axis_name,))
    l0 = pvary_like(jnp.full((b, h, s), -jnp.inf, jnp.float32), q,
                    fallback_axes=(axis_name,))
    (o, _, _, _), _ = lax.scan(step, (o0, l0, k, v), jnp.arange(sp))
    return jnp.swapaxes(o.astype(q.dtype), 1, 2)       # [b,s,h,d]


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                      scale: Optional[float] = None,
                      attn_fn=None):
    """Ulysses sequence parallelism INSIDE shard_map.

    q, k, v: local shards [batch, seq_local, heads, head_dim]; heads must be
    divisible by the sp axis size.  all_to_all to [batch, seq_global,
    heads_local, head_dim], run full attention per local head, swap back.
    `attn_fn(q, k, v, causal, scale)` defaults to the XLA sdpa; pass the
    flash kernel for long sequences.
    """
    sp = _axis_size(axis_name)
    b, s, h, d = q.shape
    if h % sp:
        raise ValueError(f"heads {h} not divisible by sp={sp}")

    def swap_in(x):   # [b, s_l, h, d] -> [b, s_g, h_l, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def swap_out(x):  # [b, s_g, h_l, d] -> [b, s_l, h, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = swap_in(q), swap_in(k), swap_in(v)
    if attn_fn is None:
        from paddle_tpu.nn.functional.attention import _sdpa_reference
        out = _sdpa_reference(qg, kg, vg, is_causal=causal, scale=scale)
    else:
        out = attn_fn(qg, kg, vg, causal=causal, scale=scale)
    return swap_out(out)


def _wrap_shard_map(fn, mesh, axis_name, seq_axis=1):
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.communication import shard_map
    spec = [None, None, None, None]
    spec[seq_axis] = axis_name
    spec = P(*spec)
    # every operand is sp-sharded (nothing replicated → no auto-psum to
    # lose); 0.4.x's rep checker trips on the pvary-less scan carry, so
    # relax it there only
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, legacy_check_rep=False)


def make_ring_attention(mesh, axis_name: str = "sp", causal: bool = False,
                        scale=None, impl: Optional[str] = None):
    """Top-level entry: global [b, seq, h, d] arrays sharded on `axis_name`
    → shard_map'd ring attention."""
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, scale=scale, impl=impl)
    return _wrap_shard_map(lambda q, k, v: fn(q, k, v), mesh, axis_name)


def make_striped_ring_attention(mesh, axis_name: str = "sp",
                                causal: bool = True, scale=None):
    """Top-level entry for the striped layout.  Operands must already be
    striped (global token j·sp + rank at local slot j — e.g.
    ``x[:, rank::sp]`` gathered per shard); outputs come back in the
    same striped layout."""
    fn = functools.partial(striped_ring_attention, axis_name=axis_name,
                           causal=causal, scale=scale)
    return _wrap_shard_map(lambda q, k, v: fn(q, k, v), mesh, axis_name)


def make_ulysses_attention(mesh, axis_name: str = "sp",
                           causal: bool = False, scale=None, attn_fn=None):
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal, scale=scale, attn_fn=attn_fn)
    return _wrap_shard_map(lambda q, k, v: fn(q, k, v), mesh, axis_name)
