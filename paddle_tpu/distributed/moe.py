"""Mixture-of-Experts with expert parallelism.

Reference parity: ``MoELayer`` (incubate/distributed/models/moe/moe_layer.py
:261) with gates (moe/gate/: NaiveGate, GShardGate, SwitchGate), dispatch via
``MoEScatter``/``MoEGather`` PyLayers (:97,:147) around the
``global_scatter``/``global_gather`` all-to-all collective ops
(operators/collective/global_scatter_op.cu.cc), capacity + load-balance loss
(moe/utils.py).

TPU-native design (the GShard recipe): token routing is expressed as dense
einsums with a one-hot dispatch mask — no gather/scatter kernels, fully
differentiable, MXU-friendly — and expert weights are stacked ``[E, ...]``
arrays whose PartitionSpec puts E on the ``ep`` mesh axis.  Under jit,
GSPMD turns the dispatch einsum into exactly the all_to_all the reference
implements as ``global_scatter`` (sharding constraints below pin that
layout).  Capacity math and the load-balance auxiliary loss follow GShard
§3.2, matching the reference's utils.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed.mpu import constrain

__all__ = ["top_k_gating", "NaiveGate", "SwitchGate", "GShardGate",
           "MoELayer", "ExpertFFN", "moe_shard_a2a", "moe_forward_a2a",
           "top_k_gating_indices", "moe_forward_index",
           "moe_shard_index_a2a", "moe_forward_ragged"]


def top_k_gating(gate_logits, k: int, capacity: int,
                 jitter_key=None, jitter_eps: float = 0.0):
    """GShard top-k gating with capacity.

    Args:
      gate_logits: [tokens, E].
    Returns:
      combine: [tokens, E, C] combine weights (0 for dropped tokens),
      dispatch: same-shape bool mask,
      aux_loss: load-balance loss (mean_prob * mean_assignment * E),
      router z-loss is folded in by callers that want it.
    """
    if jitter_key is not None and jitter_eps > 0:
        noise = jax.random.uniform(jitter_key, gate_logits.shape,
                                   minval=1 - jitter_eps,
                                   maxval=1 + jitter_eps)
        gate_logits = gate_logits * noise
    E = gate_logits.shape[1]
    topi, slot, w, keep, aux_loss = top_k_gating_indices(
        gate_logits, k=k, capacity=capacity)
    # densify the index form into GShard's [T, E, C] one-hot tensors
    onehot = jax.nn.one_hot(topi, E, dtype=w.dtype)       # [T, k, E]
    cap_onehot = jax.nn.one_hot(jnp.clip(slot, 0, capacity - 1), capacity,
                                dtype=w.dtype)            # [T, k, C]
    combine = jnp.einsum("tke,tkc,tk->tec", onehot, cap_onehot, w)
    dispatch = jnp.einsum("tke,tkc->tec",
                          onehot * keep[..., None].astype(w.dtype),
                          cap_onehot) > 0
    return combine, dispatch, aux_loss


def _gshard_aux(probs, topi, E: int, k: int):
    """GShard load-balance loss: E * mean_e(frac_tokens_e * mean_prob_e)
    (single home — shared by the capacity bookkeeping and the ragged
    dropless path so the formula cannot drift)."""
    onehot = jax.nn.one_hot(topi, E, dtype=probs.dtype)   # [T, k, E]
    me = probs.mean(axis=0)
    ce = (onehot.sum(1) > 0).astype(probs.dtype).mean(axis=0) / k
    return (me * ce).sum() * E


def top_k_gating_indices(gate_logits, k: int, capacity: int):
    """Index-form gating — the single implementation of the GShard
    bookkeeping (``top_k_gating`` densifies this form).  Returns
    per-(token, choice) indices, the input to the gather/scatter dispatch
    whose cost is O(T·k·d) instead of the dense contraction's
    O(T·E·C·d) (at bench shapes the dense dispatch einsum costs 3x the
    expert math itself).

    Fully vectorized (no Python loop over k): lax.top_k selects the same
    experts k sequential argmax passes would; queue positions come from
    one cumsum over the k-major flattening (all 1st choices in token
    order, then all 2nd choices, ...).  Standard GShard bookkeeping: an
    over-capacity assignment still occupies its position number, so under
    overflow a later-rank choice may be pushed past capacity where a
    k-pass implementation (recycling dropped slots between passes) would
    have admitted it — slightly more conservative, identical whenever
    capacity is not exceeded (and always under dropless).

    Returns:
      topi:  [T, k] int32 expert ids
      slot:  [T, k] int32 capacity slot within the expert
      w:     [T, k] combine weights, normalized over kept choices
      keep:  [T, k] bool — in-capacity assignments
      aux_loss: scalar GShard load-balance loss
    """
    tokens, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    k = min(k, E)  # degenerate configs (fewer experts than choices)
    topv, topi = jax.lax.top_k(probs, k)                  # [T, k]
    onehot = jax.nn.one_hot(topi, E, dtype=probs.dtype)   # [T, k, E]
    flat = onehot.transpose(1, 0, 2).reshape(k * tokens, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat
    pos = pos_flat.reshape(k, tokens, E).transpose(1, 0, 2)
    in_cap = (pos < capacity) & (onehot > 0)
    slot = (pos * onehot).sum(-1).astype(jnp.int32)       # [T, k]
    keep = in_cap.any(-1)                                 # [T, k]
    w = topv * keep.astype(probs.dtype)
    denom = w.sum(axis=1, keepdims=True)
    w = jnp.where(denom > 0, w / jnp.maximum(denom, 1e-9), w)
    return topi, slot, w, keep, _gshard_aux(probs, topi, E, k)


def moe_forward_index(x2d, logits, experts_fn, *, E: int, top_k: int,
                      capacity: int):
    """Gather/scatter expert dispatch (single-program; MaxText-style).

    Builds [E, C] token-index buffers with one masked scatter (dropped
    assignments target an out-of-bounds row, mode='drop'), gathers
    expert inputs directly from the token axis, and combines with a
    [T, k, d] gather — no [T, E, C] tensor exists anywhere.  Gradients
    flow through the gathers (scatter-add transposes).
    """
    T, d = x2d.shape
    topi, slot, w, keep, aux = top_k_gating_indices(logits, k=top_k,
                                                    capacity=capacity)
    safe_e = jnp.where(keep, topi, E)      # OOB row → dropped by scatter
    tok_ids = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[:, None],
                               topi.shape)
    tok_for = jnp.zeros((E, capacity), jnp.int32).at[safe_e, slot].set(
        tok_ids, mode="drop")
    # pad slots point at token 0 — harmless garbage: the combine gather
    # reads only (topi, slot) pairs and dropped pairs carry w == 0, so
    # no mask multiply (saves one [E, C, d] HBM pass)
    expert_in = x2d[tok_for]                              # [E, C, d]
    if _grouped_moe_enabled():
        # per-expert kept-assignment counts (the front-packed slot
        # prefix) let the grouped kernel skip empty capacity blocks
        counts = jnp.zeros((E,), jnp.int32).at[safe_e.reshape(-1)].add(
            1, mode="drop")
        expert_out = experts_fn(expert_in, counts)        # [E, C, d]
    else:
        expert_out = experts_fn(expert_in)                # [E, C, d]
    picked = expert_out[topi, jnp.clip(slot, 0, capacity - 1)]  # [T, k, d]
    out = jnp.einsum("tkd,tk->td", picked, w.astype(x2d.dtype))
    dropped = 1.0 - keep.astype(jnp.float32).mean()
    return out, aux, dropped


def moe_forward_ragged(x2d, logits, w1, b1, w2, b2, *, E: int, top_k: int,
                       activation=None):
    """Dropless sort + ``lax.ragged_dot`` expert dispatch (single-program).

    The zero-padding path: the (T, k) assignments are flattened, argsorted
    by expert id, and the expert GEMMs run as ONE grouped matmul over
    exactly T*k rows (``lax.ragged_dot`` with per-expert group sizes) — no
    [E, C] capacity buffers, no padding FLOPs, nothing dropped.  This is
    the TPU-native analog of the reference's pure computation under
    ``global_scatter``/``global_gather`` (global_scatter_op.cu.cc sends
    exactly count rows; here the "send" is an in-chip gather).

    Returns (out [T, d], aux_loss, dropped_frac=0.0).
    """
    act = activation or jax.nn.gelu
    T, d = x2d.shape
    k = min(top_k, E)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)                  # [T, k]
    w = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    flat_e = topi.reshape(-1)                             # [T*k] token-major
    order = jnp.argsort(flat_e)                           # stable
    tok = (order // k).astype(jnp.int32)                  # source token/row
    xs = x2d[tok]                                         # [T*k, d]
    group_sizes = jnp.bincount(flat_e, length=E).astype(jnp.int32)
    es = flat_e[order]                                    # sorted expert ids
    h = jax.lax.ragged_dot(xs, w1, group_sizes) + b1[es]
    ys = jax.lax.ragged_dot(act(h), w2, group_sizes) + b2[es]
    wf = w.reshape(-1)[order].astype(x2d.dtype)
    out = jnp.zeros((T, d), x2d.dtype).at[tok].add(ys * wf[:, None])
    return out, _gshard_aux(probs, topi, E, k), jnp.zeros((), jnp.float32)


class NaiveGate(Layer):
    """Linear router, top-k, no noise (reference moe/gate/naive_gate.py)."""

    def __init__(self, d_model: int, num_experts: int, top_k: int = 2):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.gate = self.create_parameter([d_model, num_experts])

    def logits(self, x2d):
        from paddle_tpu.core.dispatch import unwrap
        return x2d @ unwrap(self.gate)

    def extra(self) -> dict:
        return {}


class SwitchGate(NaiveGate):
    """top-1 (Switch Transformer; reference moe/gate/switch_gate.py)."""

    def __init__(self, d_model, num_experts, jitter_eps: float = 0.01):
        super().__init__(d_model, num_experts, top_k=1)
        self.jitter_eps = jitter_eps


class GShardGate(NaiveGate):
    """top-2 with capacity (reference moe/gate/gshard_gate.py)."""

    def __init__(self, d_model, num_experts, capacity_factor: float = 1.25):
        super().__init__(d_model, num_experts, top_k=2)
        self.capacity_factor = capacity_factor


class ExpertFFN(Layer):
    """Stacked expert FFNs: [E, d, h] / [E, h, d] weights, E on the ep
    axis.  One einsum per projection keeps every expert's GEMM on the MXU
    and gives GSPMD the expert axis to all_to_all over."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 activation: Callable = None, ep_axis: str = "ep"):
        super().__init__()
        from jax.sharding import PartitionSpec as P
        self.num_experts = num_experts
        self.activation = activation or F.gelu
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b1 = self.create_parameter([num_experts, d_hidden],
                                        is_bias=True)
        self.b2 = self.create_parameter([num_experts, d_model],
                                        is_bias=True)
        self.w1.partition_spec = P(ep_axis, None, None)
        self.w2.partition_spec = P(ep_axis, None, None)
        self.b1.partition_spec = P(ep_axis, None)
        self.b2.partition_spec = P(ep_axis, None)

    def forward(self, expert_inputs, counts=None):
        """expert_inputs: [E, C, d] -> [E, C, d].  ``counts`` (optional
        [E] int32 valid-slot prefix per expert) lets the grouped Pallas
        kernel skip empty capacity blocks when PADDLE_TPU_GROUPED_MOE
        is on; it is ignored by the dense einsum path."""
        from paddle_tpu.core.dispatch import unwrap
        return _expert_ffn(unwrap(expert_inputs), unwrap(self.w1),
                           unwrap(self.b1), unwrap(self.w2), unwrap(self.b2),
                           lambda v: unwrap(self.activation(v)),
                           counts=counts)


def _grouped_moe_enabled() -> bool:
    """Trace-time check of the PADDLE_TPU_GROUPED_MOE knob (lazy import
    keeps distributed/ free of an eager ops.pallas dependency)."""
    from paddle_tpu.ops.pallas.grouped_matmul import grouped_moe_enabled
    return grouped_moe_enabled()


def _router_metrics():
    """Routing-observability instruments (ISSUE 18), lazily created on
    the process-wide registry so an import of distributed/ never pulls
    exporters in."""
    from paddle_tpu.observability import default_registry
    reg = default_registry()
    return {
        "dropped": reg.counter(
            "paddle_tpu_moe_dropped_tokens_total",
            "token-choice assignments dropped by the capacity bound"),
        "overflow": reg.counter(
            "paddle_tpu_moe_capacity_overflow_total",
            "routed forwards in which at least one assignment was "
            "dropped (capacity pressure events)"),
        "aux": reg.gauge(
            "paddle_tpu_moe_aux_loss",
            "GShard load-balance auxiliary loss of the last routed "
            "forward"),
        "load": reg.gauge(
            "paddle_tpu_moe_expert_load",
            "kept token-choice assignments per expert in the last "
            "routed forward", labelnames=("expert",)),
        "imbalance": reg.gauge(
            "paddle_tpu_moe_expert_imbalance",
            "max/mean per-expert load of the last routed forward "
            "(1.0 = perfectly balanced)"),
    }


def _record_router_metrics(aux, dropped_frac, total_assignments,
                           load=None):
    """Update the dropped-token / capacity-overflow counters, the
    aux-loss gauge and the per-expert load/imbalance gauges from one
    routed forward.  Concrete (eager) values only: under jit the stats
    are tracers and the traced program must stay identical to the
    uninstrumented one, so this silently skips (the trace-time
    ``paddle_tpu_grouped_moe_path_total`` counter still attributes the
    implementation path)."""
    try:
        import jax.core as _core
        vals = [aux, dropped_frac]
        if load is not None:
            vals.append(load)
        if any(isinstance(v, _core.Tracer) for v in vals):
            return
        m = _router_metrics()
        m["aux"].set(float(aux))
        df = float(dropped_frac)
        if df > 0:
            m["dropped"].inc(df * total_assignments)
            m["overflow"].inc()
        if load is not None:
            import numpy as _np
            arr = _np.asarray(load, dtype=float)
            for e, val in enumerate(arr):
                m["load"].labels(expert=e).set(float(val))
            mean = arr.mean()
            m["imbalance"].set(
                float(arr.max() / mean) if mean > 0 else 1.0)
    except Exception:  # pragma: no cover - telemetry must never break fwd
        pass


def _expert_ffn(x, w1, b1, w2, b2, act, counts=None):
    """Stacked-expert FFN compute shared by ExpertFFN.forward and the
    all_to_all dispatch path: [E, C, d] -> [E, C, d] (more generally
    [G, C, d] with G a multiple of the expert count — the a2a paths pass
    per-source-shard groups).  With PADDLE_TPU_GROUPED_MOE=1 this routes
    to the grouped Pallas kernel (ops/pallas/grouped_matmul.py), which
    skips capacity blocks past ``counts`` and zeroes their rows — a
    no-op for MoE outputs since those slots carry zero combine weight.
    Knob off, the dense einsum pair below traces byte-identically to
    what it always produced (regression-tested)."""
    from paddle_tpu.ops.pallas import grouped_matmul as _gm
    if _gm.grouped_moe_enabled() and _gm.grouped_ffn_eligible(
            x.shape[0], x.shape[1], x.shape[2], w1.shape[2], w1.shape[0]):
        _gm.record_path("grouped")
        return _gm.grouped_expert_ffn(x, w1, b1, w2, b2, counts=counts,
                                      act=act)
    h = jnp.einsum("ecd,edh->ech", x, w1) + b1[:, None, :]
    return jnp.einsum("ech,ehd->ecd", act(h), w2) + b2[:, None, :]


def _grouped_a2a_ffn(recv, send_counts, w1, b1, w2, b2, act, capacity,
                     ep_axis):
    """Grouped-kernel expert compute for the all_to_all bodies.

    ``recv [E_loc, n*C, d]`` holds n source-shard chunks per local
    expert; each chunk is an independently front-packed capacity buffer,
    so the per-chunk occupancy counts are exchanged alongside the tokens
    (the same all_to_all permutation, tiled over the expert axis) and
    the FFN runs over ``[E_loc*n, C, d]`` groups with ``g // n`` mapping
    groups to local expert weights — empty tail blocks of every chunk
    are skipped, not just the global tail."""
    e_loc, nc, d = recv.shape
    n = nc // capacity
    # [E] -> [n*E_loc] ordered (source shard, local expert); regroup to
    # (local expert, source shard) to match recv's chunk layout
    counts_recv = jax.lax.all_to_all(send_counts, ep_axis, split_axis=0,
                                     concat_axis=0, tiled=True)
    counts_g = counts_recv.reshape(n, e_loc).T.reshape(-1)
    grp = recv.reshape(e_loc * n, capacity, d)
    out = _expert_ffn(grp, w1, b1, w2, b2, act, counts=counts_g)
    return out.reshape(e_loc, nc, d)


def moe_shard_a2a(x2d, gate_w, w1, b1, w2, b2, *, top_k: int,
                  capacity: int, activation=None, ep_axis: str = "ep"):
    """Explicit all_to_all expert dispatch — runs INSIDE shard_map.

    Semantic parity with the reference's global_scatter/global_gather
    collectives (operators/collective/global_scatter_op.cu.cc): each ep
    shard routes its local tokens into per-expert capacity buffers, an
    all_to_all exchanges the expert axis for a source-shard axis, local
    experts run, and the inverse all_to_all returns results.

    Args:
      x2d: [T_loc, d] local tokens.
      gate_w: [d, E] replicated router weight (E = global expert count).
      w1/b1/w2/b2: LOCAL expert slices [E_loc, ...] (ep-sharded).
      capacity: per (source shard, expert) buffer slots.
    Returns:
      out: [T_loc, d]; aux: global mean load-balance loss;
      dropped_frac: fraction of (token, choice) assignments dropped by
      the capacity bound, pmean'd over ep (0.0 when capacity covers every
      local token, i.e. dropless).
    """
    act = activation or jax.nn.gelu
    logits = x2d @ gate_w                                     # [T_loc, E]
    combine, dispatch, aux = top_k_gating(logits, k=top_k, capacity=capacity)
    # honesty accounting: fraction of (token, choice) assignments dropped
    # by the capacity bound, pmean'd over ep (0.0 when dropless=True —
    # capacity == tokens-per-shard can never overflow since one token
    # dispatches to k DISTINCT experts)
    total = x2d.shape[0] * top_k
    dropped_frac = jax.lax.pmean(
        1.0 - dispatch.sum().astype(jnp.float32) / total, ep_axis)

    buf = jnp.einsum("tec,td->ecd", dispatch.astype(x2d.dtype), x2d)
    # [E, C, d] -> split experts to their shards, gather source chunks:
    # [E_loc, n_shards*C, d]
    recv = jax.lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=1,
                              tiled=True)
    if _grouped_moe_enabled():
        send_counts = dispatch.astype(jnp.int32).sum(axis=(0, 2))  # [E]
        out_loc = _grouped_a2a_ffn(recv, send_counts, w1, b1, w2, b2,
                                   act, capacity, ep_axis)
    else:
        out_loc = _expert_ffn(recv, w1, b1, w2, b2, act)
    # inverse exchange: [E_loc, n*C, d] -> [E, C, d]
    back = jax.lax.all_to_all(out_loc, ep_axis, split_axis=1, concat_axis=0,
                              tiled=True)
    out = jnp.einsum("tec,ecd->td", combine.astype(x2d.dtype), back)
    return out, jax.lax.pmean(aux, ep_axis), dropped_frac


def moe_shard_index_a2a(x2d, gate_w, w1, b1, w2, b2, *, top_k: int,
                        capacity: int, activation=None, ep_axis: str = "ep"):
    """Index-dispatch all_to_all expert exchange — runs INSIDE shard_map.

    The cross-rank ``global_scatter``/``global_gather`` analog (reference
    operators/collective/global_scatter_op.cu.cc) built the TPU way: the
    [E, C, d] send buffer is assembled with an O(T·k·d) scatter/gather
    (cumsum slots front-pack each expert bucket, exactly the send layout
    global_scatter produces) instead of the O(T·E·C·d) one-hot contraction
    of :func:`moe_shard_a2a`; the exchange itself stays the deterministic
    tiled all_to_all so shapes are static for XLA.  A true
    ``lax.ragged_all_to_all`` (variable counts, zero padding on the wire)
    is the natural next step but has no XLA:CPU lowering, which would
    leave the path untestable off-chip — capacity buckets bound the wire
    overhead at (capacity_factor - 1) instead.

    Same contract as :func:`moe_shard_a2a`: local x2d [T_loc, d],
    replicated gate_w [d, E], LOCAL expert slices [E_loc, ...]; returns
    (out [T_loc, d], aux, dropped_frac).
    """
    act = activation or jax.nn.gelu
    logits = x2d @ gate_w                                     # [T_loc, E]
    T = x2d.shape[0]
    E = gate_w.shape[-1]
    topi, slot, w, keep, aux = top_k_gating_indices(logits, k=top_k,
                                                    capacity=capacity)
    dropped_frac = jax.lax.pmean(
        1.0 - keep.astype(jnp.float32).mean(), ep_axis)
    safe_e = jnp.where(keep, topi, E)        # OOB row -> dropped by scatter
    tok_ids = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[:, None],
                               topi.shape)
    tok_for = jnp.zeros((E, capacity), jnp.int32).at[safe_e, slot].set(
        tok_ids, mode="drop")
    # pad slots point at token 0 — harmless garbage: the combine gather
    # below reads only (topi, slot) pairs, and dropped pairs carry w == 0,
    # so no `filled` mask multiply (saves one [E, C, d] HBM pass)
    buf = x2d[tok_for]                                        # [E, C, d]
    recv = jax.lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=1,
                              tiled=True)                     # [E_loc, n*C, d]
    if _grouped_moe_enabled():
        send_counts = jnp.zeros((E,), jnp.int32).at[safe_e.reshape(-1)].add(
            1, mode="drop")
        out_loc = _grouped_a2a_ffn(recv, send_counts, w1, b1, w2, b2,
                                   act, capacity, ep_axis)
    else:
        out_loc = _expert_ffn(recv, w1, b1, w2, b2, act)
    back = jax.lax.all_to_all(out_loc, ep_axis, split_axis=1, concat_axis=0,
                              tiled=True)                     # [E, C, d]
    picked = back[topi, jnp.clip(slot, 0, capacity - 1)]      # [T, k, d]
    out = jnp.einsum("tkd,tk->td", picked, w.astype(x2d.dtype))
    return out, jax.lax.pmean(aux, ep_axis), dropped_frac


def moe_forward_a2a(x, gate_w, w1, b1, w2, b2, *, mesh, top_k: int = 2,
                    capacity_factor: float = 1.25, dropless: bool = False,
                    activation=None, ep_axis: str = "ep",
                    with_stats: bool = False, dispatch: str = "einsum"):
    """Jit-callable wrapper: shard_maps :func:`moe_shard_a2a` over the ep
    axis of ``mesh``.

    x: [B, S, d] — flattened to [B*S, d] and sharded on the token axis
    (constraint: B*S divisible by the ep mesh size); expert weights
    [E, ...] sharded on ep (E divisible by ep size); gate replicated.
    ``with_stats=True`` additionally returns the dropped-assignment
    fraction (always 0.0 under dropless) so capacity pressure is never
    silent.  ``dispatch`` picks the shard body: "einsum" (one-hot
    contraction, :func:`moe_shard_a2a`) or "index" (O(T·k·d)
    scatter/gather build, :func:`moe_shard_index_a2a`)."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.communication import shard_map

    if dispatch not in ("einsum", "index"):
        raise ValueError(f"unknown a2a dispatch {dispatch!r}")
    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)  # shard the flat token axis, not the batch axis
    n = mesh.shape[ep_axis]
    E = gate_w.shape[-1]
    T = x2d.shape[0]
    if T % n:
        raise ValueError(f"token count {T} not divisible by ep={n}")
    if E % n:
        raise ValueError(f"expert count {E} not divisible by ep={n}")
    t_loc = T // n
    if dropless:
        capacity = t_loc  # an expert can receive at most every local token
    else:
        capacity = max(1, int(capacity_factor * top_k * t_loc / E))

    body = moe_shard_a2a if dispatch == "einsum" else moe_shard_index_a2a

    def fn(xs, gw, a1, c1, a2, c2):
        return body(xs, gw, a1, c1, a2, c2, top_k=top_k,
                    capacity=capacity, activation=activation,
                    ep_axis=ep_axis)

    extra = {}
    if _grouped_moe_enabled():
        # jax 0.4.x's static replication checker has no rule for
        # pallas_call; relax it only when the grouped kernel is routed
        # so the knob-off trace (and its jaxpr) is untouched
        extra["legacy_check_rep"] = False
    mapped = shard_map(
        fn, mesh=mesh,
        in_specs=(P(ep_axis), P(), P(ep_axis), P(ep_axis), P(ep_axis),
                  P(ep_axis)),
        out_specs=(P(ep_axis), P(), P()), **extra)
    out, aux, dropped = mapped(x2d, gate_w, w1, b1, w2, b2)
    # couple the scalar outputs into `out`'s dataflow with a zero-weight
    # term: a caller differentiating only `out` then sends DENSE zero
    # cotangents into aux/dropped instead of symbolic Zeros, which jax
    # 0.4.x's shard_map transpose mishandles ('Zero' has no .reshape)
    out = out + (0.0 * (aux + dropped)).astype(out.dtype)
    if with_stats:
        return out.reshape(shape), aux, dropped
    return out.reshape(shape), aux


class MoELayer(Layer):
    """Mixture of experts (reference moe_layer.py:261).

    forward(x: [B, S, d]) -> [B, S, d]; the load-balance aux loss of the
    last call is at ``self.aux_loss`` (callers add it to the objective —
    same contract as the reference's gate.get_loss()).
    """

    def __init__(self, d_model: int, num_experts: int,
                 d_hidden: Optional[int] = None, gate: str = "gshard",
                 top_k: Optional[int] = None,
                 capacity_factor: float = 1.25,
                 experts: Optional[Layer] = None, ep_axis: str = "ep",
                 dispatch_mode: str = "einsum", dropless: bool = False,
                 mesh=None):
        super().__init__()
        if dispatch_mode not in ("einsum", "all_to_all", "index", "ragged",
                                 "all_to_all_index"):
            raise ValueError(f"unknown dispatch_mode {dispatch_mode}")
        if dispatch_mode in ("all_to_all", "all_to_all_index") \
                and mesh is None:
            raise ValueError(f"dispatch_mode={dispatch_mode!r} needs mesh=")
        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        self.dispatch_mode = dispatch_mode
        self.dropless = dropless
        self.mesh = mesh
        if gate == "gshard":
            self.gate = GShardGate(d_model, num_experts, capacity_factor)
        elif gate == "switch":
            self.gate = SwitchGate(d_model, num_experts)
        elif gate == "naive":
            self.gate = NaiveGate(d_model, num_experts,
                                  top_k=top_k or 2)
        else:
            raise ValueError(f"unknown gate {gate}")
        if top_k is not None:
            self.gate.top_k = top_k
        self.experts = experts or ExpertFFN(
            num_experts, d_model, d_hidden or 4 * d_model, ep_axis=ep_axis)
        self.aux_loss = None
        self.router_stats = None  # {"dropped_frac": ...} after forward

    def forward(self, x):
        """NOTE: the gating/dispatch math runs on raw traced values — the
        supported training path is through jit/functional_call (TrainStep),
        where gradients flow through the whole routed computation.  The
        eager tape does not differentiate through this layer."""
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.core.dispatch import unwrap
        data = unwrap(x)
        B, S, d = data.shape
        T = B * S

        if self.dispatch_mode in ("all_to_all", "all_to_all_index"):
            if not isinstance(self.experts, ExpertFFN):
                raise ValueError("all_to_all dispatch requires the stacked "
                                 "ExpertFFN experts")
            out, aux, dropped = moe_forward_a2a(
                data, unwrap(self.gate.gate),
                unwrap(self.experts.w1), unwrap(self.experts.b1),
                unwrap(self.experts.w2), unwrap(self.experts.b2),
                mesh=self.mesh, top_k=self.gate.top_k,
                capacity_factor=self.capacity_factor,
                dropless=self.dropless, ep_axis=self.ep_axis,
                activation=lambda v: unwrap(self.experts.activation(v)),
                with_stats=True,
                dispatch=("index" if self.dispatch_mode == "all_to_all_index"
                          else "einsum"))
            self.aux_loss = aux
            self.router_stats = {"dropped_frac": dropped}
            _record_router_metrics(aux, dropped, T * self.gate.top_k)
            return self._wrap_out(x, out)

        E = self.num_experts
        x2d = data.reshape(T, d)
        # expected assignments are top_k*T/E under balanced routing, so
        # capacity must scale with k (reference GShardGate caps per expert
        # at ceil(cap_rate * tokens), similarly k-aware in effect);
        # dropless pins capacity at T so no token can ever be dropped —
        # exact but O(T^2·E) dispatch memory, toy/test scale only (the
        # all_to_all path bounds capacity at tokens-per-shard instead)
        if self.dropless:
            capacity = T
        else:
            capacity = max(1, int(self.capacity_factor * self.gate.top_k
                                  * T / E))
        logits = unwrap(self.gate.logits(x2d))
        from paddle_tpu.robustness import fault_fires
        if fault_fires("moe.expert_imbalance", experts=E):
            # hot-expert pathology drill: every token prefers expert 0 —
            # the imbalance gauge and aux loss must surface the skew
            logits = logits + jnp.where(jnp.arange(E) == 0, 10.0,
                                        0.0).astype(logits.dtype)
        if self.dispatch_mode == "ragged":
            # dropless sort + grouped-matmul dispatch: no capacity buffers,
            # FLOPs over exactly T*k rows; the single-program fast path
            if not isinstance(self.experts, ExpertFFN):
                raise ValueError("ragged dispatch requires the stacked "
                                 "ExpertFFN experts")
            out, aux, dropped = moe_forward_ragged(
                x2d, logits, unwrap(self.experts.w1),
                unwrap(self.experts.b1), unwrap(self.experts.w2),
                unwrap(self.experts.b2), E=E, top_k=self.gate.top_k,
                activation=lambda v: unwrap(self.experts.activation(v)))
            self.aux_loss = aux
            self.router_stats = {"dropped_frac": dropped}
            _record_router_metrics(aux, dropped, T * self.gate.top_k)
            return self._wrap_out(x, out.reshape(B, S, d))
        if self.dispatch_mode == "index":
            # gather/scatter dispatch: O(T·k·d) — the single-program fast
            # path (under ep sharding keep "einsum": GSPMD lowers that
            # contraction to the all_to_all; a cross-shard gather would
            # all-gather the tokens instead)
            if not isinstance(self.experts, ExpertFFN):
                raise ValueError("index dispatch requires the stacked "
                                 "ExpertFFN experts")

            def experts_fn(buf, counts=None):
                return _expert_ffn(
                    buf, unwrap(self.experts.w1), unwrap(self.experts.b1),
                    unwrap(self.experts.w2), unwrap(self.experts.b2),
                    lambda v: unwrap(self.experts.activation(v)),
                    counts=counts)

            out, aux, dropped = moe_forward_index(
                x2d, logits, experts_fn, E=E, top_k=self.gate.top_k,
                capacity=capacity)
            self.aux_loss = aux
            self.router_stats = {"dropped_frac": dropped}
            _record_router_metrics(aux, dropped, T * self.gate.top_k)
            return self._wrap_out(x, out.reshape(B, S, d))
        combine, dispatch, aux = top_k_gating(
            logits, k=self.gate.top_k, capacity=capacity)
        self.aux_loss = aux
        self.router_stats = {"dropped_frac": 1.0 - dispatch.sum().astype(
            jnp.float32) / (T * self.gate.top_k)}
        _record_router_metrics(aux, self.router_stats["dropped_frac"],
                               T * self.gate.top_k,
                               load=dispatch.sum(axis=(0, 2)))

        # dispatch: [T,E,C] x [T,d] -> [E,C,d]; GSPMD lowers the contraction
        # to the expert all_to_all when E is sharded on ep
        expert_in = jnp.einsum("tec,td->ecd",
                               dispatch.astype(data.dtype), x2d)
        expert_in = constrain(expert_in, P(self.ep_axis, None, None))
        if _grouped_moe_enabled() and isinstance(self.experts, ExpertFFN):
            # cumsum slot assignment front-packs each expert's bucket, so
            # the filled-slot count per expert is a valid-row prefix
            counts = dispatch.astype(jnp.int32).sum(axis=(0, 2))
            expert_out = unwrap(self.experts(expert_in, counts=counts))
        else:
            expert_out = unwrap(self.experts(expert_in))
        # combine: [T,E,C] x [E,C,d] -> [T,d]
        out = jnp.einsum("tec,ecd->td", combine.astype(data.dtype),
                         expert_out)
        return self._wrap_out(x, out.reshape(B, S, d))

    @staticmethod
    def _wrap_out(x, out):
        if hasattr(x, "_data"):
            from paddle_tpu.core.tensor import Tensor
            t = Tensor(out)
            t.stop_gradient = x.stop_gradient
            return t
        return out
