"""Mixture-of-Experts with expert parallelism.

Reference parity: ``MoELayer`` (incubate/distributed/models/moe/moe_layer.py
:261) with gates (moe/gate/: NaiveGate, GShardGate, SwitchGate), dispatch via
``MoEScatter``/``MoEGather`` PyLayers (:97,:147) around the
``global_scatter``/``global_gather`` all-to-all collective ops
(operators/collective/global_scatter_op.cu.cc), capacity + load-balance loss
(moe/utils.py).

TPU-native design (the GShard recipe): token routing is expressed as dense
einsums with a one-hot dispatch mask — no gather/scatter kernels, fully
differentiable, MXU-friendly — and expert weights are stacked ``[E, ...]``
arrays whose PartitionSpec puts E on the ``ep`` mesh axis.  Under jit,
GSPMD turns the dispatch einsum into exactly the all_to_all the reference
implements as ``global_scatter`` (sharding constraints below pin that
layout).  Capacity math and the load-balance auxiliary loss follow GShard
§3.2, matching the reference's utils.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed.mpu import constrain

__all__ = ["top_k_gating", "NaiveGate", "SwitchGate", "GShardGate",
           "MoELayer", "ExpertFFN"]


def top_k_gating(gate_logits, k: int, capacity: int,
                 jitter_key=None, jitter_eps: float = 0.0):
    """GShard top-k gating with capacity.

    Args:
      gate_logits: [tokens, E].
    Returns:
      combine: [tokens, E, C] combine weights (0 for dropped tokens),
      dispatch: same-shape bool mask,
      aux_loss: load-balance loss (mean_prob * mean_assignment * E),
      router z-loss is folded in by callers that want it.
    """
    tokens, E = gate_logits.shape
    if jitter_key is not None and jitter_eps > 0:
        noise = jax.random.uniform(jitter_key, gate_logits.shape,
                                   minval=1 - jitter_eps,
                                   maxval=1 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)          # [T, E]

    combine = jnp.zeros((tokens, E, capacity), probs.dtype)
    dispatch = jnp.zeros((tokens, E, capacity), bool)
    # running per-expert fill count, updated between the k passes
    fill = jnp.zeros((E,), jnp.int32)
    masked_probs = probs
    aux_mask = jnp.zeros((tokens, E), probs.dtype)

    for _ in range(k):
        choice = jnp.argmax(masked_probs, axis=-1)        # [T]
        onehot = jax.nn.one_hot(choice, E, dtype=probs.dtype)
        aux_mask = aux_mask + onehot
        # position of each token within its chosen expert's queue
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
        pos = pos + fill[None, :] * onehot
        in_cap = (pos < capacity) & (onehot > 0)
        gate_val = (probs * onehot).sum(-1)               # [T]
        pos_idx = pos.sum(-1).astype(jnp.int32)           # [T]
        cap_onehot = jax.nn.one_hot(pos_idx, capacity,
                                    dtype=probs.dtype)    # [T, C]
        sel = in_cap.any(-1)
        combine = combine + (gate_val[:, None, None]
                             * onehot[:, :, None]
                             * cap_onehot[:, None, :]
                             * sel[:, None, None])
        dispatch = dispatch | ((onehot[:, :, None] * cap_onehot[:, None, :])
                               > 0) & sel[:, None, None]
        fill = fill + (onehot * in_cap).sum(0).astype(jnp.int32)
        masked_probs = masked_probs * (1.0 - onehot)      # exclude chosen

    # normalise combine weights over the k experts per token
    denom = combine.sum(axis=(1, 2), keepdims=True)
    combine = jnp.where(denom > 0, combine / jnp.maximum(denom, 1e-9),
                        combine)

    # GShard load-balance loss: E * mean_e(frac_tokens_e * mean_prob_e)
    me = probs.mean(axis=0)                               # [E]
    ce = (aux_mask > 0).astype(probs.dtype).mean(axis=0) / k
    aux_loss = (me * ce).sum() * E
    return combine, dispatch, aux_loss


class NaiveGate(Layer):
    """Linear router, top-k, no noise (reference moe/gate/naive_gate.py)."""

    def __init__(self, d_model: int, num_experts: int, top_k: int = 2):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.gate = self.create_parameter([d_model, num_experts])

    def logits(self, x2d):
        from paddle_tpu.core.dispatch import unwrap
        return x2d @ unwrap(self.gate)

    def extra(self) -> dict:
        return {}


class SwitchGate(NaiveGate):
    """top-1 (Switch Transformer; reference moe/gate/switch_gate.py)."""

    def __init__(self, d_model, num_experts, jitter_eps: float = 0.01):
        super().__init__(d_model, num_experts, top_k=1)
        self.jitter_eps = jitter_eps


class GShardGate(NaiveGate):
    """top-2 with capacity (reference moe/gate/gshard_gate.py)."""

    def __init__(self, d_model, num_experts, capacity_factor: float = 1.25):
        super().__init__(d_model, num_experts, top_k=2)
        self.capacity_factor = capacity_factor


class ExpertFFN(Layer):
    """Stacked expert FFNs: [E, d, h] / [E, h, d] weights, E on the ep
    axis.  One einsum per projection keeps every expert's GEMM on the MXU
    and gives GSPMD the expert axis to all_to_all over."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 activation: Callable = None, ep_axis: str = "ep"):
        super().__init__()
        from jax.sharding import PartitionSpec as P
        self.num_experts = num_experts
        self.activation = activation or F.gelu
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b1 = self.create_parameter([num_experts, d_hidden],
                                        is_bias=True)
        self.b2 = self.create_parameter([num_experts, d_model],
                                        is_bias=True)
        self.w1.partition_spec = P(ep_axis, None, None)
        self.w2.partition_spec = P(ep_axis, None, None)
        self.b1.partition_spec = P(ep_axis, None)
        self.b2.partition_spec = P(ep_axis, None)

    def forward(self, expert_inputs):
        """expert_inputs: [E, C, d] -> [E, C, d]."""
        from paddle_tpu.core.dispatch import unwrap
        w1, w2 = unwrap(self.w1), unwrap(self.w2)
        b1, b2 = unwrap(self.b1), unwrap(self.b2)
        x = unwrap(expert_inputs)
        h = jnp.einsum("ecd,edh->ech", x, w1) + b1[:, None, :]
        h = unwrap(self.activation(h))
        return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


class MoELayer(Layer):
    """Mixture of experts (reference moe_layer.py:261).

    forward(x: [B, S, d]) -> [B, S, d]; the load-balance aux loss of the
    last call is at ``self.aux_loss`` (callers add it to the objective —
    same contract as the reference's gate.get_loss()).
    """

    def __init__(self, d_model: int, num_experts: int,
                 d_hidden: Optional[int] = None, gate: str = "gshard",
                 top_k: Optional[int] = None,
                 capacity_factor: float = 1.25,
                 experts: Optional[Layer] = None, ep_axis: str = "ep"):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        if gate == "gshard":
            self.gate = GShardGate(d_model, num_experts, capacity_factor)
        elif gate == "switch":
            self.gate = SwitchGate(d_model, num_experts)
        elif gate == "naive":
            self.gate = NaiveGate(d_model, num_experts,
                                  top_k=top_k or 2)
        else:
            raise ValueError(f"unknown gate {gate}")
        if top_k is not None:
            self.gate.top_k = top_k
        self.experts = experts or ExpertFFN(
            num_experts, d_model, d_hidden or 4 * d_model, ep_axis=ep_axis)
        self.aux_loss = None

    def forward(self, x):
        """NOTE: the gating/dispatch math runs on raw traced values — the
        supported training path is through jit/functional_call (TrainStep),
        where gradients flow through the whole routed computation.  The
        eager tape does not differentiate through this layer."""
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.core.dispatch import unwrap
        data = unwrap(x)
        B, S, d = data.shape
        T = B * S
        E = self.num_experts
        x2d = data.reshape(T, d)

        # expected assignments are top_k*T/E under balanced routing, so
        # capacity must scale with k (reference GShardGate caps per expert
        # at ceil(cap_rate * tokens), similarly k-aware in effect)
        capacity = max(1, int(self.capacity_factor * self.gate.top_k
                              * T / E))
        logits = unwrap(self.gate.logits(x2d))
        combine, dispatch, aux = top_k_gating(
            logits, k=self.gate.top_k, capacity=capacity)
        self.aux_loss = aux

        # dispatch: [T,E,C] x [T,d] -> [E,C,d]; GSPMD lowers the contraction
        # to the expert all_to_all when E is sharded on ep
        expert_in = jnp.einsum("tec,td->ecd",
                               dispatch.astype(data.dtype), x2d)
        expert_in = constrain(expert_in, P(self.ep_axis, None, None))
        expert_out = unwrap(self.experts(expert_in))
        # combine: [T,E,C] x [E,C,d] -> [T,d]
        out = jnp.einsum("tec,ecd->td", combine.astype(data.dtype),
                         expert_out)
        out = out.reshape(B, S, d)
        if hasattr(x, "_data"):
            from paddle_tpu.core.tensor import Tensor
            t = Tensor(out)
            t.stop_gradient = x.stop_gradient
            return t
        return out
