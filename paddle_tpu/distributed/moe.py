"""Mixture-of-Experts with expert parallelism.

Reference parity: ``MoELayer`` (incubate/distributed/models/moe/moe_layer.py
:261) with gates (moe/gate/: NaiveGate, GShardGate, SwitchGate), dispatch via
``MoEScatter``/``MoEGather`` PyLayers (:97,:147) around the
``global_scatter``/``global_gather`` all-to-all collective ops
(operators/collective/global_scatter_op.cu.cc), capacity + load-balance loss
(moe/utils.py).

TPU-native design (the GShard recipe): token routing is expressed as dense
einsums with a one-hot dispatch mask — no gather/scatter kernels, fully
differentiable, MXU-friendly — and expert weights are stacked ``[E, ...]``
arrays whose PartitionSpec puts E on the ``ep`` mesh axis.  Under jit,
GSPMD turns the dispatch einsum into exactly the all_to_all the reference
implements as ``global_scatter`` (sharding constraints below pin that
layout).  Capacity math and the load-balance auxiliary loss follow GShard
§3.2, matching the reference's utils.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed.mpu import constrain

__all__ = ["top_k_gating", "NaiveGate", "SwitchGate", "GShardGate",
           "MoELayer", "ExpertFFN", "moe_shard_a2a", "moe_forward_a2a"]


def top_k_gating(gate_logits, k: int, capacity: int,
                 jitter_key=None, jitter_eps: float = 0.0):
    """GShard top-k gating with capacity.

    Args:
      gate_logits: [tokens, E].
    Returns:
      combine: [tokens, E, C] combine weights (0 for dropped tokens),
      dispatch: same-shape bool mask,
      aux_loss: load-balance loss (mean_prob * mean_assignment * E),
      router z-loss is folded in by callers that want it.
    """
    tokens, E = gate_logits.shape
    if jitter_key is not None and jitter_eps > 0:
        noise = jax.random.uniform(jitter_key, gate_logits.shape,
                                   minval=1 - jitter_eps,
                                   maxval=1 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)          # [T, E]

    combine = jnp.zeros((tokens, E, capacity), probs.dtype)
    dispatch = jnp.zeros((tokens, E, capacity), bool)
    # running per-expert fill count, updated between the k passes
    fill = jnp.zeros((E,), jnp.int32)
    masked_probs = probs
    aux_mask = jnp.zeros((tokens, E), probs.dtype)

    for _ in range(k):
        choice = jnp.argmax(masked_probs, axis=-1)        # [T]
        onehot = jax.nn.one_hot(choice, E, dtype=probs.dtype)
        aux_mask = aux_mask + onehot
        # position of each token within its chosen expert's queue
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
        pos = pos + fill[None, :] * onehot
        in_cap = (pos < capacity) & (onehot > 0)
        gate_val = (probs * onehot).sum(-1)               # [T]
        pos_idx = pos.sum(-1).astype(jnp.int32)           # [T]
        cap_onehot = jax.nn.one_hot(pos_idx, capacity,
                                    dtype=probs.dtype)    # [T, C]
        sel = in_cap.any(-1)
        combine = combine + (gate_val[:, None, None]
                             * onehot[:, :, None]
                             * cap_onehot[:, None, :]
                             * sel[:, None, None])
        dispatch = dispatch | ((onehot[:, :, None] * cap_onehot[:, None, :])
                               > 0) & sel[:, None, None]
        fill = fill + (onehot * in_cap).sum(0).astype(jnp.int32)
        masked_probs = masked_probs * (1.0 - onehot)      # exclude chosen

    # normalise combine weights over the k experts per token
    denom = combine.sum(axis=(1, 2), keepdims=True)
    combine = jnp.where(denom > 0, combine / jnp.maximum(denom, 1e-9),
                        combine)

    # GShard load-balance loss: E * mean_e(frac_tokens_e * mean_prob_e)
    me = probs.mean(axis=0)                               # [E]
    ce = (aux_mask > 0).astype(probs.dtype).mean(axis=0) / k
    aux_loss = (me * ce).sum() * E
    return combine, dispatch, aux_loss


class NaiveGate(Layer):
    """Linear router, top-k, no noise (reference moe/gate/naive_gate.py)."""

    def __init__(self, d_model: int, num_experts: int, top_k: int = 2):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.gate = self.create_parameter([d_model, num_experts])

    def logits(self, x2d):
        from paddle_tpu.core.dispatch import unwrap
        return x2d @ unwrap(self.gate)

    def extra(self) -> dict:
        return {}


class SwitchGate(NaiveGate):
    """top-1 (Switch Transformer; reference moe/gate/switch_gate.py)."""

    def __init__(self, d_model, num_experts, jitter_eps: float = 0.01):
        super().__init__(d_model, num_experts, top_k=1)
        self.jitter_eps = jitter_eps


class GShardGate(NaiveGate):
    """top-2 with capacity (reference moe/gate/gshard_gate.py)."""

    def __init__(self, d_model, num_experts, capacity_factor: float = 1.25):
        super().__init__(d_model, num_experts, top_k=2)
        self.capacity_factor = capacity_factor


class ExpertFFN(Layer):
    """Stacked expert FFNs: [E, d, h] / [E, h, d] weights, E on the ep
    axis.  One einsum per projection keeps every expert's GEMM on the MXU
    and gives GSPMD the expert axis to all_to_all over."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 activation: Callable = None, ep_axis: str = "ep"):
        super().__init__()
        from jax.sharding import PartitionSpec as P
        self.num_experts = num_experts
        self.activation = activation or F.gelu
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b1 = self.create_parameter([num_experts, d_hidden],
                                        is_bias=True)
        self.b2 = self.create_parameter([num_experts, d_model],
                                        is_bias=True)
        self.w1.partition_spec = P(ep_axis, None, None)
        self.w2.partition_spec = P(ep_axis, None, None)
        self.b1.partition_spec = P(ep_axis, None)
        self.b2.partition_spec = P(ep_axis, None)

    def forward(self, expert_inputs):
        """expert_inputs: [E, C, d] -> [E, C, d]."""
        from paddle_tpu.core.dispatch import unwrap
        return _expert_ffn(unwrap(expert_inputs), unwrap(self.w1),
                           unwrap(self.b1), unwrap(self.w2), unwrap(self.b2),
                           lambda v: unwrap(self.activation(v)))


def _expert_ffn(x, w1, b1, w2, b2, act):
    """Stacked-expert FFN compute shared by ExpertFFN.forward and the
    all_to_all dispatch path: [E, C, d] -> [E, C, d]."""
    h = jnp.einsum("ecd,edh->ech", x, w1) + b1[:, None, :]
    return jnp.einsum("ech,ehd->ecd", act(h), w2) + b2[:, None, :]


def moe_shard_a2a(x2d, gate_w, w1, b1, w2, b2, *, top_k: int,
                  capacity: int, activation=None, ep_axis: str = "ep"):
    """Explicit all_to_all expert dispatch — runs INSIDE shard_map.

    Semantic parity with the reference's global_scatter/global_gather
    collectives (operators/collective/global_scatter_op.cu.cc): each ep
    shard routes its local tokens into per-expert capacity buffers, an
    all_to_all exchanges the expert axis for a source-shard axis, local
    experts run, and the inverse all_to_all returns results.

    Args:
      x2d: [T_loc, d] local tokens.
      gate_w: [d, E] replicated router weight (E = global expert count).
      w1/b1/w2/b2: LOCAL expert slices [E_loc, ...] (ep-sharded).
      capacity: per (source shard, expert) buffer slots.
    Returns:
      out: [T_loc, d]; aux: global mean load-balance loss.
    """
    act = activation or jax.nn.gelu
    logits = x2d @ gate_w                                     # [T_loc, E]
    combine, dispatch, aux = top_k_gating(logits, k=top_k, capacity=capacity)

    buf = jnp.einsum("tec,td->ecd", dispatch.astype(x2d.dtype), x2d)
    # [E, C, d] -> split experts to their shards, gather source chunks:
    # [E_loc, n_shards*C, d]
    recv = jax.lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=1,
                              tiled=True)
    out_loc = _expert_ffn(recv, w1, b1, w2, b2, act)
    # inverse exchange: [E_loc, n*C, d] -> [E, C, d]
    back = jax.lax.all_to_all(out_loc, ep_axis, split_axis=1, concat_axis=0,
                              tiled=True)
    out = jnp.einsum("tec,ecd->td", combine.astype(x2d.dtype), back)
    return out, jax.lax.pmean(aux, ep_axis)


def moe_forward_a2a(x, gate_w, w1, b1, w2, b2, *, mesh, top_k: int = 2,
                    capacity_factor: float = 1.25, dropless: bool = False,
                    activation=None, ep_axis: str = "ep"):
    """Jit-callable wrapper: shard_maps :func:`moe_shard_a2a` over the ep
    axis of ``mesh``.

    x: [B, S, d] — flattened to [B*S, d] and sharded on the token axis
    (constraint: B*S divisible by the ep mesh size); expert weights
    [E, ...] sharded on ep (E divisible by ep size); gate replicated."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    shape = x.shape
    d = shape[-1]
    x2d = x.reshape(-1, d)  # shard the flat token axis, not the batch axis
    n = mesh.shape[ep_axis]
    E = gate_w.shape[-1]
    T = x2d.shape[0]
    if T % n:
        raise ValueError(f"token count {T} not divisible by ep={n}")
    if E % n:
        raise ValueError(f"expert count {E} not divisible by ep={n}")
    t_loc = T // n
    if dropless:
        capacity = t_loc  # an expert can receive at most every local token
    else:
        capacity = max(1, int(capacity_factor * top_k * t_loc / E))

    def fn(xs, gw, a1, c1, a2, c2):
        return moe_shard_a2a(xs, gw, a1, c1, a2, c2, top_k=top_k,
                             capacity=capacity, activation=activation,
                             ep_axis=ep_axis)

    mapped = shard_map(
        fn, mesh=mesh,
        in_specs=(P(ep_axis), P(), P(ep_axis), P(ep_axis), P(ep_axis),
                  P(ep_axis)),
        out_specs=(P(ep_axis), P()))
    out, aux = mapped(x2d, gate_w, w1, b1, w2, b2)
    return out.reshape(shape), aux


class MoELayer(Layer):
    """Mixture of experts (reference moe_layer.py:261).

    forward(x: [B, S, d]) -> [B, S, d]; the load-balance aux loss of the
    last call is at ``self.aux_loss`` (callers add it to the objective —
    same contract as the reference's gate.get_loss()).
    """

    def __init__(self, d_model: int, num_experts: int,
                 d_hidden: Optional[int] = None, gate: str = "gshard",
                 top_k: Optional[int] = None,
                 capacity_factor: float = 1.25,
                 experts: Optional[Layer] = None, ep_axis: str = "ep",
                 dispatch_mode: str = "einsum", dropless: bool = False,
                 mesh=None):
        super().__init__()
        if dispatch_mode not in ("einsum", "all_to_all"):
            raise ValueError(f"unknown dispatch_mode {dispatch_mode}")
        if dispatch_mode == "all_to_all" and mesh is None:
            raise ValueError("dispatch_mode='all_to_all' needs mesh=")
        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        self.dispatch_mode = dispatch_mode
        self.dropless = dropless
        self.mesh = mesh
        if gate == "gshard":
            self.gate = GShardGate(d_model, num_experts, capacity_factor)
        elif gate == "switch":
            self.gate = SwitchGate(d_model, num_experts)
        elif gate == "naive":
            self.gate = NaiveGate(d_model, num_experts,
                                  top_k=top_k or 2)
        else:
            raise ValueError(f"unknown gate {gate}")
        if top_k is not None:
            self.gate.top_k = top_k
        self.experts = experts or ExpertFFN(
            num_experts, d_model, d_hidden or 4 * d_model, ep_axis=ep_axis)
        self.aux_loss = None

    def forward(self, x):
        """NOTE: the gating/dispatch math runs on raw traced values — the
        supported training path is through jit/functional_call (TrainStep),
        where gradients flow through the whole routed computation.  The
        eager tape does not differentiate through this layer."""
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.core.dispatch import unwrap
        data = unwrap(x)
        B, S, d = data.shape
        T = B * S

        if self.dispatch_mode == "all_to_all":
            if not isinstance(self.experts, ExpertFFN):
                raise ValueError("all_to_all dispatch requires the stacked "
                                 "ExpertFFN experts")
            out, aux = moe_forward_a2a(
                data, unwrap(self.gate.gate),
                unwrap(self.experts.w1), unwrap(self.experts.b1),
                unwrap(self.experts.w2), unwrap(self.experts.b2),
                mesh=self.mesh, top_k=self.gate.top_k,
                capacity_factor=self.capacity_factor,
                dropless=self.dropless, ep_axis=self.ep_axis,
                activation=lambda v: unwrap(self.experts.activation(v)))
            self.aux_loss = aux
            if hasattr(x, "_data"):
                from paddle_tpu.core.tensor import Tensor
                t = Tensor(out)
                t.stop_gradient = x.stop_gradient
                return t
            return out

        E = self.num_experts
        x2d = data.reshape(T, d)
        # expected assignments are top_k*T/E under balanced routing, so
        # capacity must scale with k (reference GShardGate caps per expert
        # at ceil(cap_rate * tokens), similarly k-aware in effect);
        # dropless pins capacity at T so no token can ever be dropped —
        # exact but O(T^2·E) dispatch memory, toy/test scale only (the
        # all_to_all path bounds capacity at tokens-per-shard instead)
        if self.dropless:
            capacity = T
        else:
            capacity = max(1, int(self.capacity_factor * self.gate.top_k
                                  * T / E))
        logits = unwrap(self.gate.logits(x2d))
        combine, dispatch, aux = top_k_gating(
            logits, k=self.gate.top_k, capacity=capacity)
        self.aux_loss = aux

        # dispatch: [T,E,C] x [T,d] -> [E,C,d]; GSPMD lowers the contraction
        # to the expert all_to_all when E is sharded on ep
        expert_in = jnp.einsum("tec,td->ecd",
                               dispatch.astype(data.dtype), x2d)
        expert_in = constrain(expert_in, P(self.ep_axis, None, None))
        expert_out = unwrap(self.experts(expert_in))
        # combine: [T,E,C] x [E,C,d] -> [T,d]
        out = jnp.einsum("tec,ecd->td", combine.astype(data.dtype),
                         expert_out)
        out = out.reshape(B, S, d)
        if hasattr(x, "_data"):
            from paddle_tpu.core.tensor import Tensor
            t = Tensor(out)
            t.stop_gradient = x.stop_gradient
            return t
        return out
