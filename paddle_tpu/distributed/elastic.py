"""Elastic checkpoint-restart orchestration.

Reference parity: ``ElasticManager`` (fleet/elastic/manager.py:124 — etcd
heartbeat watch + job restart), the launch master/watcher
(launch/controllers/master.py:65,175, controllers/watcher.py).

TPU-native translation (SURVEY §5.3): TPU pods can't hot-swap a failed
worker into a live NCCL ring the way parameter-server jobs can — the
recovery unit is the whole SPMD program.  So elasticity = fast detect +
relaunch + resume: workers heartbeat into the native TCPStore
(csrc/store), the manager watches heartbeats and process exits, and on
any failure it kills the generation, bumps the generation counter, and
relaunches; workers resume from the latest AutoCheckpoint step.

Scope decision (recorded, VERDICT r3 Weak #5): the manager orchestrates
ONE node.  Multi-host TPU jobs are gang-scheduled by the cluster manager
(GKE/Borg/Ray), which already detects node loss and reschedules the whole
slice — re-implementing the reference's etcd-lease multi-node
ElasticManager (fleet/elastic/manager.py:124,252-299) would duplicate the
platform layer TPU deployments always run under.  Run one elastic
launcher per host under the cluster manager; cross-host resume
consistency comes from AutoCheckpoint's validated per-shard checkpoints
(every process restores the same validator-approved step).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from paddle_tpu.distributed.tcp_store import TCPStore

__all__ = ["ElasticAgent", "ElasticManager", "free_port"]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ElasticAgent:
    """Worker-side heartbeat (reference: elastic/manager.py worker lease).

    Reads PADDLE_ELASTIC_STORE / PADDLE_ELASTIC_GEN / PADDLE_TRAINER_ID
    from the env the manager sets; a daemon thread refreshes
    ``hb/<gen>/<rank>`` every ``interval`` seconds.
    """

    def __init__(self, rank: Optional[int] = None,
                 store: Optional[TCPStore] = None, interval: float = 0.5):
        addr = os.environ.get("PADDLE_ELASTIC_STORE")
        if store is None:
            if not addr:
                raise RuntimeError("PADDLE_ELASTIC_STORE not set (worker "
                                   "not launched by ElasticManager?)")
            host, port = addr.rsplit(":", 1)
            store = TCPStore(host, int(port), is_master=False)
        self._store = store
        self.rank = rank if rank is not None else \
            int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.generation = int(os.environ.get("PADDLE_ELASTIC_GEN", "0"))
        self._key = f"hb/{self.generation}/{self.rank}"
        self._interval = interval
        self._stop = threading.Event()
        self._beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _beat(self):
        self._store.set(self._key, repr(time.time()).encode())

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._beat()
            except Exception:
                return  # store gone: manager is tearing the generation down

    def stop(self):
        self._stop.set()


class ElasticManager:
    """Launcher-side watcher + relaunch loop.

    cmd: worker argv (sys.executable script args...).  Spawns ``nproc``
    workers per generation with PADDLE_TRAINER_ID / PADDLE_ELASTIC_*
    env; any non-zero exit or heartbeat staleness fails the generation,
    which is killed and relaunched up to ``max_restarts`` times.
    Training scripts resume via AutoCheckpoint.restore_latest().
    """

    def __init__(self, cmd: Sequence[str], nproc: int = 1,
                 max_restarts: int = 3, heartbeat_timeout: float = 10.0,
                 poll_interval: float = 0.2,
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None):
        self.cmd = list(cmd)
        self.nproc = nproc
        self.max_restarts = max_restarts
        self.heartbeat_timeout = heartbeat_timeout
        self.poll_interval = poll_interval
        self.extra_env = dict(env or {})
        self.log_dir = log_dir
        self.restarts = 0
        self.generation = 0
        self._port = free_port()
        self._store = TCPStore("127.0.0.1", self._port, is_master=True)

    # -- generation lifecycle ------------------------------------------------
    def _spawn(self) -> List[subprocess.Popen]:
        procs = []
        self._log_files = []
        # fresh rendezvous endpoint per generation: survivors of the old
        # coordinator must not collide with the relaunched group
        master = f"127.0.0.1:{free_port()}"
        for rank in range(self.nproc):
            env = dict(os.environ)
            env.update(self.extra_env)
            env.update({
                # same rendezvous contract as the non-elastic launcher
                "PADDLE_MASTER": master,
                "COORDINATOR_ADDRESS": master,
                "PADDLE_TRAINERS_NUM": str(self.nproc),
                "NUM_PROCESSES": str(self.nproc),
                "PADDLE_TRAINER_ID": str(rank),
                "PROCESS_ID": str(rank),
                "PADDLE_LOCAL_RANK": str(rank),
                "PADDLE_ELASTIC_STORE": f"127.0.0.1:{self._port}",
                "PADDLE_ELASTIC_GEN": str(self.generation),
                "PADDLE_ELASTIC_RESTARTS": str(self.restarts),
            })
            stdout = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                stdout = open(os.path.join(
                    self.log_dir,
                    f"workerlog.g{self.generation}.{rank}"), "w")
                self._log_files.append(stdout)
            procs.append(subprocess.Popen(
                self.cmd, env=env, stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None))
        return procs

    def _heartbeats_fresh(self, now: float,
                          procs: List[subprocess.Popen]) -> bool:
        """False when any STILL-RUNNING rank that has beaten this
        generation has gone stale (cleanly-exited ranks naturally stop
        beating; a never-started worker is covered by process polling)."""
        for rank in range(self.nproc):
            key = f"hb/{self.generation}/{rank}"
            has_beat = self._store.check(key)
            if has_beat:
                self._gen_hb_seen = True  # even for already-exited ranks
            if procs[rank].poll() is not None:
                continue  # exited; exit-code handling belongs to _watch
            if not has_beat:
                continue
            last = float(self._store.get(key, wait=False).decode())
            if now - last > self.heartbeat_timeout:
                return False
        return True

    def _watch(self, procs: List[subprocess.Popen]) -> bool:
        """True when all workers exit 0; False on any failure."""
        while True:
            alive = False
            for p in procs:
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    return False
            if not alive:
                return True
            if not self._heartbeats_fresh(time.time(), procs):
                return False
            time.sleep(self.poll_interval)

    def _kill_all(self, procs: List[subprocess.Popen]):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 5.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def run(self) -> int:
        """Blocks until the job succeeds (0) or restarts are exhausted (1).

        A generation that dies fast without EVER heartbeating is treated as
        an infrastructure failure (typically the free_port() TOCTOU: the
        rendezvous port probed free gets re-allocated before the worker
        binds) and is relaunched on a fresh port WITHOUT consuming a
        restart — bounded by its own small cap so a genuinely
        insta-crashing workload still terminates."""
        infra_retries = 0
        while True:
            self._gen_hb_seen = False
            started = time.time()
            procs = []
            try:
                procs = self._spawn()
                ok = self._watch(procs)
            finally:
                self._kill_all(procs)
                for f in getattr(self, "_log_files", []):
                    f.close()
            if ok:
                return 0
            # final sweep: the generation may have died between heartbeat
            # polls — an hb key in the store means workers DID come up
            self._gen_hb_seen = self._gen_hb_seen or any(
                self._store.check(f"hb/{self.generation}/{r}")
                for r in range(self.nproc))
            fast_infra_fail = (not self._gen_hb_seen
                               and time.time() - started
                               < min(self.heartbeat_timeout, 10.0))
            if fast_infra_fail and infra_retries < 3:
                infra_retries += 1  # global cap: never re-arms
                self.generation += 1
                continue
            self.restarts += 1
            if self.restarts > self.max_restarts:
                return 1
            self.generation += 1

    def close(self):
        self._store.close()
