"""Elastic checkpoint-restart orchestration.

Reference parity: ``ElasticManager`` (fleet/elastic/manager.py:124 — etcd
heartbeat watch + job restart), the launch master/watcher
(launch/controllers/master.py:65,175, controllers/watcher.py).

TPU-native translation (SURVEY §5.3): TPU pods can't hot-swap a failed
worker into a live NCCL ring the way parameter-server jobs can — the
recovery unit is the whole SPMD program.  So elasticity = fast detect +
relaunch + resume: workers heartbeat into the native TCPStore
(csrc/store), the manager watches heartbeats and process exits, and on
any failure it kills the generation, bumps the generation counter, and
relaunches; workers resume from the latest AutoCheckpoint step.

Two tiers:

* :class:`ElasticManager` — single node: spawn + watch + relaunch.
* :class:`MultiNodeElasticAgent` — the reference's etcd-lease multi-node
  ElasticManager (fleet/elastic/manager.py:124,252-299) rebuilt over the
  native TCPStore (csrc/store), which plays the etcd role: an atomic
  ``elastic/gen`` counter is the epoch, per-generation registration
  counters + member manifests are the lease registry, and periodic
  ``nodehb`` keys are the TTL heartbeats.  Node death/scale-up both
  resolve to "bump the generation": every agent kills its local workers,
  re-registers, recomputes ranks from the new member manifest, and
  relaunches; workers resume from the latest validated AutoCheckpoint
  step (the per-shard format reshards across changed world sizes).
  Store availability is the etcd-availability analog: run the hosting
  process somewhere stable (or behind a VIP), exactly as the reference
  assumes a live etcd.
"""

from __future__ import annotations

import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from paddle_tpu.distributed.tcp_store import TCPStore

__all__ = ["ElasticAgent", "ElasticManager", "MultiNodeElasticAgent",
           "free_port"]

_DRAIN_KEY = "elastic/drain"


def _install_drain_handlers(on_signal):
    """Route SIGTERM/SIGINT to `on_signal(signum)`; returns the previous
    handlers for restoration (empty when not on the main thread, where
    the signal module refuses installs — callers just skip the feature)."""
    old = {}
    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            old[sig] = signal.signal(
                sig, lambda signum, frame: on_signal(signum))
    except ValueError:  # not the main thread
        old.clear()
    return old


def _restore_handlers(old):
    for sig, handler in old.items():
        try:
            signal.signal(sig, handler)
        except (ValueError, TypeError):
            pass


def _elastic_metrics():
    """Restart/generation telemetry on the default registry, labeled by
    failure class so operators can alert on real failures without
    paging for free infra relaunches."""
    from paddle_tpu.observability import default_registry
    reg = default_registry()
    return {
        "restarts": reg.counter(
            "paddle_tpu_elastic_restarts_total",
            "generation relaunches", labelnames=("reason",)),
        "generation": reg.gauge("paddle_tpu_elastic_generation",
                                "current elastic generation"),
        "gen_seconds": reg.histogram(
            "paddle_tpu_elastic_generation_seconds",
            "lifetime of each finished generation",
            buckets=(1, 5, 15, 60, 300, 900, 3600, 14400, 86400)),
        "downtime": reg.counter(
            "paddle_tpu_elastic_downtime_seconds_total",
            "wall seconds between a generation ending and the next one "
            "spawning (backoff + teardown) — the elastic restart gap "
            "observability.goodput debits from training goodput"),
    }


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ElasticAgent:
    """Worker-side heartbeat (reference: elastic/manager.py worker lease).

    Reads PADDLE_ELASTIC_STORE / PADDLE_ELASTIC_GEN / PADDLE_TRAINER_ID
    from the env the manager sets; a daemon thread refreshes
    ``hb/<gen>/<rank>`` every ``interval`` seconds.

    Preemption awareness: the heartbeat thread also polls the manager's
    ``elastic/drain`` key, and (by default) SIGTERM/SIGINT in the worker
    sets the same flag — either way :attr:`draining` flips True and the
    training loop is expected to write a final synchronous checkpoint
    (``AutoCheckpoint.save_now``) and exit 0 before the manager's
    ``drain_timeout`` expires.
    """

    def __init__(self, rank: Optional[int] = None,
                 store: Optional[TCPStore] = None, interval: float = 0.5,
                 handle_signals: bool = True):
        addr = os.environ.get("PADDLE_ELASTIC_STORE")
        if store is None:
            if not addr:
                raise RuntimeError("PADDLE_ELASTIC_STORE not set (worker "
                                   "not launched by ElasticManager?)")
            host, port = addr.rsplit(":", 1)
            store = TCPStore(host, int(port), is_master=False)
        self._store = store
        self.rank = rank if rank is not None else \
            int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.generation = int(os.environ.get("PADDLE_ELASTIC_GEN", "0"))
        self._key = f"hb/{self.generation}/{self.rank}"
        # cross-host trace stitching: the manager publishes its
        # generation span's context under trace/gen/<g>; adopting it as
        # this process's ambient parent makes every local step span part
        # of the manager's trace — one timeline across all workers
        try:
            from paddle_tpu.observability.tracing import (extract_context,
                                                          tracer)
            ctx = extract_context(self._store,
                                  key=f"trace/gen/{self.generation}")
            if ctx is not None:
                tracer().set_process_context(ctx)
        except Exception:
            pass  # nobody tracing (or store too old): run untraced
        self._interval = interval
        self._stop = threading.Event()
        self._drain = threading.Event()
        self._old_handlers = _install_drain_handlers(
            lambda signum: self._drain.set()) if handle_signals else {}
        self._beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _beat(self):
        from paddle_tpu.robustness import fault_fires
        if fault_fires("elastic.heartbeat", rank=self.rank,
                       generation=self.generation):
            return  # chaos: this beat is lost (hang / network loss)
        self._store.set(self._key, repr(time.time()).encode())

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._beat()
                if not self._drain.is_set() and \
                        self._store.check(_DRAIN_KEY):
                    self._drain.set()
            except Exception:
                return  # store gone: manager is tearing the generation down

    @property
    def draining(self) -> bool:
        """True once a preemption drain was requested (manager store key
        or a SIGTERM/SIGINT delivered to this worker): checkpoint NOW and
        exit 0."""
        return self._drain.is_set()

    def stop(self):
        self._stop.set()
        _restore_handlers(self._old_handlers)


class ElasticManager:
    """Launcher-side watcher + relaunch loop.

    cmd: worker argv (sys.executable script args...).  Spawns ``nproc``
    workers per generation with PADDLE_TRAINER_ID / PADDLE_ELASTIC_*
    env; any non-zero exit or heartbeat staleness fails the generation,
    which is killed and relaunched up to ``max_restarts`` times.
    Training scripts resume via AutoCheckpoint.restore_latest().

    Robustness tentpole additions: SIGTERM/SIGINT triggers a graceful
    drain (workers signaled + ``elastic/drain`` store flag, bounded wait
    for their final synchronous checkpoint, exit 0 iff all left
    cleanly); failed generations relaunch with exponential backoff +
    jitter; ``circuit_fast_failures`` consecutive sub-
    ``circuit_min_uptime`` generations open a circuit breaker instead
    of burning the whole restart budget on a hopeless loop.

    Fast recovery (``recovery="peer"``): the manager publishes the
    ring-wise buddy map on its store and arms workers
    (``PADDLE_TPU_RECOVERY=peer`` / ``PADDLE_TPU_SNAPSHOT_INTERVAL``)
    to mirror their state to their buddy every
    ``snapshot_interval_steps`` steps
    (:class:`paddle_tpu.robustness.recovery.PeerSnapshotter`) and to
    resume via :func:`~paddle_tpu.robustness.recovery.
    resume_train_state` — a RAM fetch instead of a disk walk, so the
    restart gap the goodput ledger debits
    (``paddle_tpu_elastic_downtime_seconds_total``) shrinks to the
    relaunch itself.
    """

    def __init__(self, cmd: Sequence[str], nproc: int = 1,
                 max_restarts: int = 3, heartbeat_timeout: float = 10.0,
                 poll_interval: float = 0.2,
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None,
                 drain_timeout: float = 30.0,
                 backoff_base: float = 0.5, backoff_max: float = 30.0,
                 circuit_fast_failures: int = 5,
                 circuit_min_uptime: float = 5.0,
                 recovery: str = "disk",
                 snapshot_interval_steps: int = 10):
        self.cmd = list(cmd)
        self.nproc = nproc
        self.max_restarts = max_restarts
        self.heartbeat_timeout = heartbeat_timeout
        self.poll_interval = poll_interval
        self.extra_env = dict(env or {})
        self.log_dir = log_dir
        self.restarts = 0
        self.generation = 0
        # preemption drain: SIGTERM/SIGINT → signal workers, bounded wait
        # for their final synchronous checkpoint, exit 0 — never hard-kill
        self.drain_timeout = drain_timeout
        self._drain_signal: Optional[int] = None
        # relaunch pacing: exponential backoff + jitter between failed
        # generations (a crashing dependency gets time to recover instead
        # of being hammered), and a circuit breaker that stops relaunching
        # after `circuit_fast_failures` CONSECUTIVE generations each dying
        # within `circuit_min_uptime` seconds — a restart loop that never
        # reaches useful uptime burns quota without making progress
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.circuit_fast_failures = circuit_fast_failures
        self.circuit_min_uptime = circuit_min_uptime
        # fast-recovery mode (robustness.recovery): recovery="peer"
        # tells workers to mirror their param/opt shard to a ring buddy
        # through this manager's store every `snapshot_interval_steps`
        # steps, and to resume from the buddy's RAM copy (disk fallback
        # only when no peer holds a fresh snapshot) — the store outlives
        # generations, so the snapshots survive the crash they recover
        if recovery not in ("disk", "peer"):
            raise ValueError(f"recovery must be 'disk' or 'peer', got "
                             f"{recovery!r}")
        self.recovery = recovery
        self.snapshot_interval_steps = int(snapshot_interval_steps)
        self._port = free_port()
        self._store = TCPStore("127.0.0.1", self._port, is_master=True)
        if recovery == "peer":
            import json as _json
            from paddle_tpu.robustness.recovery import buddy_map
            self._store.set("recovery/buddies", _json.dumps(
                {str(r): b for r, b in buddy_map(nproc).items()}))

    # -- generation lifecycle ------------------------------------------------
    def _spawn(self) -> List[subprocess.Popen]:
        procs = []
        self._log_files = []
        # fresh rendezvous endpoint per generation: survivors of the old
        # coordinator must not collide with the relaunched group
        master = f"127.0.0.1:{free_port()}"
        for rank in range(self.nproc):
            env = dict(os.environ)
            env.update(self.extra_env)
            env.update({
                # same rendezvous contract as the non-elastic launcher
                "PADDLE_MASTER": master,
                "COORDINATOR_ADDRESS": master,
                "PADDLE_TRAINERS_NUM": str(self.nproc),
                "NUM_PROCESSES": str(self.nproc),
                "PADDLE_TRAINER_ID": str(rank),
                "PROCESS_ID": str(rank),
                "PADDLE_LOCAL_RANK": str(rank),
                "PADDLE_ELASTIC_STORE": f"127.0.0.1:{self._port}",
                "PADDLE_ELASTIC_GEN": str(self.generation),
                "PADDLE_ELASTIC_RESTARTS": str(self.restarts),
            })
            if self.recovery == "peer":
                env.update({
                    "PADDLE_TPU_RECOVERY": "peer",
                    "PADDLE_TPU_SNAPSHOT_INTERVAL":
                        str(self.snapshot_interval_steps),
                })
            stdout = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                stdout = open(os.path.join(
                    self.log_dir,
                    f"workerlog.g{self.generation}.{rank}"), "w")
                self._log_files.append(stdout)
            procs.append(subprocess.Popen(
                self.cmd, env=env, stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None))
        return procs

    def _heartbeats_fresh(self, now: float,
                          procs: List[subprocess.Popen]) -> bool:
        """False when any STILL-RUNNING rank that has beaten this
        generation has gone stale (cleanly-exited ranks naturally stop
        beating; a never-started worker is covered by process polling)."""
        for rank in range(self.nproc):
            key = f"hb/{self.generation}/{rank}"
            has_beat = self._store.check(key)
            if has_beat:
                self._gen_hb_seen = True  # even for already-exited ranks
            if procs[rank].poll() is not None:
                continue  # exited; exit-code handling belongs to _watch
            if not has_beat:
                continue
            last = float(self._store.get(key, wait=False).decode())
            if now - last > self.heartbeat_timeout:
                return False
        return True

    def _watch(self, procs: List[subprocess.Popen]):
        """True when all workers exit 0; False on any failure; "drain"
        when a preemption signal arrived (graceful drain already ran)."""
        while True:
            if self._drain_signal is not None:
                return "drain"
            alive = False
            for p in procs:
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    return False
            if not alive:
                return True
            if not self._heartbeats_fresh(time.time(), procs):
                return False
            time.sleep(self.poll_interval)

    def _graceful_drain(self, procs: List[subprocess.Popen]) -> int:
        """Preemption path: publish the drain flag (agents poll it),
        forward SIGTERM to every live worker, wait up to `drain_timeout`
        for them to write their final checkpoint and exit, then report
        0 only if every worker left cleanly.  Stragglers are killed —
        the platform's hard deadline is coming either way."""
        return _drain_workers(self._store, procs, self.drain_timeout,
                              generation=self.generation,
                              signal=self._drain_signal)

    def _kill_all(self, procs: List[subprocess.Popen]):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 5.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def run(self) -> int:
        """Blocks until the job succeeds (0) or restarts are exhausted (1).

        A generation that dies fast without EVER heartbeating is treated as
        an infrastructure failure (typically the free_port() TOCTOU: the
        rendezvous port probed free gets re-allocated before the worker
        binds) and is relaunched on a fresh port WITHOUT consuming a
        restart — bounded by its own small cap so a genuinely
        insta-crashing workload still terminates."""
        from paddle_tpu.observability import flight_recorder
        from paddle_tpu.observability.tracing import (inject_context,
                                                      tracer)
        metrics = _elastic_metrics()
        recorder = flight_recorder()
        tr = tracer()
        infra_retries = 0
        fast_fail_streak = 0
        prev_gen_end: Optional[float] = None
        old_handlers = _install_drain_handlers(self._on_drain_signal)
        try:
            while True:
                self._gen_hb_seen = False
                started = time.time()
                if prev_gen_end is not None:
                    # restart gap: dead time between generations (kill
                    # sweep + backoff) is the goodput debit the fleet
                    # plane surfaces
                    gap = max(0.0, started - prev_gen_end)
                    metrics["downtime"].inc(gap)
                    recorder.record("elastic.restart_gap",
                                    generation=self.generation,
                                    gap_s=round(gap, 3))
                metrics["generation"].set(self.generation)
                recorder.record("elastic.spawn",
                                generation=self.generation,
                                nproc=self.nproc, restarts=self.restarts,
                                recovery=self.recovery)
                # generation-lifetime span; its context is published on
                # the store BEFORE workers spawn so their ElasticAgents
                # adopt it and the whole generation stitches into one
                # trace across processes
                gen_span = tr.start_span("elastic.generation",
                                         generation=self.generation,
                                         nproc=self.nproc)
                if gen_span.context is not None:
                    try:
                        inject_context(self._store,
                                       key=f"trace/gen/"
                                           f"{self.generation}",
                                       ctx=gen_span.context)
                    except Exception:
                        pass
                procs, drain_rc, ok = [], None, None
                try:
                    procs = self._spawn()
                    ok = self._watch(procs)
                    if ok == "drain":
                        drain_rc = self._graceful_drain(procs)
                finally:
                    self._kill_all(procs)
                    for f in getattr(self, "_log_files", []):
                        f.close()
                    gen_span.set_attribute(
                        "outcome",
                        "drain" if ok == "drain" else
                        "ok" if ok is True else
                        "fail" if ok is False else "error")
                    gen_span.end()
                metrics["gen_seconds"].observe(time.time() - started)
                prev_gen_end = time.time()
                if ok == "drain":
                    return drain_rc
                if ok:
                    recorder.record("elastic.done",
                                    generation=self.generation)
                    return 0
                # final sweep: the generation may have died between
                # heartbeat polls — an hb key in the store means workers
                # DID come up
                self._gen_hb_seen = self._gen_hb_seen or any(
                    self._store.check(f"hb/{self.generation}/{r}")
                    for r in range(self.nproc))
                fast_infra_fail = (not self._gen_hb_seen
                                   and time.time() - started
                                   < min(self.heartbeat_timeout, 10.0))
                recorder.record("elastic.generation_failed",
                                generation=self.generation,
                                infra=fast_infra_fail,
                                hb_seen=self._gen_hb_seen)
                # circuit breaker: consecutive sub-`circuit_min_uptime`
                # failures mean relaunching is not helping — open the
                # circuit instead of burning the restart budget forever
                if time.time() - started < self.circuit_min_uptime:
                    fast_fail_streak += 1
                else:
                    fast_fail_streak = 0
                if self.circuit_fast_failures and \
                        fast_fail_streak >= self.circuit_fast_failures:
                    recorder.record("elastic.circuit_open",
                                    generation=self.generation,
                                    streak=fast_fail_streak)
                    return 1
                if fast_infra_fail and infra_retries < 3:
                    infra_retries += 1  # global cap: never re-arms
                    metrics["restarts"].labels(reason="infra").inc()
                    self.generation += 1
                    continue
                self.restarts += 1
                metrics["restarts"].labels(reason="fail").inc()
                if self.restarts > self.max_restarts:
                    recorder.record("elastic.exhausted",
                                    generation=self.generation,
                                    restarts=self.restarts)
                    return 1
                self._backoff(self.restarts)
                if self._drain_signal is not None:
                    # preempted between generations: nothing is running,
                    # the last checkpoint is already durable — leave clean
                    recorder.record("elastic.drain_end",
                                    generation=self.generation,
                                    clean=True, stragglers=0)
                    return 0
                self.generation += 1
        finally:
            _restore_handlers(old_handlers)

    def _on_drain_signal(self, signum: int):
        self._drain_signal = signum

    def _backoff(self, attempt: int):
        """Exponential backoff + jitter before a relaunch, capped and
        interruptible by a drain signal (a preempted manager must not
        sit out its grace period asleep)."""
        delay = min(self.backoff_max,
                    self.backoff_base * (2 ** max(0, attempt - 1)))
        deadline = time.monotonic() + delay * (1.0 + 0.25 * random.random())
        while time.monotonic() < deadline:
            if self._drain_signal is not None:
                return
            time.sleep(min(0.05, self.poll_interval))

    def close(self):
        self._store.close()


class MultiNodeElasticAgent:
    """Multi-node elastic orchestration over the shared TCPStore.

    Reference parity: ``ElasticManager`` + its etcd watcher
    (fleet/elastic/manager.py:124 registration with TTL leases,
    :252-299 scale/death watch + relaunch with recomputed ranks).

    One agent runs per node.  Protocol, all on the shared store:

    1. **Epoch**: ``elastic/gen`` (atomic counter) names the current
       generation ``g``.  Any agent observing a failure (or joining late)
       bumps it; every agent polls it and treats a bump as "kill local
       workers, re-rendezvous".
    2. **Rendezvous** (per ``g``): registrants take an index from the
       ``elastic/nreg/<g>`` counter and publish a payload under
       ``elastic/member/<g>/<idx>``.  Registrant 0 is the leader: it
       waits until the count covers ``min_nodes`` and is either at
       ``max_nodes`` or stable for ``rendezvous_window`` seconds, then
       publishes the ``elastic/members/<g>`` manifest.  Node rank =
       manifest index; global worker ranks are the prefix sums of each
       node's ``nproc``.  A registrant excluded from the manifest (it
       arrived after finalization) bumps the generation — that IS the
       scale-up path.
    3. **Leases**: each agent refreshes ``elastic/nodehb/<g>/<rank>``;
       a peer stale past ``heartbeat_timeout`` fails the generation.
       Local worker process exits / stale worker heartbeats (the
       single-node watcher's checks) fail it too.
    4. **Completion**: a node whose workers all exit 0 increments
       ``elastic/ndone/<g>`` and waits for it to reach the member count.

    Workers resume from :class:`~paddle_tpu.distributed.checkpoint.
    AutoCheckpoint` — its per-shard format restores under a different
    process count, so scale-down resumes are exact, not best-effort.

    A node on the SDC quarantine roster
    (:func:`paddle_tpu.robustness.recovery.is_quarantined`) refuses to
    re-register: ``run()`` returns 3 and the surviving fleet
    re-rendezvouses without the blamed hardware.
    """

    _RESTART = object()

    def __init__(self, cmd: Sequence[str], *, store_addr: str,
                 host_store: bool = False, nproc: int = 1,
                 min_nodes: int = 1, max_nodes: Optional[int] = None,
                 max_restarts: int = 3, heartbeat_timeout: float = 10.0,
                 rendezvous_window: float = 2.0,
                 rendezvous_timeout: float = 120.0,
                 node_host: str = "127.0.0.1",
                 poll_interval: float = 0.2,
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None,
                 node_id: Optional[str] = None,
                 recovery: str = "disk",
                 snapshot_interval_steps: int = 10):
        if recovery not in ("disk", "peer"):
            raise ValueError(f"recovery must be 'disk' or 'peer', got "
                             f"{recovery!r}")
        self.recovery = recovery
        self.snapshot_interval_steps = int(snapshot_interval_steps)
        self.cmd = list(cmd)
        self.nproc = nproc
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.max_restarts = max_restarts
        self.heartbeat_timeout = heartbeat_timeout
        self.rendezvous_window = rendezvous_window
        self.rendezvous_timeout = rendezvous_timeout
        self.node_host = node_host
        self.poll_interval = poll_interval
        self.extra_env = dict(env or {})
        self.log_dir = log_dir
        self.node_id = node_id or f"{socket.gethostname()}:{os.getpid()}"
        host, port = store_addr.rsplit(":", 1)
        self.store_addr = store_addr
        if host_store:
            self._store = TCPStore(host, int(port), is_master=True)
        else:
            # the hosting agent may still be starting up — TCPStore's own
            # backoff-retry connect (the etcd client's dial-retry analog)
            # covers the window; 60s is the join patience
            self._store = TCPStore(host, int(port), is_master=False,
                                   connect_timeout=60.0)
        self._log_files: List = []
        self.drain_timeout = 30.0
        self.backoff_base, self.backoff_max = 0.5, 30.0
        self._drain_signal: Optional[int] = None

    # -- store helpers -------------------------------------------------------
    def _gen_now(self) -> int:
        return self._store.add("elastic/gen", 0)

    def _bump(self, g: int, reason: str = "fail"):
        """Fail generation g exactly once per observer: benign if two
        agents race (both saw g; the counter moves past g either way and
        every agent re-reads the CURRENT value at re-rendezvous).  The
        recorded reason lets survivors keep scale-up rescales off the
        failure budget."""
        if self._gen_now() == g:
            self._store.set(f"elastic/why/{g}", reason)
            self._store.add("elastic/gen", 1)

    def _bump_reason(self, g: int) -> str:
        try:
            return self._store.get(f"elastic/why/{g}",
                                   wait=False).decode()
        except Exception:
            return "fail"

    # -- rendezvous ----------------------------------------------------------
    def _rendezvous(self, g: int):
        """Returns (node_rank, members, timed_out): (None, None, False)
        when generation g was abandoned benignly (bump observed / this
        node excluded), (None, None, True) when the rendezvous DEADLINE
        forced the abandonment — the caller counts consecutive timeouts
        so permanent peer loss terminates instead of spinning forever."""
        import json
        deadline = time.monotonic() + self.rendezvous_timeout
        idx = self._store.add(f"elastic/nreg/{g}", 1) - 1
        payload = {"node": self.node_id, "host": self.node_host,
                   "port": free_port(), "nproc": self.nproc}
        self._store.set(f"elastic/member/{g}/{idx}", json.dumps(payload))
        if idx == 0:
            last_c, last_t = 1, time.monotonic()
            while True:
                c = self._store.add(f"elastic/nreg/{g}", 0)
                if c != last_c:
                    last_c, last_t = c, time.monotonic()
                if c >= self.min_nodes and (
                        (self.max_nodes and c >= self.max_nodes)
                        or time.monotonic() - last_t
                        >= self.rendezvous_window):
                    break
                if self._gen_now() != g:
                    return None, None, False
                if time.monotonic() > deadline:
                    # not enough peers arrived: abandon g so every waiter
                    # (including us) retries a fresh generation
                    self._bump(g, "rendezvous")
                    return None, None, True
                time.sleep(0.05)
            members = []
            for i in range(last_c):
                # a registrant may have taken an index and died before
                # publishing its payload — poll without blocking, bounded
                # by the rendezvous deadline, then abandon the generation
                while not self._store.check(f"elastic/member/{g}/{i}"):
                    if time.monotonic() > deadline:
                        self._bump(g, "rendezvous")
                        return None, None, True
                    time.sleep(0.05)
                members.append(json.loads(self._store.get(
                    f"elastic/member/{g}/{i}").decode()))
            self._store.set(f"elastic/members/{g}", json.dumps(members))
        else:
            while not self._store.check(f"elastic/members/{g}"):
                if self._gen_now() != g:
                    return None, None, False
                if time.monotonic() > deadline:
                    self._bump(g, "rendezvous")
                    return None, None, True
                time.sleep(0.05)
            members = json.loads(self._store.get(
                f"elastic/members/{g}").decode())
        mine = [i for i, m in enumerate(members)
                if m["node"] == self.node_id]
        if not mine:
            # registered after finalization: force a rescale that
            # includes us (the reference's scale-up watch)
            self._bump(g, "scale")
            return None, None, False
        return mine[0], members, False

    # -- generation ----------------------------------------------------------
    def _spawn(self, g: int, node_rank: int, members) -> List:
        total = sum(m["nproc"] for m in members)
        base = sum(m["nproc"] for m in members[:node_rank])
        master = f"{members[0]['host']}:{members[0]['port']}"
        procs = []
        self._log_files = []
        for local_rank in range(self.nproc):
            rank = base + local_rank
            env = dict(os.environ)
            env.update(self.extra_env)
            env.update({
                "PADDLE_MASTER": master,
                "COORDINATOR_ADDRESS": master,
                "PADDLE_TRAINERS_NUM": str(total),
                "NUM_PROCESSES": str(total),
                "PADDLE_TRAINER_ID": str(rank),
                "PROCESS_ID": str(rank),
                "PADDLE_LOCAL_RANK": str(local_rank),
                "PADDLE_NODE_RANK": str(node_rank),
                "PADDLE_ELASTIC_STORE": self.store_addr,
                "PADDLE_ELASTIC_GEN": str(g),
            })
            if self.recovery == "peer":
                # workers derive the ring buddy map from their rank /
                # world size (both above), which tracks rescales — the
                # buddy of rank r is always (r + 1) % world
                env.update({
                    "PADDLE_TPU_RECOVERY": "peer",
                    "PADDLE_TPU_SNAPSHOT_INTERVAL":
                        str(self.snapshot_interval_steps),
                })
            stdout = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                stdout = open(os.path.join(
                    self.log_dir, f"workerlog.g{g}.n{node_rank}.{rank}"),
                    "w")
                self._log_files.append(stdout)
            procs.append(subprocess.Popen(
                self.cmd, env=env, stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None))
        return procs

    def _run_generation(self, g: int, node_rank: int, members):
        """0 on global success, _RESTART to re-rendezvous, or
        ``("drain", rc)`` after a graceful preemption drain."""
        n_nodes = len(members)
        base = sum(m["nproc"] for m in members[:node_rank])
        started = time.monotonic()
        peer_seen: Dict[int, tuple] = {}   # rank -> (last bytes, seen at)
        done_marked = False
        # node 0 roots the generation trace and publishes its context;
        # every other node parents a node-local span under it, and all
        # workers (via ElasticAgent's extract) join the same trace_id —
        # the multi-host timeline stitches on that id
        from paddle_tpu.observability.tracing import (extract_context,
                                                      inject_context,
                                                      tracer)
        tr = tracer()
        if node_rank == 0:
            gen_span = tr.start_span("elastic.generation", generation=g,
                                     node=self.node_id, nodes=n_nodes)
            if gen_span.context is not None:
                try:
                    inject_context(self._store, key=f"trace/gen/{g}",
                                   ctx=gen_span.context)
                except Exception:
                    pass
        else:
            parent = extract_context(self._store, key=f"trace/gen/{g}")
            gen_span = tr.start_span("elastic.node_generation",
                                     parent=parent, generation=g,
                                     node=self.node_id)
        procs = self._spawn(g, node_rank, members)
        try:
            while True:
                now = time.monotonic()
                if self._drain_signal is not None or \
                        self._store.check(_DRAIN_KEY):
                    # preemption (local signal or a peer's published
                    # flag): drain THIS node's workers gracefully; peers
                    # see the store flag and do the same
                    return ("drain",
                            _drain_workers(self._store, procs,
                                           self.drain_timeout,
                                           generation=g,
                                           node=self.node_id))
                self._store.set(f"elastic/nodehb/{g}/{node_rank}",
                                repr(time.time()).encode())
                if self._gen_now() != g:
                    return self._RESTART
                # local worker exits
                codes = [p.poll() for p in procs]
                if any(rc not in (None, 0) for rc in codes):
                    # fast death with no heartbeat ever = infrastructure
                    # (the free_port TOCTOU class) — same classification
                    # as ElasticManager.run(); recorded so peers don't
                    # charge their restart budget either
                    fast = (now - started
                            < min(self.heartbeat_timeout, 10.0))
                    any_hb = any(
                        self._store.check(f"hb/{g}/{base + lr}")
                        for lr in range(self.nproc))
                    self._bump(g, "infra" if fast and not any_hb
                               else "fail")
                    return self._RESTART
                # local worker heartbeat staleness (only once seen)
                for lr in range(self.nproc):
                    if codes[lr] is not None:
                        continue
                    key = f"hb/{g}/{base + lr}"
                    if self._store.check(key):
                        last = float(self._store.get(key,
                                                     wait=False).decode())
                        if time.time() - last > self.heartbeat_timeout:
                            self._bump(g)
                            return self._RESTART
                # peer node leases — staleness is judged by when WE last
                # observed the value CHANGE (local monotonic clock), never
                # by comparing the peer's embedded wall-clock to ours:
                # cross-host clock skew must not fail healthy generations
                for r in range(n_nodes):
                    if r == node_rank:
                        continue
                    key = f"elastic/nodehb/{g}/{r}"
                    if self._store.check(key):
                        val = self._store.get(key, wait=False)
                        prev = peer_seen.get(r)
                        if prev is None or prev[0] != val:
                            peer_seen[r] = (val, now)
                    entry = peer_seen.get(r)
                    stale = ((now - entry[1] > self.heartbeat_timeout)
                             if entry is not None else
                             (now - started > 2 * self.heartbeat_timeout))
                    if stale:
                        self._bump(g)
                        return self._RESTART
                if all(rc == 0 for rc in codes):
                    if not done_marked:
                        done_marked = True
                        ndone = self._store.add(f"elastic/ndone/{g}", 1)
                    else:
                        ndone = self._store.add(f"elastic/ndone/{g}", 0)
                    if ndone >= n_nodes:
                        return 0
                time.sleep(self.poll_interval)
        finally:
            _kill_procs(procs)
            for f in self._log_files:
                f.close()
            gen_span.end()

    def run(self) -> int:
        """Budget accounting: only generations that this agent actually
        RAN and that ended for a "fail" reason consume ``max_restarts`` —
        scale-up rescales and abandoned rendezvous (both recorded in
        ``elastic/why/<g>``) are free, so a 4-node job where 3 survivors
        race to report one death still burns exactly one restart each."""
        from paddle_tpu.observability import flight_recorder
        metrics = _elastic_metrics()
        recorder = flight_recorder()
        failures = 0
        infra = 0    # free infra relaunches (bounded; never re-arms)
        barren = 0   # consecutive DEADLINE-forced rendezvous abandonments
        old_handlers = _install_drain_handlers(
            lambda signum: setattr(self, "_drain_signal", signum))
        try:
            return self._run_inner(metrics, recorder, failures, infra,
                                   barren)
        finally:
            _restore_handlers(old_handlers)

    def _run_inner(self, metrics, recorder, failures, infra, barren) -> int:
        while True:
            # SDC quarantine (robustness.recovery): a host the sentinels
            # blamed for silent corruption must sit out — the surviving
            # peers re-rendezvous without it (the per-shard checkpoint
            # format re-shards across the smaller world), and this agent
            # leaves with a distinctive code instead of re-registering
            # bad hardware into every future generation
            try:
                from paddle_tpu.robustness.recovery import is_quarantined
                if is_quarantined(self._store, self.node_id):
                    recorder.record("elastic.quarantined",
                                    node=self.node_id)
                    return 3
            except Exception:
                pass  # roster unreadable: run (quarantine is advisory)
            g = self._gen_now()
            metrics["generation"].set(g)
            if self._drain_signal is not None:
                # preempted while between generations: no local workers,
                # nothing to flush — leave clean (peers drain themselves)
                try:
                    self._store.set(_DRAIN_KEY, b"1")
                except Exception:
                    pass
                recorder.record("elastic.drain_end", generation=g,
                                node=self.node_id, clean=True,
                                stragglers=0)
                return 0
            if failures > self.max_restarts:
                recorder.record("elastic.exhausted", generation=g,
                                node=self.node_id, failures=failures)
                return 1
            node_rank, members, timed_out = self._rendezvous(g)
            if node_rank is None:
                # benign abandonments (peer bumped / scale-up) are free
                # and fast; deadline timeouts mean peers are GONE — after
                # max_restarts+1 consecutive barren rendezvous (each
                # rendezvous_timeout long) give up instead of spinning
                # forever on a permanently-lost quorum
                if timed_out:
                    barren += 1
                    if barren > self.max_restarts:
                        return 1
                time.sleep(self.poll_interval)
                continue
            barren = 0
            gen_started = time.time()
            recorder.record("elastic.spawn", generation=g,
                            node=self.node_id, node_rank=node_rank,
                            nodes=len(members))
            rc = self._run_generation(g, node_rank, members)
            metrics["gen_seconds"].observe(time.time() - gen_started)
            if rc == 0:
                recorder.record("elastic.done", generation=g,
                                node=self.node_id)
                return 0
            if isinstance(rc, tuple) and rc[0] == "drain":
                return rc[1]
            reason = self._bump_reason(g)
            metrics["restarts"].labels(reason=reason).inc()
            recorder.record("elastic.generation_failed", generation=g,
                            node=self.node_id, reason=reason)
            if reason == "infra":
                infra += 1
                if infra > 3:   # insta-crashing workload, not infra
                    failures += 1
            elif reason == "fail":
                failures += 1
                # pace the re-rendezvous after a real failure: peers all
                # back off together (similar delays), so the crashed
                # dependency gets breathing room before the next epoch
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** max(0,
                                                          failures - 1)))
                time.sleep(delay * (1.0 + 0.25 * random.random()))

    def close(self):
        self._store.close()


def _kill_procs(procs: List[subprocess.Popen]):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + 5.0
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def _drain_workers(store, procs: List[subprocess.Popen],
                   drain_timeout: float, **ctx) -> int:
    """Shared graceful-drain body (single-node manager + multi-node
    agent): publish the store drain flag, SIGTERM live workers, bounded
    wait for the final synchronous checkpoints, 0 iff all exited 0."""
    from paddle_tpu.observability import flight_recorder
    recorder = flight_recorder()
    recorder.record("elastic.drain_begin", timeout=drain_timeout, **ctx)
    try:
        store.set(_DRAIN_KEY, b"1")
    except Exception:
        pass  # store already down: the SIGTERM forward still drains
    for p in procs:
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass
    deadline = time.monotonic() + drain_timeout
    for p in procs:
        try:
            p.wait(timeout=max(0.05, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            pass
    stragglers = sum(p.poll() is None for p in procs)
    clean = stragglers == 0 and all(p.poll() == 0 for p in procs)
    recorder.record("elastic.drain_end", clean=clean,
                    stragglers=stragglers, **ctx)
    return 0 if clean else 1
