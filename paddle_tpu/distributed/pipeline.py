"""Pipeline parallelism.

Reference parity: ``PipelineLayer``/``LayerDesc``/``SharedLayerDesc``
(fleet/meta_parallel/parallel_layers/pp_layers.py:240,56,76), segmentation
(``SegmentLayers`` pp_layers.py:92), the 1F1B runtime
(``PipelineParallel.forward_backward_pipeline``
meta_parallel/pipeline_parallel.py:188) and interleaved variant (:565,642),
P2P activations (pp_utils/p2p_communication.py).

TPU-native design: the reference runs one Python process per stage that
`send/recv`s activations over NCCL and hand-schedules
forward/backward interleaving.  Under single-controller SPMD the whole
schedule is ONE traced program: stage weights are stacked on a leading
[num_stages, ...] axis sharded over the ``pp`` mesh axis, and a
``lax.scan`` over schedule ticks moves activations between neighbouring
stages with ``lax.ppermute`` (XLA collective-permute — ICI point-to-point).
Because ppermute/scan are differentiable, ``jax.grad`` of the scanned loss
IS the pipelined backward — the compiler produces the reverse schedule that
the reference writes by hand, and rematerialisation (``jax.checkpoint`` on
the stage fn) gives the 1F1B-grade memory profile.

Scope note: the scanned schedule is GPipe-shaped (all forwards, then the
transposed backwards). 1F1B reorders the *runtime buffer lifetimes*, which
in the reference reduces live activations from O(M) to O(S); here the same
reduction comes from `remat='stage'` (save only stage boundaries, recompute
inside the backward scan), which is how praxis/maxtext express it on TPU.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.distributed.communication import axis_size as _axis_size, \
    vma_of as _vma_of
from paddle_tpu.jit.train_step import CompiledStepBase as _TrainStepBase
from paddle_tpu.nn.layer import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer",
           "spmd_pipeline", "build_1f1b_schedule", "pipeline_1f1b",
           "build_interleaved_schedule", "pipeline_interleaved",
           "PipelineTrainStep"]


class LayerDesc:
    """Deferred layer constructor (reference pp_layers.py:56)."""

    def __init__(self, layer_cls, *args, **kwargs):
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"{layer_cls} must be a Layer subclass")
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (reference
    pp_layers.py:76 — e.g. tied embedding/lm-head; the reference allreduces
    the shared grads across stages (:532); here the tied parameter is a
    single array the compiler sees twice, so its gradient contributions sum
    automatically)."""

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layer descs into S contiguous stages (reference
    pp_layers.py:92): 'uniform' by count or 'param' by parameter volume."""

    def __init__(self, layers: Sequence, num_parts: int,
                 method: str = "uniform"):
        self.layers = list(layers)
        self.num_parts = num_parts
        self.method = method
        if len(self.layers) < num_parts:
            raise ValueError(
                f"{len(self.layers)} layers < {num_parts} stages")

    def do_segment(self) -> List[int]:
        """Returns stage boundaries, len == num_parts+1."""
        n, s = len(self.layers), self.num_parts
        if self.method == "uniform":
            base, rem = divmod(n, s)
            sizes = [base + (1 if i < rem else 0) for i in range(s)]
        elif self.method.startswith("layer:"):
            # weight by occurrences of a named layer class (reference
            # supports 'layer:TransformerLayer')
            name = self.method.split(":", 1)[1]
            weights = [1 if getattr(d, "layer_cls", type(d)).__name__ == name
                       else 0 for d in self.layers]
            sizes = self._balance(weights, s)
        elif self.method == "param":
            weights = []
            for d in self.layers:
                layer = d.build_layer() if isinstance(d, LayerDesc) else d
                weights.append(sum(int(np.prod(p.shape))
                                   for p in layer.parameters()) or 1)
            sizes = self._balance(weights, s)
        else:
            raise ValueError(f"unknown segment method {self.method}")
        bounds = [0]
        for sz in sizes:
            bounds.append(bounds[-1] + sz)
        return bounds

    @staticmethod
    def _balance(weights: List[int], s: int) -> List[int]:
        """Greedy prefix split minimising max stage weight."""
        total = sum(weights)
        target = total / s
        sizes, acc, count = [], 0.0, 0
        remaining_parts = s
        for i, w in enumerate(weights):
            acc += w
            count += 1
            remaining = len(weights) - i - 1
            if (acc >= target and remaining_parts > 1
                    and remaining >= remaining_parts - 1):
                sizes.append(count)
                acc, count = 0.0, 0
                remaining_parts -= 1
        sizes.append(count)
        while len(sizes) < s:
            sizes.append(0)
        return sizes


class PipelineLayer(Layer):
    """Stage-segmented model container (reference pp_layers.py:240).

    Single-controller SPMD holds ALL stages' weights (each sharded to its
    stage's devices by the pp dim of the stacked arrays), so unlike the
    reference there is no per-rank construction: ``forward`` runs the full
    serial stack (parity/eval path), and ``stage_layers(i)`` exposes the
    per-stage slices for the spmd schedule.
    """

    def __init__(self, layers: Sequence, num_stages: int,
                 topology=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, name=None):
        super().__init__()
        self._descs = list(layers)
        self._num_stages = num_stages
        self.seg_method = seg_method
        self.recompute_interval = recompute_interval

        self.segment_bounds = SegmentLayers(
            self._descs, num_stages, seg_method).do_segment()

        from paddle_tpu.nn.common_layers import LayerList
        built: List[Layer] = []
        self._shared: dict = {}
        self._shared_fwd: dict = {}
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    # reuse the first instance's weights: same Layer object
                    built.append(self._shared[d.layer_name])
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                    built.append(layer)
                self._shared_fwd[len(built) - 1] = d.forward_func
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:
                raise TypeError(f"bad pipeline element {d!r}")
        self.run_function = LayerList(built)

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def get_num_virtual_stages(self) -> int:
        return 1

    def stage_layers(self, stage: int) -> List[Layer]:
        lo, hi = self.segment_bounds[stage], self.segment_bounds[stage + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x):
        for i, layer in enumerate(self.run_function):
            fwd = self._shared_fwd.get(i)
            x = fwd(layer, x) if fwd is not None else layer(x)
        return x


# -- the SPMD schedule -------------------------------------------------------

def spmd_pipeline(stage_fn: Callable, stage_params: Any, microbatches,
                  *, num_microbatches: int, axis_name: str = "pp",
                  remat: bool = True):
    """Run a homogeneous-stage pipeline INSIDE an enclosing shard_map.

    Args:
      stage_fn: ``(params_for_this_stage, x) -> y`` — one stage's compute.
        Same jaxpr on every device (SPMD); per-stage behaviour comes from
        the params.
      stage_params: this device's slice of the stacked [S, ...] params
        (shard_map has already split the leading axis).
      microbatches: ``[M, mb, ...]`` array of all microbatch inputs,
        replicated over the pp axis.
      num_microbatches: M (static).
      remat: jax.checkpoint the stage fn — recompute stage interiors in
        the backward pass, keeping only boundary activations live (the
        memory behaviour 1F1B buys in the reference).

    Returns ``[M, mb, ...]`` outputs, valid on the LAST stage (other
    stages hold zeros); combine with a ``where(axis_index==S-1, ...)``
    psum or an out_spec that keeps the pp axis.

    Schedule: T = M + S - 1 ticks.  At tick t stage s computes microbatch
    ``t - s`` (when in range) — the classic GPipe wavefront; ppermute
    rotates boundary activations one hop per tick over ICI.
    """
    S = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = num_microbatches
    mb_shape = microbatches.shape[1:]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # probe output shape: stages must be shape-preserving on the boundary
    out_shape = jax.eval_shape(fn, stage_params,
                               jax.ShapeDtypeStruct(
                                   mb_shape, microbatches.dtype))
    if (out_shape.shape, out_shape.dtype) != (mb_shape, microbatches.dtype):
        raise ValueError(
            "spmd_pipeline requires shape-preserving stages; got "
            f"{mb_shape}->{out_shape.shape}")

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t (clamped; masked out when t >= M)
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, inject, recv)
        y = fn(stage_params, x)
        # rotate boundary activation to the next stage (ring; the wrap
        # last->first carries garbage that stage 0 ignores via `where`)
        new_recv = lax.ppermute(y, axis_name,
                                [(i, (i + 1) % S) for i in range(S)])
        # last stage records microbatch t-(S-1)
        m = t - (S - 1)
        write = (idx == S - 1) & (m >= 0) & (m < M)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(write, y,
                      lax.dynamic_index_in_dim(outputs, jnp.clip(m, 0, M - 1),
                                               axis=0, keepdims=False)),
            jnp.clip(m, 0, M - 1), axis=0)
        return (new_recv, outputs), None

    # the carry becomes device-varying after ppermute; mark the zero init
    # as varying too so shard_map's vma check accepts the scan
    from paddle_tpu.distributed.communication import pvary
    init = (pvary(jnp.zeros(mb_shape, microbatches.dtype), axis_name),
            pvary(jnp.zeros((M,) + mb_shape, microbatches.dtype),
                  axis_name))
    (recv, outputs), _ = lax.scan(tick, init, jnp.arange(M + S - 1))
    return outputs


def stack_stage_params(per_stage_params: List[Any]):
    """[pytree per stage] -> stacked pytree with leading S axis (to be
    sharded P('pp', ...)).  Stages must be homogeneous."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


# -- 1F1B: the explicit fused forward/backward schedule ----------------------

def build_1f1b_schedule(num_stages: int, num_microbatches: int):
    """Static [T, S] op/microbatch tables for the 1F1B schedule (reference
    PipelineParallel.forward_backward_pipeline, pipeline_parallel.py:188).

    Discrete-event simulation on the host (trace-time constant): each stage
    does warmup = S-1-s forwards, then strictly alternates backward/forward
    (the "one forward, one backward" steady state), then drains.  Arrival
    constraints (activation from upstream, cotangent from downstream, one
    hop per tick) are enforced by readiness sets, so the table is valid by
    construction.

    Returns (op[T,S], mb[T,S]) int32 numpy arrays; op: 0 idle, 1 fwd, 2 bwd.
    The max number of in-flight microbatches at stage s is S-s (<= S), which
    bounds the activation buffer — the memory property 1F1B exists for.
    """
    S, M = num_stages, num_microbatches
    fwd_ready = [set() for _ in range(S)]   # microbatches whose input arrived
    bwd_ready = [set() for _ in range(S)]   # cotangent arrived
    fwd_ready[0] = set(range(M))            # stage 0 owns all inputs
    fwd_done = [0] * S
    bwd_done = [0] * S
    ops, mbs = [], []
    guard = 0
    while any(b < M for b in bwd_done):
        guard += 1
        if guard > 4 * (M + S) + 16:
            raise RuntimeError("1f1b schedule did not converge")
        row_op = [0] * S
        row_mb = [0] * S
        events = []  # (stage, kind, m) applied after the tick
        for s in range(S):
            warmup = min(S - 1 - s, M)
            # next microbatch in order for each direction
            fm, bm = fwd_done[s], bwd_done[s]
            can_fwd = fm < M and fm in fwd_ready[s]
            can_bwd = bm < fwd_done[s] and bm in bwd_ready[s]
            prefer_bwd = fwd_done[s] >= warmup
            do_bwd = can_bwd and (prefer_bwd or not can_fwd)
            do_fwd = (not do_bwd) and can_fwd and \
                (fwd_done[s] - bwd_done[s]) <= warmup
            if do_bwd:
                row_op[s], row_mb[s] = 2, bm
                bwd_done[s] += 1
                if s > 0:
                    events.append((s - 1, "bwd", bm))
            elif do_fwd:
                row_op[s], row_mb[s] = 1, fm
                fwd_done[s] += 1
                if s < S - 1:
                    events.append((s + 1, "fwd", fm))
                else:
                    # last stage: its own cotangent is ready immediately
                    events.append((s, "bwd", fm))
        for s, kind, m in events:
            (fwd_ready if kind == "fwd" else bwd_ready)[s].add(m)
        ops.append(row_op)
        mbs.append(row_mb)
    return (np.asarray(ops, np.int32), np.asarray(mbs, np.int32))



def _varying_axes(axis_name, *trees):
    """Union of manual axes any leaf varies over, plus the pipeline axis —
    under a multi-axis mesh (pp x dp x tp) compute mixes them all, so every
    branch output / scan carry is marked varying over the full set."""
    axes = {axis_name}
    for v in jax.tree.leaves(trees):
        vma = _vma_of(v)
        if vma:
            axes |= set(vma)
    return tuple(sorted(axes))


def _pvary_axes(x, axes):
    from paddle_tpu.distributed.communication import pvary
    for ax in axes:
        x = pvary(x, ax)
    return x


def pipeline_1f1b(stage_fn: Callable, first_fn: Callable, last_fn: Callable,
                  stage_params: Any, mb_inputs, mb_labels, *,
                  num_microbatches: int, axis_name: str = "pp",
                  remat: bool = True, first_params: Any = None,
                  last_params: Any = None, stage_grad_reduce=None):
    """Fused forward+backward 1F1B pipeline step INSIDE a shard_map.

    The reference hand-schedules 1F1B across NCCL ranks
    (pipeline_parallel.py:188 warmup/steady/cooldown, p2p_communication.py);
    here the whole schedule is ONE lax.scan over ticks: every tick each
    stage consults the static schedule table and either forwards a
    microbatch, backwards one (recomputing its stage from the saved
    boundary input — the reference's recompute-interval memory trick, so
    only O(S) boundary activations are ever live), or idles.  Boundary
    activations ppermute forward, cotangents ppermute backward, parameter
    gradients accumulate in the carry.

    Args:
      stage_fn:  (params, x[mb, ...]) -> y[mb, ...] — the stage's block
        stack; boundary shape-preserving.
      first_fn:  (first_params-or-stage_params, raw_mb) -> x — input
        embedding, applied only on stage 0 (raw microbatch may be int ids).
      last_fn:   (last_params-or-stage_params, y, labels_mb) -> scalar
        loss — head + loss, applied only on the last stage.
      stage_params: this device's stage param slice (shard_map already
        split the stacked [S, ...] axis).
      first_params / last_params: OPTIONAL separate param trees for the
        embedding / head.  When given, first_fn/last_fn receive them
        instead of stage_params, so stage slices stay structurally
        homogeneous WITHOUT zero-replicated embed/head slots — the
        embed/head arrays live once (replicated or fsdp/tp-sharded by the
        caller), not stacked S-fold.  Their grads come back as separate
        trees, psum'd over the pp axis (stage 0 / stage S-1 own the only
        nonzero contributions).  When None, the old contract holds:
        first_fn/last_fn read from stage_params and their grads fold into
        the stage grads.  (Reference analog: pp_layers.py:92 segmentation
        where stage 0's partition simply owns the embedding layer.)
      mb_inputs: [M, mb, ...] raw microbatch inputs (replicated on pp).
      mb_labels: [M, mb, ...] labels (replicated on pp).

    Returns ``(mean_loss, stage_param_grads)`` without param groups, or
    ``(mean_loss, (stage_grads, first_grads, last_grads))`` when
    first_params/last_params are given (None entries where not given) —
    loss is valid on the last stage (psum'd over pp so every stage sees
    it), stage grads are per-stage.
    """
    S = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = num_microbatches
    from paddle_tpu.distributed.communication import pvary

    has_first = first_params is not None
    has_last = last_params is not None
    has_groups = has_first or has_last
    fparams = first_params if has_first else stage_params
    lparams = last_params if has_last else stage_params

    op_np, mb_np = build_1f1b_schedule(S, M)
    op_table = jnp.asarray(op_np)    # [T, S]
    mb_table = jnp.asarray(mb_np)
    T = op_np.shape[0]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # probe boundary shape; the embed→block seam may change dtype (e.g.
    # fp32 embedding into a bf16 block stack) — the block output fixes the
    # wire type and the seam casts into it
    x0 = jax.eval_shape(
        first_fn, fparams,
        jax.ShapeDtypeStruct(mb_inputs.shape[1:], mb_inputs.dtype))
    y0 = jax.eval_shape(fn, stage_params, x0)
    if y0.shape != x0.shape:
        raise ValueError(f"stage must preserve boundary shape: {x0} -> {y0}")
    bshape, bdtype = y0.shape, y0.dtype
    if y0.dtype != x0.dtype:
        y1 = jax.eval_shape(fn, stage_params,
                            jax.ShapeDtypeStruct(bshape, bdtype))
        if (y1.shape, y1.dtype) != (bshape, bdtype):
            raise ValueError(
                f"stage must be closed over the wire type {bdtype}: "
                f"{bshape}/{bdtype} -> {y1.shape}/{y1.dtype}")

    zeros_b = lambda: jnp.zeros(bshape, bdtype)
    promote = lambda tree: jax.tree.map(
        lambda a: jnp.zeros(a.shape, jnp.promote_types(a.dtype, jnp.float32)
                            if jnp.issubdtype(a.dtype, jnp.floating)
                            else a.dtype),
        tree)
    # stage_grad_reduce: optional per-tick reduction of the stage-grad
    # contribution (e.g. reduce-scatter over a ZeRO axis).  Applying it
    # INSIDE the tick keeps the grad accumulator at the reduced (sharded)
    # size instead of the full gathered size — at 70B scale the fp32 grad
    # carry would otherwise dominate HBM.  It must be linear (it is summed
    # across ticks) and uniform within every group of devices that share a
    # pp index (it runs inside the op-switch, whose branch choice varies
    # only over pp).
    grad_zero = promote(stage_params)
    if stage_grad_reduce is not None:
        grad_zero = stage_grad_reduce(grad_zero)
    fgrad_zero = promote(fparams) if has_first else None
    lgrad_zero = promote(lparams) if has_last else None

    inv_m = 1.0 / M

    # Sender-side static info lets the receiver decide whether this tick's
    # incoming wire payloads are real: what my upstream (idx-1) / downstream
    # (idx+1) neighbour did LAST tick, from the same static table.
    # up_op[t, s] = op of stage s-1 at tick t-1; down_op likewise.
    up_op = np.zeros_like(op_np)
    up_mb = np.zeros_like(mb_np)
    down_op = np.zeros_like(op_np)
    down_mb = np.zeros_like(mb_np)
    up_op[1:, 1:] = op_np[:-1, :-1]
    up_mb[1:, 1:] = mb_np[:-1, :-1]
    down_op[1:, :-1] = op_np[:-1, 1:]
    down_mb[1:, :-1] = mb_np[:-1, 1:]
    up_op_t = jnp.asarray(up_op)
    up_mb_t = jnp.asarray(up_mb)
    down_op_t = jnp.asarray(down_op)
    down_mb_t = jnp.asarray(down_mb)

    def _store(buf, valid, m, payload):
        """buf[m % S] = payload where valid (else unchanged)."""
        slot = m % S
        cur = lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            buf, jnp.where(valid, payload, cur), slot, 0)

    zero_tree = lambda z: jax.tree.map(lambda g: jnp.zeros_like(g), z)

    def tick(carry, t):
        (fwd_wire, bwd_wire, in_buf, cot_buf, grads, fgrads, lgrads,
         loss_acc) = carry
        op = op_table[t, idx]
        m = mb_table[t, idx]

        # 1) bank incoming wire payloads (schedule allows consuming them
        #    ticks later, so they must survive subsequent rotations)
        in_buf = _store(in_buf, up_op_t[t, idx] == 1, up_mb_t[t, idx],
                        fwd_wire)
        cot_buf = _store(cot_buf, down_op_t[t, idx] == 2, down_mb_t[t, idx],
                         bwd_wire)

        raw = lax.dynamic_index_in_dim(mb_inputs, m, 0, keepdims=False)
        lab = lax.dynamic_index_in_dim(mb_labels, m, 0, keepdims=False)
        x_saved = lax.dynamic_index_in_dim(in_buf, m % S, 0, keepdims=False)
        g_recv = lax.dynamic_index_in_dim(cot_buf, m % S, 0, keepdims=False)

        def thread_first(p, pf, x):
            # embed path on stage 0 only; `where` keeps the jaxpr uniform
            # across stages, grads flow to embed params only where idx==0
            x_in = jnp.where(idx == 0, first_fn(pf, raw).astype(bdtype), x)
            return fn(p, x_in)

        # 2) compute — switch so idle ticks cost nothing and fwd ticks
        #    don't pay the vjp.  Every branch output is pvary'd so the
        #    branches agree on varying-manual-axes types.
        def pv(y, dx, gtree, fgtree, lgtree, l):
            pvt = lambda tr: jax.tree.map(lambda a: _pvary_axes(a, vaxes),
                                          tr)
            return (_pvary_axes(y, act_axes), _pvary_axes(dx, act_axes),
                    pvt(gtree), pvt(fgtree), pvt(lgtree),
                    _pvary_axes(l, vaxes))

        def do_idle(_):
            return pv(zeros_b(), zeros_b(), zero_tree(grad_zero),
                      zero_tree(fgrad_zero), zero_tree(lgrad_zero),
                      jnp.zeros(()))

        def do_fwd(_):
            y = thread_first(stage_params, fparams, x_saved)
            return pv(y, zeros_b(), zero_tree(grad_zero),
                      zero_tree(fgrad_zero), zero_tree(lgrad_zero),
                      jnp.zeros(()))

        def do_bwd(_):
            def run(loss_like):
                val, pull = jax.vjp(loss_like, stage_params, fparams,
                                    lparams, x_saved)
                # the seed's varying-axes set must match val's (under a
                # multi-axis mesh the loss also varies over dp/tp axes)
                vma = _vma_of(val)
                seed = _pvary_axes(jnp.ones((), val.dtype),
                                   vma or (axis_name,))
                dp, dfp, dlp, dx = pull(seed)
                return val, dp, dfp, dlp, dx

            def last_branch(_):
                return run(lambda p, pf, pl, x: last_fn(
                    pl, thread_first(p, pf, x), lab) * inv_m)

            def mid_branch(_):
                # lparams is untouched here; jax.vjp returns zero
                # cotangents for unused arguments, keeping the branch
                # pytrees structurally identical
                return run(lambda p, pf, pl, x: jnp.sum(
                    thread_first(p, pf, x).astype(jnp.float32)
                    * g_recv.astype(jnp.float32)))

            val, dp, dfp, dlp, dx = lax.cond(idx == S - 1, last_branch,
                                             mid_branch, None)
            loss_c = jnp.where(idx == S - 1, val, 0.0)
            # fold group grads back into the stage tree when aliased
            if not has_first:
                dp = jax.tree.map(lambda a, b: a + b, dp, dfp)
            if not has_last:
                dp = jax.tree.map(lambda a, b: a + b, dp, dlp)
            cast = lambda dtree, ztree: jax.tree.map(
                lambda d, z: d.astype(z.dtype), dtree, ztree)
            if stage_grad_reduce is not None:
                dp = stage_grad_reduce(jax.tree.map(
                    lambda d: d.astype(jnp.float32)
                    if jnp.issubdtype(d.dtype, jnp.floating) else d, dp))
            return pv(zeros_b(), dx.astype(bdtype), cast(dp, grad_zero),
                      cast(dfp, fgrad_zero) if has_first
                      else zero_tree(fgrad_zero),
                      cast(dlp, lgrad_zero) if has_last
                      else zero_tree(lgrad_zero),
                      loss_c.astype(jnp.float32).reshape(()))

        send_y, send_dx, dp, dfp, dlp, loss_c = lax.switch(
            jnp.clip(op, 0, 2), [do_idle, do_fwd, do_bwd], None)

        add = lambda a, d: jax.tree.map(lambda g, x: g + x, a, d)
        grads = add(grads, dp)
        fgrads = add(fgrads, dfp)
        lgrads = add(lgrads, dlp)
        loss_acc = loss_acc + loss_c

        # 3) rotate: activations forward, cotangents backward (ring; the
        #    wrap edges carry garbage that validity gating ignores)
        new_fwd = lax.ppermute(send_y, axis_name,
                               [(i, (i + 1) % S) for i in range(S)])
        new_bwd = lax.ppermute(send_dx, axis_name,
                               [(i, (i - 1) % S) for i in range(S)])
        return (new_fwd, new_bwd, in_buf, cot_buf, grads, fgrads, lgrads,
                loss_acc), None

    # activations only vary over the pipeline axis and whatever the batch is
    # sharded on (e.g. dp) — marking them varying over tp too would insert a
    # spurious psum in the transpose, double-counting every gradient
    act_axes = _varying_axes(axis_name, mb_inputs, mb_labels)
    vaxes = _varying_axes(axis_name, stage_params, fparams, lparams,
                          mb_inputs, mb_labels)
    # group params arrive pp-replicated (invariant); left that way, the
    # per-tick vjp would AUTO-insert their grad psum over pp INSIDE the
    # lax.cond branch only some pp groups take — a cross-stage collective
    # half the devices never reach (deadlock).  pvary them over the
    # ACTIVATION axes (pp + data axes) so grads come back as per-device
    # partial sums and those reductions happen explicitly, outside
    # divergent control flow.  tp is deliberately left invariant: the wire
    # activations must stay off tp, and any auto tp-reduction is uniform
    # within a tp group (all its members share a pp index and branch).
    if has_first:
        fparams = jax.tree.map(lambda a: _pvary_axes(a, act_axes), fparams)
    if has_last:
        lparams = jax.tree.map(lambda a: _pvary_axes(a, act_axes), lparams)
    pvz = lambda tr: jax.tree.map(lambda z: _pvary_axes(z, vaxes), tr)
    init = (_pvary_axes(zeros_b(), act_axes),
            _pvary_axes(zeros_b(), act_axes),
            _pvary_axes(jnp.zeros((S,) + bshape, bdtype), act_axes),
            _pvary_axes(jnp.zeros((S,) + bshape, bdtype), act_axes),
            pvz(grad_zero), pvz(fgrad_zero), pvz(lgrad_zero),
            _pvary_axes(jnp.zeros((), jnp.float32), vaxes))
    (_, _, _, _, grads, fgrads, lgrads, loss_acc), _ = lax.scan(
        tick, init, jnp.arange(T))

    # every stage reports the (last-stage-only) loss
    loss = lax.psum(loss_acc, axis_name)
    if not has_groups:
        return loss, grads
    # group grads: only stage 0 (first) / stage S-1 (last) hold nonzero
    # contributions; psum over pp makes the true grad visible everywhere
    # (matching the groups' pp-replicated storage)
    psum_tree = lambda tr: jax.tree.map(
        lambda g: lax.psum(g, axis_name), tr) if tr is not None else None
    return loss, (grads, psum_tree(fgrads) if has_first else None,
                  psum_tree(lgrads) if has_last else None)


# -- interleaved virtual stages ----------------------------------------------

def build_interleaved_schedule(num_stages: int, num_chunks: int,
                               num_microbatches: int):
    """Static schedule for interleaved virtual stages (reference
    PipelineParallel._forward_backward_pipeline with virtual_pp_degree,
    pipeline_parallel.py:565,642; PipelineLayerChunk pp_layers.py:214).

    Device s holds chunks c=0..V-1; chunk c on device s is GLOBAL stage
    g = c*S + s (the reference's interleaved layout: consecutive model
    slices round-robin over devices).  Discrete-event simulation: one op
    per device per tick, backward preferred once warmup completes, with
    the same arrival constraints as 1F1B (one hop per tick both ways).

    Returns (op[T,S], chunk[T,S], mb[T,S]); op: 0 idle, 1 fwd, 2 bwd.
    """
    S, V, M = num_stages, num_chunks, num_microbatches
    G = S * V
    dev = lambda g: g % S
    fwd_ready = [set() for _ in range(G)]
    bwd_ready = [set() for _ in range(G)]
    fwd_ready[0] = set(range(M))
    fwd_done = [0] * G
    bwd_done = [0] * G
    ops, chunks, mbs = [], [], []
    guard = 0
    while any(b < M for b in bwd_done):
        guard += 1
        if guard > 8 * (M * V + G) + 16:
            raise RuntimeError("interleaved schedule did not converge")
        row_op = [0] * S
        row_ch = [0] * S
        row_mb = [0] * S
        events = []
        for s in range(S):
            # candidate ops among this device's chunks, deepest global
            # stage first so drains happen promptly
            pick = None
            for c in reversed(range(V)):
                g = c * S + s
                bm = bwd_done[g]
                if bm < fwd_done[g] and bm in bwd_ready[g]:
                    pick = (2, c, bm)
                    break
            if pick is None:
                # forward: lowest chunk whose next microbatch arrived and
                # whose in-flight count stays within the warmup bound
                for c in range(V):
                    g = c * S + s
                    fm = fwd_done[g]
                    warmup = min(G - 1 - g, M)
                    if fm < M and fm in fwd_ready[g] and \
                            (fwd_done[g] - bwd_done[g]) <= warmup:
                        pick = (1, c, fm)
                        break
            if pick is None:
                continue
            kind, c, m = pick
            g = c * S + s
            row_op[s], row_ch[s], row_mb[s] = kind, c, m
            if kind == 1:
                fwd_done[g] += 1
                if g < G - 1:
                    events.append((g + 1, "fwd", m))
                else:
                    events.append((g, "bwd", m))
            else:
                bwd_done[g] += 1
                if g > 0:
                    events.append((g - 1, "bwd", m))
        for g, kind, m in events:
            (fwd_ready if kind == "fwd" else bwd_ready)[g].add(m)
        ops.append(row_op)
        chunks.append(row_ch)
        mbs.append(row_mb)
    return (np.asarray(ops, np.int32), np.asarray(chunks, np.int32),
            np.asarray(mbs, np.int32))


def pipeline_interleaved(stage_fn: Callable, first_fn: Callable,
                         last_fn: Callable, chunk_params: Any,
                         mb_inputs, mb_labels, *, num_microbatches: int,
                         num_chunks: int, axis_name: str = "pp",
                         remat: bool = True):
    """Interleaved-virtual-stage fused fwd+bwd pipeline INSIDE shard_map.

    chunk_params: this device's [V, ...] chunk param stack (the global
    stack is [S, V, ...], shard_map split axis 0; element [s][c] serves
    global stage c*S + s).  Contract otherwise as :func:`pipeline_1f1b`.

    Wire routing differs from plain 1F1B in that the ring wrap is REAL:
    a forward boundary leaving device S-1 (chunk c) lands on device 0
    as the input of chunk c+1, and symmetrically for cotangents — the
    banking tables below encode exactly which (chunk, mb) each tick's
    incoming payload belongs to.
    """
    S = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = num_microbatches
    V = num_chunks
    G = S * V
    from paddle_tpu.distributed.communication import pvary

    # contract: called inside shard_map with the [S, V, ...] global stack
    # split by in_specs=P('pp'), so every local leaf arrives as [1, V, ...];
    # normalise to [V, ...] here and restore the leading pp axis on the
    # returned grads so out_specs=P('pp') reassembles the global stack
    for a in jax.tree.leaves(chunk_params):
        if a.ndim < 2 or a.shape[0] != 1 or a.shape[1] != V:
            raise ValueError(
                "pipeline_interleaved expects chunk_params leaves shaped "
                f"[1, V={V}, ...] (the shard_map-split [S, V, ...] stack); "
                f"got {a.shape}")
    chunk_params = jax.tree.map(lambda a: a.reshape(a.shape[1:]),
                                chunk_params)

    op_np, ch_np, mb_np = build_interleaved_schedule(S, V, M)
    T = op_np.shape[0]
    op_table = jnp.asarray(op_np)
    ch_table = jnp.asarray(ch_np)
    mb_table = jnp.asarray(mb_np)

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # host-side banking tables: validity + (chunk, mb) of each incoming wire
    up_valid = np.zeros((T, S), bool)
    up_ch = np.zeros((T, S), np.int32)
    up_mb = np.zeros((T, S), np.int32)
    dn_valid = np.zeros((T, S), bool)
    dn_ch = np.zeros((T, S), np.int32)
    dn_mb = np.zeros((T, S), np.int32)
    for t in range(1, T):
        for s in range(S):
            u = (s - 1) % S
            if op_np[t - 1, u] == 1:
                c = int(ch_np[t - 1, u])
                tc = c if s > 0 else c + 1
                if tc < V and (c * S + u) < G - 1:
                    up_valid[t, s] = True
                    up_ch[t, s] = tc
                    up_mb[t, s] = mb_np[t - 1, u]
            w = (s + 1) % S
            if op_np[t - 1, w] == 2:
                c = int(ch_np[t - 1, w])
                tc = c if s < S - 1 else c - 1
                if tc >= 0 and (c * S + w) > 0:
                    dn_valid[t, s] = True
                    dn_ch[t, s] = tc
                    dn_mb[t, s] = mb_np[t - 1, w]
    up_valid_t = jnp.asarray(up_valid)
    up_ch_t = jnp.asarray(up_ch)
    up_mb_t = jnp.asarray(up_mb)
    dn_valid_t = jnp.asarray(dn_valid)
    dn_ch_t = jnp.asarray(dn_ch)
    dn_mb_t = jnp.asarray(dn_mb)

    # probe boundary shape
    x0 = jax.eval_shape(
        first_fn, jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape[1:],
                                                              a.dtype),
                               chunk_params),
        jax.ShapeDtypeStruct(mb_inputs.shape[1:], mb_inputs.dtype))
    bshape, bdtype = x0.shape, x0.dtype
    y0 = jax.eval_shape(fn, jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), chunk_params),
        x0)
    if (y0.shape, y0.dtype) != (bshape, bdtype):
        raise ValueError(f"stage must preserve boundary: {x0} -> {y0}")

    B = min(M, G + 2)  # slots per chunk: in-flight per stage <= G+1
    zeros_b = lambda: jnp.zeros(bshape, bdtype)
    pslice = lambda c: jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
        chunk_params)
    grad_zero = jax.tree.map(
        lambda a: jnp.zeros(a.shape, jnp.promote_types(a.dtype, jnp.float32)
                            if jnp.issubdtype(a.dtype, jnp.floating)
                            else a.dtype),
        chunk_params)
    inv_m = 1.0 / M

    def _store2(buf, valid, c, m, payload):
        """buf[c, m % B] = payload where valid."""
        cur = lax.dynamic_slice(
            buf, (c, m % B) + (0,) * len(bshape), (1, 1) + bshape)
        new = jnp.where(valid, payload.reshape((1, 1) + bshape), cur)
        return lax.dynamic_update_slice(buf, new,
                                        (c, m % B) + (0,) * len(bshape))

    def _load2(buf, c, m):
        return lax.dynamic_slice(
            buf, (c, m % B) + (0,) * len(bshape),
            (1, 1) + bshape).reshape(bshape)

    def tick(carry, t):
        fwd_wire, bwd_wire, in_buf, cot_buf, grads, loss_acc = carry
        op = op_table[t, idx]
        c = ch_table[t, idx]
        m = mb_table[t, idx]

        in_buf = _store2(in_buf, up_valid_t[t, idx], up_ch_t[t, idx],
                         up_mb_t[t, idx], fwd_wire)
        cot_buf = _store2(cot_buf, dn_valid_t[t, idx], dn_ch_t[t, idx],
                          dn_mb_t[t, idx], bwd_wire)

        raw = lax.dynamic_index_in_dim(mb_inputs, m, 0, keepdims=False)
        lab = lax.dynamic_index_in_dim(mb_labels, m, 0, keepdims=False)
        x_saved = _load2(in_buf, c, m)
        g_recv = _load2(cot_buf, c, m)
        params_c = pslice(c)
        is_first = (idx == 0) & (c == 0)
        is_last = (idx == S - 1) & (c == V - 1)

        def thread_first(p, x):
            x_in = jnp.where(is_first, first_fn(p, raw), x)
            return fn(p, x_in)

        def pv(y, dx, gtree, l):
            return (_pvary_axes(y, act_axes), _pvary_axes(dx, act_axes),
                    jax.tree.map(lambda a: _pvary_axes(a, vaxes), gtree),
                    _pvary_axes(l, vaxes))

        def do_idle(_):
            return pv(zeros_b(), zeros_b(), jax.tree.map(
                lambda g: jnp.zeros_like(g), grad_zero), jnp.zeros(()))

        def do_fwd(_):
            y = thread_first(params_c, x_saved)
            return pv(y, zeros_b(), jax.tree.map(
                lambda g: jnp.zeros_like(g), grad_zero), jnp.zeros(()))

        def do_bwd(_):
            def run(loss_like):
                val, pull = jax.vjp(loss_like, params_c, x_saved)
                vma = _vma_of(val)
                seed = _pvary_axes(jnp.ones((), val.dtype),
                                   vma or (axis_name,))
                dp, dx = pull(seed)
                return val, dp, dx

            def last_branch(_):
                return run(lambda p, x: last_fn(p, thread_first(p, x), lab)
                           * inv_m)

            def mid_branch(_):
                return run(lambda p, x: jnp.sum(
                    thread_first(p, x).astype(jnp.float32)
                    * g_recv.astype(jnp.float32)))

            val, dp, dx = lax.cond(is_last, last_branch, mid_branch, None)
            loss_c = jnp.where(is_last, val, 0.0)
            # scatter this chunk's grads into the [V, ...] accumulator
            dpf = jax.tree.map(
                lambda d, z: lax.dynamic_update_index_in_dim(
                    jnp.zeros_like(z), d.astype(z.dtype), c, 0),
                dp, grad_zero)
            return pv(zeros_b(), dx.astype(bdtype), dpf,
                      loss_c.astype(jnp.float32).reshape(()))

        send_y, send_dx, dp, loss_c = lax.switch(
            jnp.clip(op, 0, 2), [do_idle, do_fwd, do_bwd], None)

        grads = jax.tree.map(lambda g, d: g + d, grads, dp)
        loss_acc = loss_acc + loss_c

        new_fwd = lax.ppermute(send_y, axis_name,
                               [(i, (i + 1) % S) for i in range(S)])
        new_bwd = lax.ppermute(send_dx, axis_name,
                               [(i, (i - 1) % S) for i in range(S)])
        return (new_fwd, new_bwd, in_buf, cot_buf, grads, loss_acc), None

    act_axes = _varying_axes(axis_name, mb_inputs, mb_labels)
    vaxes = _varying_axes(axis_name, chunk_params, mb_inputs, mb_labels)
    init = (_pvary_axes(zeros_b(), act_axes),
            _pvary_axes(zeros_b(), act_axes),
            _pvary_axes(jnp.zeros((V, B) + bshape, bdtype), act_axes),
            _pvary_axes(jnp.zeros((V, B) + bshape, bdtype), act_axes),
            jax.tree.map(lambda z: _pvary_axes(z, vaxes), grad_zero),
            _pvary_axes(jnp.zeros((), jnp.float32), vaxes))
    (_, _, _, _, grads, loss_acc), _ = lax.scan(tick, init, jnp.arange(T))
    loss = lax.psum(loss_acc, axis_name)
    grads = jax.tree.map(lambda g: g[None], grads)
    return loss, grads


# -- PP composed with dp/fsdp/tp: the 4-D training step ----------------------

def _spec_axis_pos(spec, axis):
    """Index of the array dim `axis` shards in a PartitionSpec, or None."""
    for i, e in enumerate(spec):
        if e == axis or (isinstance(e, tuple) and axis in e):
            return i
    return None


def _spec_axes(spec):
    out = set()
    for e in spec:
        if isinstance(e, tuple):
            out.update(a for a in e if a is not None)
        elif e is not None:
            out.add(e)
    return out


class PipelineTrainStep(_TrainStepBase):
    """Compiled hybrid-parallel training step: 1F1B pipeline over ``pp``,
    data parallelism over ``dp``, ZeRO-sharded data parallelism over
    ``fsdp``, tensor parallelism over ``tp`` — one mesh, ONE jitted
    program, matching the reference's 4-D hybrid topology
    ``["data", "pipe", "sharding", "model"]`` (fleet/base/topology.py:54).

    Reference role: PipelineParallel inside HybridParallelClipGrad/fleet
    (meta_parallel/pipeline_parallel.py + hybrid_parallel_optimizer.py +
    sharding/group_sharded) where pp/dp/sharding/mp process groups compose.
    Here the composition is a single fully-manual shard_map:

    * pp — the 1F1B tick scan runs over the pp axis.
    * dp + fsdp — each microbatch's SAMPLE axis is split over dp×fsdp;
      every data shard runs all M microbatches on its slice and grads are
      normalized back to the global-batch mean.
    * fsdp (ZeRO): param leaves whose spec names the fsdp axis are STORED
      sharded (so are their optimizer-state leaves — ZeRO-1 memory comes
      free from GSPMD on the update), all_gather'd over fsdp once at step
      entry (ZeRO-3 compute), and their grads reduce-scattered back.
    * tp — ``stage_fn`` is written Megatron-style against LOCAL tp shards
      (explicit lax.psum over the tp axis where its math requires it —
      same contract as mpu layers).

    Args:
      stage_fn/first_fn/last_fn: as :func:`pipeline_1f1b`, operating on
        local tp shards.
      stacked_params: dict name -> global [S, ...] stacked arrays.
      param_specs: dict name -> PartitionSpec with the leading pp axis and
        any fsdp/tp placements, e.g. P('pp', 'fsdp', 'tp').
      first_params/last_params (+ their specs): optional separate
        embed/head param dicts — NOT stacked, NOT pp-sharded (specs name
        only fsdp/tp axes), owned logically by stage 0 / stage S-1 (see
        :func:`pipeline_1f1b`).
      optimizer: a paddle_tpu optimizer (init_state_pytree/apply_gradients
        — grad clip and fp32 master weights ride along exactly as in
        ``jit.TrainStep``; pass ``compute_dtype='bfloat16'`` for AMP-O2).
      batch: step() takes {'inputs': [M, mb, ...], 'labels': [M, mb, ...]};
        the microbatch axis is split over dp×fsdp (× any extra_data_axes).
      extra_data_axes: additional mesh axes the batch is split over — pass
        ``('ep',)`` when the stage runs an all_to_all MoE, so the
        expert-parallel group doubles as a data-parallel group (the
        reference's dp×ep overlap); loss averaging and grad normalization
        account for them automatically.
    """

    def __init__(self, stage_fn, first_fn, last_fn, stacked_params,
                 optimizer, mesh, num_microbatches, param_specs, *,
                 pp_axis: str = "pp", dp_axis: Optional[str] = "dp",
                 fsdp_axis: Optional[str] = "fsdp", remat: bool = True,
                 first_params=None, first_specs=None,
                 last_params=None, last_specs=None, compute_dtype=None,
                 scatter_grads_per_tick: bool = False,
                 extra_data_axes=()):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self._pp = pp_axis
        self._dp = dp_axis if dp_axis in mesh.axis_names else None
        self._fsdp = fsdp_axis if (fsdp_axis and
                                   fsdp_axis in mesh.axis_names) else None
        data_axes = tuple(a for a in (self._dp, self._fsdp) if a)
        has_first = first_params is not None
        has_last = last_params is not None

        # one flat dict drives placement, donation, clip (global norm spans
        # stage+embed+head), optimizer update, and checkpointing
        flat, specs = {}, {}
        for n, a in stacked_params.items():
            flat[n] = a
            specs[n] = param_specs[n]
        for prefix, tree, tree_specs in (("first/", first_params,
                                          first_specs),
                                         ("last/", last_params,
                                          last_specs)):
            if tree is not None:
                for n, a in tree.items():
                    spec = (tree_specs or {}).get(n, P())
                    if pp_axis in _spec_axes(spec):
                        raise ValueError(
                            f"{prefix}{n}: embed/head params must not be "
                            f"pp-sharded (they are owned by one stage and "
                            f"replicated over pp); got {spec}")
                    flat[prefix + n] = a
                    specs[prefix + n] = spec
        if compute_dtype is not None:
            flat = {n: jnp.asarray(a).astype(compute_dtype)
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                    else a for n, a in flat.items()}
        self._specs = specs
        param_sh = {n: NamedSharding(mesh, specs[n]) for n in flat}
        self._init_step_state(optimizer, flat, param_sh)

        self._jitted = jax.jit(
            build_pipeline_step_fn(
                stage_fn, first_fn, last_fn, optimizer, mesh,
                num_microbatches, specs, pp_axis=pp_axis, dp_axis=self._dp,
                fsdp_axis=self._fsdp, remat=remat, has_first=has_first,
                has_last=has_last,
                scatter_grads_per_tick=scatter_grads_per_tick,
                extra_data_axes=extra_data_axes),
            donate_argnums=(0, 1, 2))

    def __call__(self, batch):
        mb_inputs = jnp.asarray(batch["inputs"])
        mb_labels = jnp.asarray(batch["labels"])
        return self._run_jitted(mb_inputs, mb_labels)


def build_pipeline_step_fn(stage_fn, first_fn, last_fn, optimizer, mesh,
                           num_microbatches, specs, *, pp_axis="pp",
                           dp_axis=None, fsdp_axis=None, remat=True,
                           has_first=False, has_last=False,
                           scatter_grads_per_tick=False,
                           extra_data_axes=()):
    """The pure 4-D training-step function behind ``PipelineTrainStep``:
    ``step(params, opt_state, step_count, mb_inputs, mb_labels, lr) ->
    (loss, params, opt_state, step_count)``.

    Factored out so callers that never materialize arrays (the capacity
    planner's abstract AOT lowering) compile the exact same program the
    real training step runs.  ``specs`` is the flat dict (stage names
    plus "first/"/"last/" prefixed group names) of PartitionSpecs; dp/fsdp
    axis names must already be filtered against the mesh (None = absent).
    """
    from jax.sharding import PartitionSpec as P

    manual = set(mesh.axis_names)
    fsdp = fsdp_axis
    # extra_data_axes: additional mesh axes the batch is split over (e.g.
    # 'ep' when the stage runs an all_to_all MoE — the expert-parallel
    # group doubles as a data-parallel group for the non-expert params,
    # exactly the reference's dp×ep overlap).  Treated like dp for loss
    # averaging and grad normalization; ep-SHARDED expert leaves come back
    # complete from the a2a transpose and need no extra reduction.
    data_axes = tuple(a for a in (dp_axis, fsdp_axis) if a) + \
        tuple(a for a in extra_data_axes if a in manual)

    def split(params):
        stage, first, last = {}, {}, {}
        for n, v in params.items():
            if n.startswith("first/"):
                first[n[6:]] = v
            elif n.startswith("last/"):
                last[n[5:]] = v
            else:
                stage[n] = v
        return (stage, first if has_first else None,
                last if has_last else None)

    def gather_tree(tree, prefix=""):
        # ZeRO-3: materialize full (per-stage) values of fsdp-sharded
        # leaves; the matching reduce-scatter runs on the grads below
        if tree is None or fsdp is None:
            return tree
        out = {}
        for n, v in tree.items():
            pos = _spec_axis_pos(specs[prefix + n], fsdp)
            out[n] = v if pos is None else lax.all_gather(
                v, fsdp, axis=pos, tiled=True)
        return out

    def scatter_tree(tree, prefix=""):
        if tree is None or fsdp is None:
            return tree
        out = {}
        for n, g in tree.items():
            pos = _spec_axis_pos(specs[prefix + n], fsdp)
            out[n] = g if pos is None else lax.psum_scatter(
                g, fsdp, scatter_dimension=pos, tiled=True)
        return out

    def reduce_leaf(g, spec, exclude=()):
        # vma cleanup: pmean over any axis the grad still varies on
        # but its out_spec omits (values already equal across them)
        present = _spec_axes(spec)
        vma = _vma_of(g)
        if vma is None:
            # jax 0.4.x: no vma tracking means no auto-inserted psum in
            # the vjp — grads of params invariant on an axis come back
            # as RAW per-device partial sums; reduce them explicitly
            # (the uniform 1/D scale above turns sums into means)
            for ax in manual - present - set(exclude):
                g = lax.psum(g, ax)
            return g
        for ax in manual - present - set(exclude):
            if ax in vma:
                g = lax.pmean(g, ax)
        return g

    per_tick = scatter_grads_per_tick and fsdp is not None

    def tick_reduce(tree):
        # keep the scan's grad accumulator ZeRO-sharded: reduce-scatter
        # each tick's contribution instead of accumulating full-size
        return scatter_tree(tree)

    def body(params, mb_inputs, mb_labels):
        stage_p, first_p, last_p = split(params)
        out = pipeline_1f1b(
            stage_fn, first_fn, last_fn, gather_tree(stage_p),
            mb_inputs, mb_labels,
            num_microbatches=num_microbatches, axis_name=pp_axis,
            remat=remat,
            first_params=gather_tree(first_p, "first/"),
            last_params=gather_tree(last_p, "last/"),
            stage_grad_reduce=tick_reduce if per_tick else None)
        if has_first or has_last:
            loss, (g_stage, g_first, g_last) = out
        else:
            loss, g_stage = out
            g_first = g_last = None

        # data semantics: each of the D = dp*fsdp data shards computed
        # the mean loss of ITS microbatch slice; the vjp transpose
        # already psum'd grads over axes the params are INVARIANT on
        # (dp always; fsdp for non-fsdp-sharded leaves), and the
        # reduce-scatter below sums the fsdp-sharded ones — so a
        # uniform 1/D turns every leaf into the global-batch mean.
        d_total = 1
        for ax in data_axes:
            d_total *= _axis_size(ax)
        scale = 1.0 / d_total
        norm = lambda tr: None if tr is None else jax.tree.map(
            lambda g: g * scale, tr)
        g_stage, g_first, g_last = norm(g_stage), norm(g_first), \
            norm(g_last)
        for ax in data_axes:
            loss = lax.pmean(loss, ax)
        vma_l = _vma_of(loss) or ()
        for ax in manual - set(data_axes):
            if ax in vma_l:  # e.g. tp: equal across shards, clean vma
                loss = lax.pmean(loss, ax)

        if not per_tick:  # already reduce-scattered inside the ticks
            g_stage = scatter_tree(g_stage)

        def group_reduce(tr, prefix):
            # group grads come back as per-device partial sums over
            # the data axes (their params were pvary'd — see
            # pipeline_1f1b); reduce them explicitly here, OUTSIDE any
            # divergent control flow: sum over dp, sum(+shard) over
            # fsdp.  tp shards hold equal values — reduce_leaf's
            # pmean cleans that vma up below.
            if tr is None:
                return None
            out = {}
            for n, g in tr.items():
                vma = _vma_of(g)
                for ax in data_axes:
                    # no vma tracking (0.4.x) → partials, always reduce
                    if ax != fsdp and (vma is None or ax in vma):
                        g = lax.psum(g, ax)
                if fsdp:
                    pos = _spec_axis_pos(specs[prefix + n], fsdp)
                    g = lax.psum(g, fsdp) if pos is None else \
                        lax.psum_scatter(g, fsdp,
                                         scatter_dimension=pos,
                                         tiled=True)
                out[n] = g
            return out

        g_first = group_reduce(g_first, "first/")
        g_last = group_reduce(g_last, "last/")

        merged = {n: reduce_leaf(g, specs[n], exclude=(pp_axis,))
                  for n, g in g_stage.items()}
        for prefix, tr in (("first/", g_first), ("last/", g_last)):
            if tr is not None:
                for n, g in tr.items():
                    if _vma_of(g) is None:
                        # 0.4.x (no vma tracking): pp (psum_tree inside
                        # pipeline_1f1b) and the data axes (group_reduce
                        # above) are ALREADY summed — a pessimistic psum
                        # there would double-count; what remains (e.g.
                        # tp) is still raw per-device vjp partials, and
                        # reduce_leaf's unconditional psum closes them
                        merged[prefix + n] = reduce_leaf(
                            g, specs[prefix + n],
                            exclude=(pp_axis,) + tuple(data_axes))
                    else:
                        merged[prefix + n] = reduce_leaf(
                            g, specs[prefix + n])
        return loss, merged

    from paddle_tpu.distributed.communication import shard_map

    batch_spec = P(None, data_axes) if data_axes else P()
    # grads ARE replicated over the data axes (group_reduce psums them)
    # but jax 0.4.x's static rep inference can't see through the
    # pipelined backward — legacy_check_rep only relaxes the old checker
    shmap = shard_map(
        body, mesh=mesh,
        in_specs=(dict(specs), batch_spec, batch_spec),
        out_specs=(P(), dict(specs)), legacy_check_rep=False)

    def step_impl(params, opt_state, step_count, mb_inputs, mb_labels,
                  lr):
        loss, grads = shmap(params, mb_inputs, mb_labels)
        step_count = step_count + 1
        new_params, new_state = optimizer.apply_gradients(
            params, grads, opt_state, step_count, lr=lr)
        return loss, new_params, new_state, step_count

    return step_impl
