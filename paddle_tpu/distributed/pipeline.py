"""Pipeline parallelism.

Reference parity: ``PipelineLayer``/``LayerDesc``/``SharedLayerDesc``
(fleet/meta_parallel/parallel_layers/pp_layers.py:240,56,76), segmentation
(``SegmentLayers`` pp_layers.py:92), the 1F1B runtime
(``PipelineParallel.forward_backward_pipeline``
meta_parallel/pipeline_parallel.py:188) and interleaved variant (:565,642),
P2P activations (pp_utils/p2p_communication.py).

TPU-native design: the reference runs one Python process per stage that
`send/recv`s activations over NCCL and hand-schedules
forward/backward interleaving.  Under single-controller SPMD the whole
schedule is ONE traced program: stage weights are stacked on a leading
[num_stages, ...] axis sharded over the ``pp`` mesh axis, and a
``lax.scan`` over schedule ticks moves activations between neighbouring
stages with ``lax.ppermute`` (XLA collective-permute — ICI point-to-point).
Because ppermute/scan are differentiable, ``jax.grad`` of the scanned loss
IS the pipelined backward — the compiler produces the reverse schedule that
the reference writes by hand, and rematerialisation (``jax.checkpoint`` on
the stage fn) gives the 1F1B-grade memory profile.

Scope note: the scanned schedule is GPipe-shaped (all forwards, then the
transposed backwards). 1F1B reorders the *runtime buffer lifetimes*, which
in the reference reduces live activations from O(M) to O(S); here the same
reduction comes from `remat='stage'` (save only stage boundaries, recompute
inside the backward scan), which is how praxis/maxtext express it on TPU.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.nn.layer import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer",
           "spmd_pipeline"]


class LayerDesc:
    """Deferred layer constructor (reference pp_layers.py:56)."""

    def __init__(self, layer_cls, *args, **kwargs):
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"{layer_cls} must be a Layer subclass")
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (reference
    pp_layers.py:76 — e.g. tied embedding/lm-head; the reference allreduces
    the shared grads across stages (:532); here the tied parameter is a
    single array the compiler sees twice, so its gradient contributions sum
    automatically)."""

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layer descs into S contiguous stages (reference
    pp_layers.py:92): 'uniform' by count or 'param' by parameter volume."""

    def __init__(self, layers: Sequence, num_parts: int,
                 method: str = "uniform"):
        self.layers = list(layers)
        self.num_parts = num_parts
        self.method = method
        if len(self.layers) < num_parts:
            raise ValueError(
                f"{len(self.layers)} layers < {num_parts} stages")

    def do_segment(self) -> List[int]:
        """Returns stage boundaries, len == num_parts+1."""
        n, s = len(self.layers), self.num_parts
        if self.method == "uniform":
            base, rem = divmod(n, s)
            sizes = [base + (1 if i < rem else 0) for i in range(s)]
        elif self.method.startswith("layer:"):
            # weight by occurrences of a named layer class (reference
            # supports 'layer:TransformerLayer')
            name = self.method.split(":", 1)[1]
            weights = [1 if getattr(d, "layer_cls", type(d)).__name__ == name
                       else 0 for d in self.layers]
            sizes = self._balance(weights, s)
        elif self.method == "param":
            weights = []
            for d in self.layers:
                layer = d.build_layer() if isinstance(d, LayerDesc) else d
                weights.append(sum(int(np.prod(p.shape))
                                   for p in layer.parameters()) or 1)
            sizes = self._balance(weights, s)
        else:
            raise ValueError(f"unknown segment method {self.method}")
        bounds = [0]
        for sz in sizes:
            bounds.append(bounds[-1] + sz)
        return bounds

    @staticmethod
    def _balance(weights: List[int], s: int) -> List[int]:
        """Greedy prefix split minimising max stage weight."""
        total = sum(weights)
        target = total / s
        sizes, acc, count = [], 0.0, 0
        remaining_parts = s
        for i, w in enumerate(weights):
            acc += w
            count += 1
            remaining = len(weights) - i - 1
            if (acc >= target and remaining_parts > 1
                    and remaining >= remaining_parts - 1):
                sizes.append(count)
                acc, count = 0.0, 0
                remaining_parts -= 1
        sizes.append(count)
        while len(sizes) < s:
            sizes.append(0)
        return sizes


class PipelineLayer(Layer):
    """Stage-segmented model container (reference pp_layers.py:240).

    Single-controller SPMD holds ALL stages' weights (each sharded to its
    stage's devices by the pp dim of the stacked arrays), so unlike the
    reference there is no per-rank construction: ``forward`` runs the full
    serial stack (parity/eval path), and ``stage_layers(i)`` exposes the
    per-stage slices for the spmd schedule.
    """

    def __init__(self, layers: Sequence, num_stages: int,
                 topology=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, name=None):
        super().__init__()
        self._descs = list(layers)
        self._num_stages = num_stages
        self.seg_method = seg_method
        self.recompute_interval = recompute_interval

        self.segment_bounds = SegmentLayers(
            self._descs, num_stages, seg_method).do_segment()

        from paddle_tpu.nn.common_layers import LayerList
        built: List[Layer] = []
        self._shared: dict = {}
        self._shared_fwd: dict = {}
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    # reuse the first instance's weights: same Layer object
                    built.append(self._shared[d.layer_name])
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                    built.append(layer)
                self._shared_fwd[len(built) - 1] = d.forward_func
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:
                raise TypeError(f"bad pipeline element {d!r}")
        self.run_function = LayerList(built)

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def get_num_virtual_stages(self) -> int:
        return 1

    def stage_layers(self, stage: int) -> List[Layer]:
        lo, hi = self.segment_bounds[stage], self.segment_bounds[stage + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x):
        for i, layer in enumerate(self.run_function):
            fwd = self._shared_fwd.get(i)
            x = fwd(layer, x) if fwd is not None else layer(x)
        return x


# -- the SPMD schedule -------------------------------------------------------

def spmd_pipeline(stage_fn: Callable, stage_params: Any, microbatches,
                  *, num_microbatches: int, axis_name: str = "pp",
                  remat: bool = True):
    """Run a homogeneous-stage pipeline INSIDE an enclosing shard_map.

    Args:
      stage_fn: ``(params_for_this_stage, x) -> y`` — one stage's compute.
        Same jaxpr on every device (SPMD); per-stage behaviour comes from
        the params.
      stage_params: this device's slice of the stacked [S, ...] params
        (shard_map has already split the leading axis).
      microbatches: ``[M, mb, ...]`` array of all microbatch inputs,
        replicated over the pp axis.
      num_microbatches: M (static).
      remat: jax.checkpoint the stage fn — recompute stage interiors in
        the backward pass, keeping only boundary activations live (the
        memory behaviour 1F1B buys in the reference).

    Returns ``[M, mb, ...]`` outputs, valid on the LAST stage (other
    stages hold zeros); combine with a ``where(axis_index==S-1, ...)``
    psum or an out_spec that keeps the pp axis.

    Schedule: T = M + S - 1 ticks.  At tick t stage s computes microbatch
    ``t - s`` (when in range) — the classic GPipe wavefront; ppermute
    rotates boundary activations one hop per tick over ICI.
    """
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = num_microbatches
    mb_shape = microbatches.shape[1:]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # probe output shape: stages must be shape-preserving on the boundary
    out_shape = jax.eval_shape(fn, stage_params,
                               jax.ShapeDtypeStruct(
                                   mb_shape, microbatches.dtype))
    if (out_shape.shape, out_shape.dtype) != (mb_shape, microbatches.dtype):
        raise ValueError(
            "spmd_pipeline requires shape-preserving stages; got "
            f"{mb_shape}->{out_shape.shape}")

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t (clamped; masked out when t >= M)
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, inject, recv)
        y = fn(stage_params, x)
        # rotate boundary activation to the next stage (ring; the wrap
        # last->first carries garbage that stage 0 ignores via `where`)
        new_recv = lax.ppermute(y, axis_name,
                                [(i, (i + 1) % S) for i in range(S)])
        # last stage records microbatch t-(S-1)
        m = t - (S - 1)
        write = (idx == S - 1) & (m >= 0) & (m < M)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(write, y,
                      lax.dynamic_index_in_dim(outputs, jnp.clip(m, 0, M - 1),
                                               axis=0, keepdims=False)),
            jnp.clip(m, 0, M - 1), axis=0)
        return (new_recv, outputs), None

    # the carry becomes device-varying after ppermute; mark the zero init
    # as varying too so shard_map's vma check accepts the scan
    from paddle_tpu.distributed.communication import pvary
    init = (pvary(jnp.zeros(mb_shape, microbatches.dtype), axis_name),
            pvary(jnp.zeros((M,) + mb_shape, microbatches.dtype),
                  axis_name))
    (recv, outputs), _ = lax.scan(tick, init, jnp.arange(M + S - 1))
    return outputs


def stack_stage_params(per_stage_params: List[Any]):
    """[pytree per stage] -> stacked pytree with leading S axis (to be
    sharded P('pp', ...)).  Stages must be homogeneous."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
