"""Pipeline parallelism.

Reference parity: ``PipelineLayer``/``LayerDesc``/``SharedLayerDesc``
(fleet/meta_parallel/parallel_layers/pp_layers.py:240,56,76), segmentation
(``SegmentLayers`` pp_layers.py:92), the 1F1B runtime
(``PipelineParallel.forward_backward_pipeline``
meta_parallel/pipeline_parallel.py:188) and interleaved variant (:565,642),
P2P activations (pp_utils/p2p_communication.py).

TPU-native design: the reference runs one Python process per stage that
`send/recv`s activations over NCCL and hand-schedules
forward/backward interleaving.  Under single-controller SPMD the whole
schedule is ONE traced program: stage weights are stacked on a leading
[num_stages, ...] axis sharded over the ``pp`` mesh axis, and a
``lax.scan`` over schedule ticks moves activations between neighbouring
stages with ``lax.ppermute`` (XLA collective-permute — ICI point-to-point).
Because ppermute/scan are differentiable, ``jax.grad`` of the scanned loss
IS the pipelined backward — the compiler produces the reverse schedule that
the reference writes by hand, and rematerialisation (``jax.checkpoint`` on
the stage fn) gives the 1F1B-grade memory profile.

Scope note: the scanned schedule is GPipe-shaped (all forwards, then the
transposed backwards). 1F1B reorders the *runtime buffer lifetimes*, which
in the reference reduces live activations from O(M) to O(S); here the same
reduction comes from `remat='stage'` (save only stage boundaries, recompute
inside the backward scan), which is how praxis/maxtext express it on TPU.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.nn.layer import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer",
           "spmd_pipeline", "build_1f1b_schedule", "pipeline_1f1b",
           "build_interleaved_schedule", "pipeline_interleaved",
           "PipelineTrainStep"]


class LayerDesc:
    """Deferred layer constructor (reference pp_layers.py:56)."""

    def __init__(self, layer_cls, *args, **kwargs):
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"{layer_cls} must be a Layer subclass")
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (reference
    pp_layers.py:76 — e.g. tied embedding/lm-head; the reference allreduces
    the shared grads across stages (:532); here the tied parameter is a
    single array the compiler sees twice, so its gradient contributions sum
    automatically)."""

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layer descs into S contiguous stages (reference
    pp_layers.py:92): 'uniform' by count or 'param' by parameter volume."""

    def __init__(self, layers: Sequence, num_parts: int,
                 method: str = "uniform"):
        self.layers = list(layers)
        self.num_parts = num_parts
        self.method = method
        if len(self.layers) < num_parts:
            raise ValueError(
                f"{len(self.layers)} layers < {num_parts} stages")

    def do_segment(self) -> List[int]:
        """Returns stage boundaries, len == num_parts+1."""
        n, s = len(self.layers), self.num_parts
        if self.method == "uniform":
            base, rem = divmod(n, s)
            sizes = [base + (1 if i < rem else 0) for i in range(s)]
        elif self.method.startswith("layer:"):
            # weight by occurrences of a named layer class (reference
            # supports 'layer:TransformerLayer')
            name = self.method.split(":", 1)[1]
            weights = [1 if getattr(d, "layer_cls", type(d)).__name__ == name
                       else 0 for d in self.layers]
            sizes = self._balance(weights, s)
        elif self.method == "param":
            weights = []
            for d in self.layers:
                layer = d.build_layer() if isinstance(d, LayerDesc) else d
                weights.append(sum(int(np.prod(p.shape))
                                   for p in layer.parameters()) or 1)
            sizes = self._balance(weights, s)
        else:
            raise ValueError(f"unknown segment method {self.method}")
        bounds = [0]
        for sz in sizes:
            bounds.append(bounds[-1] + sz)
        return bounds

    @staticmethod
    def _balance(weights: List[int], s: int) -> List[int]:
        """Greedy prefix split minimising max stage weight."""
        total = sum(weights)
        target = total / s
        sizes, acc, count = [], 0.0, 0
        remaining_parts = s
        for i, w in enumerate(weights):
            acc += w
            count += 1
            remaining = len(weights) - i - 1
            if (acc >= target and remaining_parts > 1
                    and remaining >= remaining_parts - 1):
                sizes.append(count)
                acc, count = 0.0, 0
                remaining_parts -= 1
        sizes.append(count)
        while len(sizes) < s:
            sizes.append(0)
        return sizes


class PipelineLayer(Layer):
    """Stage-segmented model container (reference pp_layers.py:240).

    Single-controller SPMD holds ALL stages' weights (each sharded to its
    stage's devices by the pp dim of the stacked arrays), so unlike the
    reference there is no per-rank construction: ``forward`` runs the full
    serial stack (parity/eval path), and ``stage_layers(i)`` exposes the
    per-stage slices for the spmd schedule.
    """

    def __init__(self, layers: Sequence, num_stages: int,
                 topology=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, name=None):
        super().__init__()
        self._descs = list(layers)
        self._num_stages = num_stages
        self.seg_method = seg_method
        self.recompute_interval = recompute_interval

        self.segment_bounds = SegmentLayers(
            self._descs, num_stages, seg_method).do_segment()

        from paddle_tpu.nn.common_layers import LayerList
        built: List[Layer] = []
        self._shared: dict = {}
        self._shared_fwd: dict = {}
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    # reuse the first instance's weights: same Layer object
                    built.append(self._shared[d.layer_name])
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                    built.append(layer)
                self._shared_fwd[len(built) - 1] = d.forward_func
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:
                raise TypeError(f"bad pipeline element {d!r}")
        self.run_function = LayerList(built)

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def get_num_virtual_stages(self) -> int:
        return 1

    def stage_layers(self, stage: int) -> List[Layer]:
        lo, hi = self.segment_bounds[stage], self.segment_bounds[stage + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x):
        for i, layer in enumerate(self.run_function):
            fwd = self._shared_fwd.get(i)
            x = fwd(layer, x) if fwd is not None else layer(x)
        return x


# -- the SPMD schedule -------------------------------------------------------

def spmd_pipeline(stage_fn: Callable, stage_params: Any, microbatches,
                  *, num_microbatches: int, axis_name: str = "pp",
                  remat: bool = True):
    """Run a homogeneous-stage pipeline INSIDE an enclosing shard_map.

    Args:
      stage_fn: ``(params_for_this_stage, x) -> y`` — one stage's compute.
        Same jaxpr on every device (SPMD); per-stage behaviour comes from
        the params.
      stage_params: this device's slice of the stacked [S, ...] params
        (shard_map has already split the leading axis).
      microbatches: ``[M, mb, ...]`` array of all microbatch inputs,
        replicated over the pp axis.
      num_microbatches: M (static).
      remat: jax.checkpoint the stage fn — recompute stage interiors in
        the backward pass, keeping only boundary activations live (the
        memory behaviour 1F1B buys in the reference).

    Returns ``[M, mb, ...]`` outputs, valid on the LAST stage (other
    stages hold zeros); combine with a ``where(axis_index==S-1, ...)``
    psum or an out_spec that keeps the pp axis.

    Schedule: T = M + S - 1 ticks.  At tick t stage s computes microbatch
    ``t - s`` (when in range) — the classic GPipe wavefront; ppermute
    rotates boundary activations one hop per tick over ICI.
    """
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = num_microbatches
    mb_shape = microbatches.shape[1:]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # probe output shape: stages must be shape-preserving on the boundary
    out_shape = jax.eval_shape(fn, stage_params,
                               jax.ShapeDtypeStruct(
                                   mb_shape, microbatches.dtype))
    if (out_shape.shape, out_shape.dtype) != (mb_shape, microbatches.dtype):
        raise ValueError(
            "spmd_pipeline requires shape-preserving stages; got "
            f"{mb_shape}->{out_shape.shape}")

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t (clamped; masked out when t >= M)
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, inject, recv)
        y = fn(stage_params, x)
        # rotate boundary activation to the next stage (ring; the wrap
        # last->first carries garbage that stage 0 ignores via `where`)
        new_recv = lax.ppermute(y, axis_name,
                                [(i, (i + 1) % S) for i in range(S)])
        # last stage records microbatch t-(S-1)
        m = t - (S - 1)
        write = (idx == S - 1) & (m >= 0) & (m < M)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(write, y,
                      lax.dynamic_index_in_dim(outputs, jnp.clip(m, 0, M - 1),
                                               axis=0, keepdims=False)),
            jnp.clip(m, 0, M - 1), axis=0)
        return (new_recv, outputs), None

    # the carry becomes device-varying after ppermute; mark the zero init
    # as varying too so shard_map's vma check accepts the scan
    from paddle_tpu.distributed.communication import pvary
    init = (pvary(jnp.zeros(mb_shape, microbatches.dtype), axis_name),
            pvary(jnp.zeros((M,) + mb_shape, microbatches.dtype),
                  axis_name))
    (recv, outputs), _ = lax.scan(tick, init, jnp.arange(M + S - 1))
    return outputs


def stack_stage_params(per_stage_params: List[Any]):
    """[pytree per stage] -> stacked pytree with leading S axis (to be
    sharded P('pp', ...)).  Stages must be homogeneous."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


# -- 1F1B: the explicit fused forward/backward schedule ----------------------

def build_1f1b_schedule(num_stages: int, num_microbatches: int):
    """Static [T, S] op/microbatch tables for the 1F1B schedule (reference
    PipelineParallel.forward_backward_pipeline, pipeline_parallel.py:188).

    Discrete-event simulation on the host (trace-time constant): each stage
    does warmup = S-1-s forwards, then strictly alternates backward/forward
    (the "one forward, one backward" steady state), then drains.  Arrival
    constraints (activation from upstream, cotangent from downstream, one
    hop per tick) are enforced by readiness sets, so the table is valid by
    construction.

    Returns (op[T,S], mb[T,S]) int32 numpy arrays; op: 0 idle, 1 fwd, 2 bwd.
    The max number of in-flight microbatches at stage s is S-s (<= S), which
    bounds the activation buffer — the memory property 1F1B exists for.
    """
    S, M = num_stages, num_microbatches
    fwd_ready = [set() for _ in range(S)]   # microbatches whose input arrived
    bwd_ready = [set() for _ in range(S)]   # cotangent arrived
    fwd_ready[0] = set(range(M))            # stage 0 owns all inputs
    fwd_done = [0] * S
    bwd_done = [0] * S
    ops, mbs = [], []
    guard = 0
    while any(b < M for b in bwd_done):
        guard += 1
        if guard > 4 * (M + S) + 16:
            raise RuntimeError("1f1b schedule did not converge")
        row_op = [0] * S
        row_mb = [0] * S
        events = []  # (stage, kind, m) applied after the tick
        for s in range(S):
            warmup = min(S - 1 - s, M)
            # next microbatch in order for each direction
            fm, bm = fwd_done[s], bwd_done[s]
            can_fwd = fm < M and fm in fwd_ready[s]
            can_bwd = bm < fwd_done[s] and bm in bwd_ready[s]
            prefer_bwd = fwd_done[s] >= warmup
            do_bwd = can_bwd and (prefer_bwd or not can_fwd)
            do_fwd = (not do_bwd) and can_fwd and \
                (fwd_done[s] - bwd_done[s]) <= warmup
            if do_bwd:
                row_op[s], row_mb[s] = 2, bm
                bwd_done[s] += 1
                if s > 0:
                    events.append((s - 1, "bwd", bm))
            elif do_fwd:
                row_op[s], row_mb[s] = 1, fm
                fwd_done[s] += 1
                if s < S - 1:
                    events.append((s + 1, "fwd", fm))
                else:
                    # last stage: its own cotangent is ready immediately
                    events.append((s, "bwd", fm))
        for s, kind, m in events:
            (fwd_ready if kind == "fwd" else bwd_ready)[s].add(m)
        ops.append(row_op)
        mbs.append(row_mb)
    return (np.asarray(ops, np.int32), np.asarray(mbs, np.int32))



def _varying_axes(axis_name, *trees):
    """Union of manual axes any leaf varies over, plus the pipeline axis —
    under a multi-axis mesh (pp x dp x tp) compute mixes them all, so every
    branch output / scan carry is marked varying over the full set."""
    axes = {axis_name}
    for v in jax.tree.leaves(trees):
        vma = getattr(jax.typeof(v), "vma", None)
        if vma:
            axes |= set(vma)
    return tuple(sorted(axes))


def _pvary_axes(x, axes):
    from paddle_tpu.distributed.communication import pvary
    for ax in axes:
        x = pvary(x, ax)
    return x


def pipeline_1f1b(stage_fn: Callable, first_fn: Callable, last_fn: Callable,
                  stage_params: Any, mb_inputs, mb_labels, *,
                  num_microbatches: int, axis_name: str = "pp",
                  remat: bool = True):
    """Fused forward+backward 1F1B pipeline step INSIDE a shard_map.

    The reference hand-schedules 1F1B across NCCL ranks
    (pipeline_parallel.py:188 warmup/steady/cooldown, p2p_communication.py);
    here the whole schedule is ONE lax.scan over ticks: every tick each
    stage consults the static schedule table and either forwards a
    microbatch, backwards one (recomputing its stage from the saved
    boundary input — the reference's recompute-interval memory trick, so
    only O(S) boundary activations are ever live), or idles.  Boundary
    activations ppermute forward, cotangents ppermute backward, parameter
    gradients accumulate in the carry.

    Args:
      stage_fn:  (params, x[mb, ...]) -> y[mb, ...] — the stage's block
        stack; boundary shape-preserving.
      first_fn:  (params, raw_mb) -> x — input embedding, applied only on
        stage 0 (raw microbatch may be int ids; its params live in stage
        0's param slice).
      last_fn:   (params, y, labels_mb) -> scalar loss — head + loss,
        applied only on the last stage.
      stage_params: this device's stage param slice (shard_map already
        split the stacked [S, ...] axis).  To keep SPMD homogeneous, every
        stage's slice has the same structure — embed/head slots exist on
        every stage and are zeros except where used.
      mb_inputs: [M, mb, ...] raw microbatch inputs (replicated on pp).
      mb_labels: [M, mb, ...] labels (replicated on pp).

    Returns (mean_loss, stage_param_grads) — loss is valid on the last
    stage (psum'd over pp so every stage sees it), grads are per-stage.
    """
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = num_microbatches
    from paddle_tpu.distributed.communication import pvary

    op_np, mb_np = build_1f1b_schedule(S, M)
    op_table = jnp.asarray(op_np)    # [T, S]
    mb_table = jnp.asarray(mb_np)
    T = op_np.shape[0]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # probe boundary shape
    x0 = jax.eval_shape(
        first_fn, stage_params,
        jax.ShapeDtypeStruct(mb_inputs.shape[1:], mb_inputs.dtype))
    y0 = jax.eval_shape(fn, stage_params, x0)
    if (y0.shape, y0.dtype) != (x0.shape, x0.dtype):
        raise ValueError(f"stage must preserve boundary: {x0} -> {y0}")
    bshape, bdtype = y0.shape, y0.dtype

    zeros_b = lambda: jnp.zeros(bshape, bdtype)
    grad_zero = jax.tree.map(
        lambda a: jnp.zeros(a.shape, jnp.promote_types(a.dtype, jnp.float32)
                            if jnp.issubdtype(a.dtype, jnp.floating)
                            else a.dtype),
        stage_params)

    inv_m = 1.0 / M

    # Sender-side static info lets the receiver decide whether this tick's
    # incoming wire payloads are real: what my upstream (idx-1) / downstream
    # (idx+1) neighbour did LAST tick, from the same static table.
    # up_op[t, s] = op of stage s-1 at tick t-1; down_op likewise.
    up_op = np.zeros_like(op_np)
    up_mb = np.zeros_like(mb_np)
    down_op = np.zeros_like(op_np)
    down_mb = np.zeros_like(mb_np)
    up_op[1:, 1:] = op_np[:-1, :-1]
    up_mb[1:, 1:] = mb_np[:-1, :-1]
    down_op[1:, :-1] = op_np[:-1, 1:]
    down_mb[1:, :-1] = mb_np[:-1, 1:]
    up_op_t = jnp.asarray(up_op)
    up_mb_t = jnp.asarray(up_mb)
    down_op_t = jnp.asarray(down_op)
    down_mb_t = jnp.asarray(down_mb)

    def _store(buf, valid, m, payload):
        """buf[m % S] = payload where valid (else unchanged)."""
        slot = m % S
        cur = lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            buf, jnp.where(valid, payload, cur), slot, 0)

    def tick(carry, t):
        fwd_wire, bwd_wire, in_buf, cot_buf, grads, loss_acc = carry
        op = op_table[t, idx]
        m = mb_table[t, idx]

        # 1) bank incoming wire payloads (schedule allows consuming them
        #    ticks later, so they must survive subsequent rotations)
        in_buf = _store(in_buf, up_op_t[t, idx] == 1, up_mb_t[t, idx],
                        fwd_wire)
        cot_buf = _store(cot_buf, down_op_t[t, idx] == 2, down_mb_t[t, idx],
                         bwd_wire)

        raw = lax.dynamic_index_in_dim(mb_inputs, m, 0, keepdims=False)
        lab = lax.dynamic_index_in_dim(mb_labels, m, 0, keepdims=False)
        x_saved = lax.dynamic_index_in_dim(in_buf, m % S, 0, keepdims=False)
        g_recv = lax.dynamic_index_in_dim(cot_buf, m % S, 0, keepdims=False)

        def thread_first(p, x):
            # embed path on stage 0 only; `where` keeps the jaxpr uniform
            # across stages, grads flow to embed params only where idx==0
            x_in = jnp.where(idx == 0, first_fn(p, raw), x)
            return fn(p, x_in)

        # 2) compute — switch so idle ticks cost nothing and fwd ticks
        #    don't pay the vjp.  Every branch output is pvary'd so the
        #    branches agree on varying-manual-axes types.
        def pv(y, dx, gtree, l):
            return (_pvary_axes(y, act_axes), _pvary_axes(dx, act_axes),
                    jax.tree.map(lambda a: _pvary_axes(a, vaxes), gtree),
                    _pvary_axes(l, vaxes))

        def do_idle(_):
            return pv(zeros_b(), zeros_b(), jax.tree.map(
                lambda g: jnp.zeros_like(g), grad_zero), jnp.zeros(()))

        def do_fwd(_):
            y = thread_first(stage_params, x_saved)
            return pv(y, zeros_b(), jax.tree.map(
                lambda g: jnp.zeros_like(g), grad_zero), jnp.zeros(()))

        def do_bwd(_):
            def run(loss_like):
                from paddle_tpu.distributed.communication import pvary
                val, pull = jax.vjp(loss_like, stage_params, x_saved)
                # the seed's varying-axes set must match val's (under a
                # multi-axis mesh the loss also varies over dp/tp axes)
                vma = getattr(jax.typeof(val), "vma", None)
                seed = _pvary_axes(jnp.ones((), val.dtype),
                                   vma or (axis_name,))
                dp, dx = pull(seed)
                return val, dp, dx

            def last_branch(_):
                return run(lambda p, x: last_fn(p, thread_first(p, x), lab)
                           * inv_m)

            def mid_branch(_):
                return run(lambda p, x: jnp.sum(
                    thread_first(p, x).astype(jnp.float32)
                    * g_recv.astype(jnp.float32)))

            val, dp, dx = lax.cond(idx == S - 1, last_branch, mid_branch,
                                   None)
            loss_c = jnp.where(idx == S - 1, val, 0.0)
            dpf = jax.tree.map(lambda d, z: d.astype(z.dtype), dp, grad_zero)
            return pv(zeros_b(), dx.astype(bdtype), dpf,
                      loss_c.astype(jnp.float32).reshape(()))

        send_y, send_dx, dp, loss_c = lax.switch(
            jnp.clip(op, 0, 2), [do_idle, do_fwd, do_bwd], None)

        grads = jax.tree.map(lambda g, d: g + d, grads, dp)
        loss_acc = loss_acc + loss_c

        # 3) rotate: activations forward, cotangents backward (ring; the
        #    wrap edges carry garbage that validity gating ignores)
        new_fwd = lax.ppermute(send_y, axis_name,
                               [(i, (i + 1) % S) for i in range(S)])
        new_bwd = lax.ppermute(send_dx, axis_name,
                               [(i, (i - 1) % S) for i in range(S)])
        return (new_fwd, new_bwd, in_buf, cot_buf, grads, loss_acc), None

    # activations only vary over the pipeline axis and whatever the batch is
    # sharded on (e.g. dp) — marking them varying over tp too would insert a
    # spurious psum in the transpose, double-counting every gradient
    act_axes = _varying_axes(axis_name, mb_inputs, mb_labels)
    vaxes = _varying_axes(axis_name, stage_params, mb_inputs, mb_labels)
    init = (_pvary_axes(zeros_b(), act_axes),
            _pvary_axes(zeros_b(), act_axes),
            _pvary_axes(jnp.zeros((S,) + bshape, bdtype), act_axes),
            _pvary_axes(jnp.zeros((S,) + bshape, bdtype), act_axes),
            jax.tree.map(lambda z: _pvary_axes(z, vaxes), grad_zero),
            _pvary_axes(jnp.zeros((), jnp.float32), vaxes))
    (_, _, _, _, grads, loss_acc), _ = lax.scan(tick, init, jnp.arange(T))

    # every stage reports the (last-stage-only) loss
    loss = lax.psum(loss_acc, axis_name)
    return loss, grads


# -- interleaved virtual stages ----------------------------------------------

def build_interleaved_schedule(num_stages: int, num_chunks: int,
                               num_microbatches: int):
    """Static schedule for interleaved virtual stages (reference
    PipelineParallel._forward_backward_pipeline with virtual_pp_degree,
    pipeline_parallel.py:565,642; PipelineLayerChunk pp_layers.py:214).

    Device s holds chunks c=0..V-1; chunk c on device s is GLOBAL stage
    g = c*S + s (the reference's interleaved layout: consecutive model
    slices round-robin over devices).  Discrete-event simulation: one op
    per device per tick, backward preferred once warmup completes, with
    the same arrival constraints as 1F1B (one hop per tick both ways).

    Returns (op[T,S], chunk[T,S], mb[T,S]); op: 0 idle, 1 fwd, 2 bwd.
    """
    S, V, M = num_stages, num_chunks, num_microbatches
    G = S * V
    dev = lambda g: g % S
    fwd_ready = [set() for _ in range(G)]
    bwd_ready = [set() for _ in range(G)]
    fwd_ready[0] = set(range(M))
    fwd_done = [0] * G
    bwd_done = [0] * G
    ops, chunks, mbs = [], [], []
    guard = 0
    while any(b < M for b in bwd_done):
        guard += 1
        if guard > 8 * (M * V + G) + 16:
            raise RuntimeError("interleaved schedule did not converge")
        row_op = [0] * S
        row_ch = [0] * S
        row_mb = [0] * S
        events = []
        for s in range(S):
            # candidate ops among this device's chunks, deepest global
            # stage first so drains happen promptly
            pick = None
            for c in reversed(range(V)):
                g = c * S + s
                bm = bwd_done[g]
                if bm < fwd_done[g] and bm in bwd_ready[g]:
                    pick = (2, c, bm)
                    break
            if pick is None:
                # forward: lowest chunk whose next microbatch arrived and
                # whose in-flight count stays within the warmup bound
                for c in range(V):
                    g = c * S + s
                    fm = fwd_done[g]
                    warmup = min(G - 1 - g, M)
                    if fm < M and fm in fwd_ready[g] and \
                            (fwd_done[g] - bwd_done[g]) <= warmup:
                        pick = (1, c, fm)
                        break
            if pick is None:
                continue
            kind, c, m = pick
            g = c * S + s
            row_op[s], row_ch[s], row_mb[s] = kind, c, m
            if kind == 1:
                fwd_done[g] += 1
                if g < G - 1:
                    events.append((g + 1, "fwd", m))
                else:
                    events.append((g, "bwd", m))
            else:
                bwd_done[g] += 1
                if g > 0:
                    events.append((g - 1, "bwd", m))
        for g, kind, m in events:
            (fwd_ready if kind == "fwd" else bwd_ready)[g].add(m)
        ops.append(row_op)
        chunks.append(row_ch)
        mbs.append(row_mb)
    return (np.asarray(ops, np.int32), np.asarray(chunks, np.int32),
            np.asarray(mbs, np.int32))


def pipeline_interleaved(stage_fn: Callable, first_fn: Callable,
                         last_fn: Callable, chunk_params: Any,
                         mb_inputs, mb_labels, *, num_microbatches: int,
                         num_chunks: int, axis_name: str = "pp",
                         remat: bool = True):
    """Interleaved-virtual-stage fused fwd+bwd pipeline INSIDE shard_map.

    chunk_params: this device's [V, ...] chunk param stack (the global
    stack is [S, V, ...], shard_map split axis 0; element [s][c] serves
    global stage c*S + s).  Contract otherwise as :func:`pipeline_1f1b`.

    Wire routing differs from plain 1F1B in that the ring wrap is REAL:
    a forward boundary leaving device S-1 (chunk c) lands on device 0
    as the input of chunk c+1, and symmetrically for cotangents — the
    banking tables below encode exactly which (chunk, mb) each tick's
    incoming payload belongs to.
    """
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = num_microbatches
    V = num_chunks
    G = S * V
    from paddle_tpu.distributed.communication import pvary

    # contract: called inside shard_map with the [S, V, ...] global stack
    # split by in_specs=P('pp'), so every local leaf arrives as [1, V, ...];
    # normalise to [V, ...] here and restore the leading pp axis on the
    # returned grads so out_specs=P('pp') reassembles the global stack
    for a in jax.tree.leaves(chunk_params):
        if a.ndim < 2 or a.shape[0] != 1 or a.shape[1] != V:
            raise ValueError(
                "pipeline_interleaved expects chunk_params leaves shaped "
                f"[1, V={V}, ...] (the shard_map-split [S, V, ...] stack); "
                f"got {a.shape}")
    chunk_params = jax.tree.map(lambda a: a.reshape(a.shape[1:]),
                                chunk_params)

    op_np, ch_np, mb_np = build_interleaved_schedule(S, V, M)
    T = op_np.shape[0]
    op_table = jnp.asarray(op_np)
    ch_table = jnp.asarray(ch_np)
    mb_table = jnp.asarray(mb_np)

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # host-side banking tables: validity + (chunk, mb) of each incoming wire
    up_valid = np.zeros((T, S), bool)
    up_ch = np.zeros((T, S), np.int32)
    up_mb = np.zeros((T, S), np.int32)
    dn_valid = np.zeros((T, S), bool)
    dn_ch = np.zeros((T, S), np.int32)
    dn_mb = np.zeros((T, S), np.int32)
    for t in range(1, T):
        for s in range(S):
            u = (s - 1) % S
            if op_np[t - 1, u] == 1:
                c = int(ch_np[t - 1, u])
                tc = c if s > 0 else c + 1
                if tc < V and (c * S + u) < G - 1:
                    up_valid[t, s] = True
                    up_ch[t, s] = tc
                    up_mb[t, s] = mb_np[t - 1, u]
            w = (s + 1) % S
            if op_np[t - 1, w] == 2:
                c = int(ch_np[t - 1, w])
                tc = c if s < S - 1 else c - 1
                if tc >= 0 and (c * S + w) > 0:
                    dn_valid[t, s] = True
                    dn_ch[t, s] = tc
                    dn_mb[t, s] = mb_np[t - 1, w]
    up_valid_t = jnp.asarray(up_valid)
    up_ch_t = jnp.asarray(up_ch)
    up_mb_t = jnp.asarray(up_mb)
    dn_valid_t = jnp.asarray(dn_valid)
    dn_ch_t = jnp.asarray(dn_ch)
    dn_mb_t = jnp.asarray(dn_mb)

    # probe boundary shape
    x0 = jax.eval_shape(
        first_fn, jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape[1:],
                                                              a.dtype),
                               chunk_params),
        jax.ShapeDtypeStruct(mb_inputs.shape[1:], mb_inputs.dtype))
    bshape, bdtype = x0.shape, x0.dtype
    y0 = jax.eval_shape(fn, jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), chunk_params),
        x0)
    if (y0.shape, y0.dtype) != (bshape, bdtype):
        raise ValueError(f"stage must preserve boundary: {x0} -> {y0}")

    B = min(M, G + 2)  # slots per chunk: in-flight per stage <= G+1
    zeros_b = lambda: jnp.zeros(bshape, bdtype)
    pslice = lambda c: jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
        chunk_params)
    grad_zero = jax.tree.map(
        lambda a: jnp.zeros(a.shape, jnp.promote_types(a.dtype, jnp.float32)
                            if jnp.issubdtype(a.dtype, jnp.floating)
                            else a.dtype),
        chunk_params)
    inv_m = 1.0 / M

    def _store2(buf, valid, c, m, payload):
        """buf[c, m % B] = payload where valid."""
        cur = lax.dynamic_slice(
            buf, (c, m % B) + (0,) * len(bshape), (1, 1) + bshape)
        new = jnp.where(valid, payload.reshape((1, 1) + bshape), cur)
        return lax.dynamic_update_slice(buf, new,
                                        (c, m % B) + (0,) * len(bshape))

    def _load2(buf, c, m):
        return lax.dynamic_slice(
            buf, (c, m % B) + (0,) * len(bshape),
            (1, 1) + bshape).reshape(bshape)

    def tick(carry, t):
        fwd_wire, bwd_wire, in_buf, cot_buf, grads, loss_acc = carry
        op = op_table[t, idx]
        c = ch_table[t, idx]
        m = mb_table[t, idx]

        in_buf = _store2(in_buf, up_valid_t[t, idx], up_ch_t[t, idx],
                         up_mb_t[t, idx], fwd_wire)
        cot_buf = _store2(cot_buf, dn_valid_t[t, idx], dn_ch_t[t, idx],
                          dn_mb_t[t, idx], bwd_wire)

        raw = lax.dynamic_index_in_dim(mb_inputs, m, 0, keepdims=False)
        lab = lax.dynamic_index_in_dim(mb_labels, m, 0, keepdims=False)
        x_saved = _load2(in_buf, c, m)
        g_recv = _load2(cot_buf, c, m)
        params_c = pslice(c)
        is_first = (idx == 0) & (c == 0)
        is_last = (idx == S - 1) & (c == V - 1)

        def thread_first(p, x):
            x_in = jnp.where(is_first, first_fn(p, raw), x)
            return fn(p, x_in)

        def pv(y, dx, gtree, l):
            return (_pvary_axes(y, act_axes), _pvary_axes(dx, act_axes),
                    jax.tree.map(lambda a: _pvary_axes(a, vaxes), gtree),
                    _pvary_axes(l, vaxes))

        def do_idle(_):
            return pv(zeros_b(), zeros_b(), jax.tree.map(
                lambda g: jnp.zeros_like(g), grad_zero), jnp.zeros(()))

        def do_fwd(_):
            y = thread_first(params_c, x_saved)
            return pv(y, zeros_b(), jax.tree.map(
                lambda g: jnp.zeros_like(g), grad_zero), jnp.zeros(()))

        def do_bwd(_):
            def run(loss_like):
                val, pull = jax.vjp(loss_like, params_c, x_saved)
                vma = getattr(jax.typeof(val), "vma", None)
                seed = _pvary_axes(jnp.ones((), val.dtype),
                                   vma or (axis_name,))
                dp, dx = pull(seed)
                return val, dp, dx

            def last_branch(_):
                return run(lambda p, x: last_fn(p, thread_first(p, x), lab)
                           * inv_m)

            def mid_branch(_):
                return run(lambda p, x: jnp.sum(
                    thread_first(p, x).astype(jnp.float32)
                    * g_recv.astype(jnp.float32)))

            val, dp, dx = lax.cond(is_last, last_branch, mid_branch, None)
            loss_c = jnp.where(is_last, val, 0.0)
            # scatter this chunk's grads into the [V, ...] accumulator
            dpf = jax.tree.map(
                lambda d, z: lax.dynamic_update_index_in_dim(
                    jnp.zeros_like(z), d.astype(z.dtype), c, 0),
                dp, grad_zero)
            return pv(zeros_b(), dx.astype(bdtype), dpf,
                      loss_c.astype(jnp.float32).reshape(()))

        send_y, send_dx, dp, loss_c = lax.switch(
            jnp.clip(op, 0, 2), [do_idle, do_fwd, do_bwd], None)

        grads = jax.tree.map(lambda g, d: g + d, grads, dp)
        loss_acc = loss_acc + loss_c

        new_fwd = lax.ppermute(send_y, axis_name,
                               [(i, (i + 1) % S) for i in range(S)])
        new_bwd = lax.ppermute(send_dx, axis_name,
                               [(i, (i - 1) % S) for i in range(S)])
        return (new_fwd, new_bwd, in_buf, cot_buf, grads, loss_acc), None

    act_axes = _varying_axes(axis_name, mb_inputs, mb_labels)
    vaxes = _varying_axes(axis_name, chunk_params, mb_inputs, mb_labels)
    init = (_pvary_axes(zeros_b(), act_axes),
            _pvary_axes(zeros_b(), act_axes),
            _pvary_axes(jnp.zeros((V, B) + bshape, bdtype), act_axes),
            _pvary_axes(jnp.zeros((V, B) + bshape, bdtype), act_axes),
            jax.tree.map(lambda z: _pvary_axes(z, vaxes), grad_zero),
            _pvary_axes(jnp.zeros((), jnp.float32), vaxes))
    (_, _, _, _, grads, loss_acc), _ = lax.scan(tick, init, jnp.arange(T))
    loss = lax.psum(loss_acc, axis_name)
    grads = jax.tree.map(lambda g: g[None], grads)
    return loss, grads


# -- PP composed with dp/tp: the 3-D training step ---------------------------

class PipelineTrainStep:
    """Compiled hybrid-parallel training step: 1F1B pipeline over ``pp``,
    data parallelism over ``dp``, tensor parallelism over ``tp`` — one mesh,
    one jitted program.

    Reference role: PipelineParallel inside HybridParallelClipGrad/fleet
    (meta_parallel/pipeline_parallel.py + hybrid_parallel_optimizer.py) where
    pp/dp/mp process groups compose.  Here the composition is a single
    fully-manual shard_map: the 1F1B tick scan runs over the pp axis;
    each microbatch's SAMPLE axis is split over dp — batch shape
    [M, mb, ...] with mb divisible by the dp size, every dp shard running
    all M microbatches on its slice, grads normalized back to the
    global-batch mean — and ``stage_fn`` is
    written Megatron-style against LOCAL tp shards (explicit lax.psum over
    the tp axis where its math requires it — same contract as mpu layers).

    Args:
      stage_fn/first_fn/last_fn: as :func:`pipeline_1f1b`, but operating on
        local tp param shards.
      stacked_params: dict name -> global [S, ...] stacked arrays.
      param_specs: dict name -> PartitionSpec with the leading pp axis and
        any tp placements, e.g. P('pp', None, 'tp').
      optimizer: a paddle_tpu optimizer (init_state_pytree/apply_gradients).
      batch: step() takes {'inputs': [M, mb, ...], 'labels': [M, mb, ...]};
        the microbatch axis is split over dp.
    """

    def __init__(self, stage_fn, first_fn, last_fn, stacked_params,
                 optimizer, mesh, num_microbatches, param_specs, *,
                 pp_axis: str = "pp", dp_axis: Optional[str] = "dp",
                 remat: bool = True):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.optimizer = optimizer
        self.num_microbatches = num_microbatches
        self._pp = pp_axis
        self._dp = dp_axis if dp_axis in mesh.axis_names else None
        self._specs = dict(param_specs)

        self._param_sh = {n: NamedSharding(mesh, self._specs[n])
                          for n in stacked_params}
        self.params = {n: jax.device_put(jnp.asarray(a), self._param_sh[n])
                       for n, a in stacked_params.items()}
        self.opt_state = optimizer.init_state_pytree(self.params)
        self.opt_state = {
            n: jax.tree.map(
                lambda a: jax.device_put(a, self._param_sh[n])
                if hasattr(a, "shape") and a.shape == self.params[n].shape
                else a, st)
            for n, st in self.opt_state.items()}
        self.step_count = jnp.zeros((), jnp.int32)

        manual = set(mesh.axis_names)

        def body(params, mb_inputs, mb_labels):
            loss, grads = pipeline_1f1b(
                stage_fn, first_fn, last_fn, params, mb_inputs, mb_labels,
                num_microbatches=num_microbatches, axis_name=pp_axis,
                remat=remat)
            # dp semantics: each dp shard computes the mean loss of ITS
            # microbatch slice; the vjp transpose has already psum'd the
            # per-shard grads over dp, so divide by dp size to get the
            # global-batch mean.  Then pmean over any axis a leaf's grad
            # still varies on but its out_spec omits (vma cleanup; values
            # are already equal across those shards).
            if self._dp:
                dp_size = lax.axis_size(self._dp)
                grads = {n: g / dp_size for n, g in grads.items()}
                loss = lax.pmean(loss, self._dp)

            def reduce_leaf(g, spec):
                present = set()
                for e in spec:
                    if isinstance(e, tuple):
                        present.update(e)
                    elif e is not None:
                        present.add(e)
                vma = getattr(jax.typeof(g), "vma", None) or ()
                for ax in manual - present - {pp_axis}:
                    if ax in vma:
                        g = lax.pmean(g, ax)
                return g
            grads = {n: reduce_leaf(g, self._specs[n])
                     for n, g in grads.items()}
            vma_l = getattr(jax.typeof(loss), "vma", None) or ()
            for ax in manual - {pp_axis}:
                if ax in vma_l:
                    loss = lax.pmean(loss, ax)
            return loss, grads

        batch_spec = P(None, self._dp) if self._dp else P()
        self._shmap = jax.shard_map(
            body, mesh=mesh,
            in_specs=({n: self._specs[n] for n in self.params},
                      batch_spec, batch_spec),
            out_specs=(P(), {n: self._specs[n] for n in self.params}))

        def step_impl(params, opt_state, step_count, mb_inputs, mb_labels,
                      lr):
            loss, grads = self._shmap(params, mb_inputs, mb_labels)
            step_count = step_count + 1
            new_params, new_state = optimizer.apply_gradients(
                params, grads, opt_state, step_count, lr=lr)
            return loss, new_params, new_state, step_count

        self._jitted = jax.jit(step_impl, donate_argnums=(0, 1, 2))

    def __call__(self, batch):
        mb_inputs = jnp.asarray(batch["inputs"])
        mb_labels = jnp.asarray(batch["labels"])
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        loss, self.params, self.opt_state, self.step_count = self._jitted(
            self.params, self.opt_state, self.step_count, mb_inputs,
            mb_labels, lr)
        if self.optimizer._lr_scheduler is not None:
            self.optimizer._lr_scheduler.step()
        return loss
