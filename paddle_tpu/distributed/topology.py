"""Hybrid-parallel topology math.

Reference parity: ``CommunicateTopology`` / ``HybridCommunicateGroup``
(python/paddle/distributed/fleet/base/topology.py:54,140).  In the reference
these objects create one NCCL ProcessGroup per axis of the
["data","pipe","sharding","sep","model"] hypercube.  Here the same coordinate
arithmetic instead *names the axes of one jax.sharding.Mesh* — groups are not
runtime objects on TPU (XLA compiles the collectives), but the rank↔coordinate
math is still load-bearing for pipeline schedules, checkpoint layout, and
parity of the fleet API.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Sequence, Tuple

import numpy as np

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]


class CommunicateTopology:
    """N-dim cartesian rank topology (row-major, first axis slowest)."""

    def __init__(self, hybrid_group_names: Sequence[str] = (
            "data", "pipe", "sharding", "model"),
            dims: Sequence[int] = (1, 1, 1, 1)):
        assert len(hybrid_group_names) == len(dims)
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(
            *(range(d) for d in self._dims)))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int) -> Tuple[int, ...]:
        return self.coordinate[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on `axis_name` equals `index`."""
        axis = self._parallel_names.index(axis_name)
        return sorted(self._coord2rank[c] for c in self.coordinate
                      if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Partition of ranks into communication groups along one axis:
        each group varies `axis_name` and fixes every other coordinate."""
        axis = self._parallel_names.index(axis_name)
        other = [n for i, n in enumerate(self._parallel_names) if i != axis]
        groups = []
        for fixed in itertools.product(
                *(range(self._dims[i])
                  for i in range(len(self._dims)) if i != axis)):
            kw = dict(zip(other, fixed))
            group = []
            for k in range(self._dims[axis]):
                kw[self._parallel_names[axis]] = k
                group.append(self.get_rank(**kw))
            groups.append(group)
        return groups

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        """Rank with the same coordinate as `global_rank` except overrides."""
        coord = dict(zip(self._parallel_names, self.get_coord(global_rank)))
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    """Per-rank view of the topology (reference: topology.py:140).

    The reference builds NCCL groups here; we only answer the coordinate
    queries (degree / rank-in-group / group ranks) that fleet layers,
    pipeline schedules, and checkpoint sharding ask for, and expose the
    mesh-axis names that the GSPMD substrate uses instead of groups.
    """

    # (topology axis, mesh axis) pairs, reference order topology.py:56
    AXES = (("data", "dp"), ("pipe", "pp"), ("sharding", "sharding"),
            ("model", "mp"))

    def __init__(self, topology: CommunicateTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.nranks = topology.world_size()
        for topo_name, short in self.AXES:
            try:
                degree = topology.get_dim(topo_name)
            except ValueError:
                degree = 1
            setattr(self, f"_{short}_degree", degree)

    # degrees ---------------------------------------------------------------
    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    # coordinates -----------------------------------------------------------
    def _axis_info(self, name: str):
        coord = dict(zip(self._topo.get_hybrid_group_names(),
                         self._topo.get_coord(self.global_rank)))
        rank_in_group = coord.get(name, 0)
        index = {k: v for k, v in coord.items() if k != name}
        ranks = [r for r in range(self.nranks)
                 if all(dict(zip(self._topo.get_hybrid_group_names(),
                                 self._topo.get_coord(r))).get(k) == v
                        for k, v in index.items())]
        return rank_in_group, sorted(ranks)

    def get_data_parallel_rank(self) -> int:
        return self._axis_info("data")[0]

    def get_model_parallel_rank(self) -> int:
        return self._axis_info("model")[0]

    def get_stage_id(self) -> int:
        return self._axis_info("pipe")[0]

    def get_sharding_parallel_rank(self) -> int:
        return self._axis_info("sharding")[0]

    def get_data_parallel_group_ranks(self) -> List[int]:
        return self._axis_info("data")[1]

    def get_model_parallel_group_ranks(self) -> List[int]:
        return self._axis_info("model")[1]

    def get_pipe_parallel_group_ranks(self) -> List[int]:
        return self._axis_info("pipe")[1]

    def get_sharding_parallel_group_ranks(self) -> List[int]:
        return self._axis_info("sharding")[1]

    # pipeline neighbours ---------------------------------------------------
    def is_first_stage(self) -> bool:
        return self.get_stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_next_rank(self) -> int:
        stage = (self.get_stage_id() + 1) % self._pp_degree
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage)

    def get_p2p_prev_rank(self) -> int:
        stage = (self.get_stage_id() - 1) % self._pp_degree
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage)

    # mesh ------------------------------------------------------------------
    def mesh_shape(self) -> Dict[str, int]:
        """{mesh axis name: degree>1} — the jax Mesh this topology induces."""
        out = {}
        for topo_name, short in self.AXES:
            try:
                d = self._topo.get_dim(topo_name)
            except ValueError:
                d = 1
            if d > 1:
                out[short] = d
        return out or {"dp": 1}
