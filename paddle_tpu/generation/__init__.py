"""Text generation: compiled KV-cache decode loop.

Reference role: PaddleNLP-style ``model.generate`` over the reference's
fused decoding ops (fused_multi_transformer + beam/sampling ops).
TPU-native design: ONE jitted prefill + ONE jitted step function driven
by ``lax.scan`` — static cache buffers mean every decode step reuses the
same executable, sampling (greedy / temperature / top-k / top-p) is pure
jnp, and early EOS termination is a masked no-op so the trip count stays
static.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional

import weakref

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["StaticCache", "GenerationConfig", "generate",
           "static_cache_attention", "reject_scalar_mask"]


def reject_scalar_mask(attn_mask):
    """Guard shared by the cached-decode forward signatures: a scalar
    attn_mask means the caller positionally passed position_offset where
    attn_mask now sits.  Returns the unwrapped mask (or None)."""
    from paddle_tpu.core.dispatch import unwrap
    raw = None if attn_mask is None else unwrap(attn_mask)
    if isinstance(attn_mask, (int, float)) or (
            raw is not None and getattr(raw, "ndim", 1) == 0):
        raise TypeError(
            "attn_mask got a scalar — position_offset must be passed by "
            "keyword (the forward signature gained attn_mask before it)")
    return raw


class StaticCache(NamedTuple):
    """Pre-allocated KV buffers [batch, max_len, kv_heads, head_dim]."""
    k: object
    v: object


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    seed: int = 0


def static_cache_attention(q, k, v, cache: StaticCache, position_offset,
                           attn_mask=None):
    """Shared static-buffer decode attention (used by Llama and GPT):
    write k/v at position_offset via dynamic_update_slice, attend over the
    valid causal prefix of the fixed buffer, honoring a caller mask.

    q/k/v: [b, s, h, hd] current-step projections (paddle layout).
    Returns (out [b, s, h, hd-flattened by caller], new_cache)."""
    from paddle_tpu.core.dispatch import unwrap, wrap_like
    from paddle_tpu.nn.functional.attention import \
        scaled_dot_product_attention

    s = q.shape[1]
    if getattr(position_offset, "ndim", 0) == 1:
        # per-row positions [B] (continuous batching: every slot decodes
        # at its own offset).  Single-token steps only: the write is a
        # per-row scatter, the causal bound is per-row.
        if s != 1:
            raise ValueError("per-row position_offset requires seq==1 "
                             f"(got {s})")
        B = q.shape[0]
        rows = jnp.arange(B)
        kb = unwrap(cache.k).at[rows, position_offset].set(
            unwrap(k)[:, 0].astype(cache.k.dtype))
        vb = unwrap(cache.v).at[rows, position_offset].set(
            unwrap(v)[:, 0].astype(cache.v.dtype))
        max_len = kb.shape[1]
        kpos = jnp.arange(max_len)[None, None, None, :]
        qpos = position_offset[:, None, None, None]
        mask = kpos <= qpos                     # [B,1,1,max_len]
    else:
        kb = jax.lax.dynamic_update_slice(
            unwrap(cache.k), unwrap(k).astype(cache.k.dtype),
            (0, position_offset, 0, 0))
        vb = jax.lax.dynamic_update_slice(
            unwrap(cache.v), unwrap(v).astype(cache.v.dtype),
            (0, position_offset, 0, 0))
        max_len = kb.shape[1]
        kpos = jnp.arange(max_len)[None, None, None, :]
        qpos = position_offset + jnp.arange(s)[None, None, :, None]
        mask = kpos <= qpos  # valid-prefix causal bound over the buffer
    if attn_mask is not None:
        am = reject_scalar_mask(attn_mask)
        if am.dtype == jnp.bool_:
            mask = mask & am
        else:  # additive mask: fold the causal bound in
            mask = jnp.where(mask, am.astype(jnp.float32), -1e30)
    out = scaled_dot_product_attention(q, wrap_like(kb), wrap_like(vb),
                                       attn_mask=mask, is_causal=False)
    return out, StaticCache(wrap_like(kb), wrap_like(vb))


def _sample(logits, cfg: GenerationConfig, key):
    """[B, vocab] -> [B] next tokens."""
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k and cfg.top_k > 0:
        k = min(cfg.top_k, logits.shape[-1])  # clamp: top_k may exceed vocab
        kth = jnp.sort(logits, axis=-1)[:, -k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest prefix with mass >= top_p stays; find its cutoff logit
        cutoff_idx = jnp.sum(cum < cfg.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1)


def _empty_caches(model, batch, max_len, dtype):
    cfg = model.config
    n_kv = cfg.num_key_value_heads
    hd = cfg.head_dim
    shape = (batch, max_len, n_kv, hd)
    return [StaticCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(cfg.num_hidden_layers)]


def generate(model, input_ids, generation_config: Optional[
        GenerationConfig] = None, **kwargs):
    """Autoregressive decoding with a compiled per-token step.

    input_ids: [batch, prompt_len] (numpy / Tensor / jax).  Returns
    [batch, prompt_len + max_new_tokens] int32.  EOS handling matches the
    usual transformers convention: the EOS token itself is emitted verbatim
    (including when it is the very first sampled token), and every position
    AFTER a sequence's EOS is filled with pad_token_id.
    """
    from paddle_tpu.core.dispatch import unwrap
    from paddle_tpu.core.functional import functional_call, params_of

    cfg = generation_config or GenerationConfig(**kwargs)
    ids = jnp.asarray(unwrap(input_ids), jnp.int32)
    if ids.ndim == 1:
        ids = ids[None]
    B, L = ids.shape
    max_len = L + cfg.max_new_tokens
    params = params_of(model)
    compute_dtype = next(iter(params.values())).dtype

    # one compiled run per (model, batch/prompt shape, sampling config):
    # repeated generate() calls at the same shapes reuse the executable;
    # keyed on the model WEAKLY so dropping the model frees its
    # executables, and bounded per model so variable prompt lengths
    # don't accumulate without limit
    cfg_key = (cfg.max_new_tokens, cfg.do_sample, cfg.temperature,
               cfg.top_k, cfg.top_p, cfg.eos_token_id, cfg.pad_token_id)
    shape_key = (B, L, str(compute_dtype), cfg_key)
    per_model = _RUN_CACHE.get(model)
    if per_model is None:
        per_model = _RUN_CACHE[model] = {}
    run = per_model.get(shape_key)
    if run is None:
        if len(per_model) >= _RUN_CACHE_MAX_PER_MODEL:
            per_model.pop(next(iter(per_model)))  # evict least recent
        run = _build_run(model, cfg, B, L)
    else:
        per_model.pop(shape_key)  # re-insert so order tracks recency (LRU)
    per_model[shape_key] = run

    caches0 = _empty_caches(model, B, max_len, compute_dtype)
    key = jax.random.PRNGKey(cfg.seed)
    was_training = getattr(model, "training", False)
    if was_training:
        model.eval()  # decode is inference: dropout must be off
    try:
        return np.asarray(run(params, ids, caches0, key))
    finally:
        if was_training:
            model.train()


_RUN_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_RUN_CACHE_MAX_PER_MODEL = 16


def _build_run(model, cfg: GenerationConfig, B: int, L: int):
    from paddle_tpu.core.dispatch import unwrap
    from paddle_tpu.core.functional import functional_call

    # weak reference: the cached closure must not keep the model alive
    # (the cache is keyed weakly on the model for exactly that reason)
    model_ref = weakref.ref(model)

    def fwd(params, tok, caches, pos):
        m = model_ref()
        assert m is not None, "model was garbage-collected"
        out = functional_call(m, params, tok, None, caches, pos)
        logits, new_caches = out
        raw = unwrap(logits)
        return raw[:, -1, :].astype(jnp.float32), jax.tree.map(
            unwrap, new_caches, is_leaf=lambda t: hasattr(t, "_data"))

    @jax.jit
    def run(params, ids, caches0, key):
        # prefill the whole prompt in one pass
        logits, caches = fwd(params, ids, caches0, 0)
        key, sub = jax.random.split(key)
        next_tok = _sample(logits, cfg, sub)
        done = jnp.zeros((B,), bool)
        if cfg.eos_token_id is not None:
            done = next_tok == cfg.eos_token_id

        def step(carry, _):
            caches, tok, pos, key, done = carry
            logits, caches = fwd(params, tok[:, None], caches, pos)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, cfg, sub)
            if cfg.eos_token_id is not None:
                nxt = jnp.where(done, cfg.pad_token_id, nxt)
                done = done | (nxt == cfg.eos_token_id)
            return (caches, nxt, pos + 1, key, done), nxt

        carry = (caches, next_tok, L, key, done)
        if cfg.max_new_tokens > 1:
            _, rest = jax.lax.scan(step, carry, None,
                                   length=cfg.max_new_tokens - 1)
            out = jnp.concatenate([next_tok[:, None], rest.T], axis=1)
        else:
            out = next_tok[:, None]
        return jnp.concatenate([ids, out], axis=1)

    return run
