"""Tape traversal: the eager backward pass.

Parity target: the reference's RunBackward
(/root/reference/paddle/fluid/eager/backward.cc:104) — a topological queue over
GradNodes with per-tensor gradient accumulation (GradTensorHolder).  Here each
GradNode holds a jax.vjp pullback, so "running" a node is one pullback call.
"""

from __future__ import annotations

from collections import defaultdict
from typing import List, Optional

import jax.numpy as jnp

from paddle_tpu.core.tensor import GradNode, Tensor

__all__ = ["run_backward", "calc_gradients"]


def _topo_order(roots: List[GradNode]):
    """Reverse-topological order over the node graph (outputs first)."""
    indeg = defaultdict(int)  # node -> number of consumers discovered
    seen = set()
    stack = list(roots)
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        for t in node.inputs:
            parent = t._grad_node
            if parent is not None:
                indeg[id(parent)] += 1
                stack.append(parent)
    return indeg, seen


def _add(a, b):
    """Pairwise grad accumulation; taped when either side carries history."""
    from paddle_tpu.core.sparse_grad import RowSparseGrad
    if isinstance(a, RowSparseGrad):
        return a + b          # sparse+sparse → concat; sparse+dense → dense
    if isinstance(b, RowSparseGrad):
        return b + a
    if isinstance(a, Tensor) or isinstance(b, Tensor):
        # both sides must be Tensors: a raw jax.Array's __add__ would coerce
        # the Tensor via __jax_array__ and silently drop its grad history
        if not isinstance(a, Tensor):
            a = Tensor._wrap(a)
        if not isinstance(b, Tensor):
            b = Tensor._wrap(b)
        from paddle_tpu.core.tensor import enable_grad
        with enable_grad():
            return a + b
    return a + b


def _accumulate(store, key, value):
    cur = store.get(key)
    store[key] = value if cur is None else _add(cur, value)


def _apply_node(node, cots, create_graph):
    """Run one node's pullback; taped (create_graph) or raw."""
    if create_graph and node.create_graph_apply is not None:
        from paddle_tpu.core.tensor import enable_grad
        with enable_grad():
            return node.create_graph_apply(cots)
    cots = [c._data if isinstance(c, Tensor) else c for c in cots]
    return node.apply(cots)


def run_backward(tensors: List[Tensor], grad_tensors=None, retain_graph=False,
                 create_graph=False):
    """Standard .backward(): writes .grad on leaf tensors (and on tensors that
    called retain_grads())."""
    grads = _backward_impl(tensors, grad_tensors, retain_graph,
                           accumulate_into_grad=True, wanted=None,
                           create_graph=create_graph)
    return grads


def calc_gradients(outputs, inputs, grad_outputs=None, retain_graph=False,
                   allow_unused=False, create_graph=False):
    """paddle.grad parity: return grads of outputs wrt inputs, no .grad writes.

    With create_graph=True each node is applied through the taped
    double-backward (GradNode.create_graph_apply), so the returned grads carry
    their own grad history — reference: paddle.grad(create_graph=True)
    (python/paddle/autograd/__init__).
    """
    wanted = {id(t): t for t in inputs}
    grads = _backward_impl(outputs, grad_outputs, retain_graph,
                           accumulate_into_grad=False, wanted=wanted,
                           create_graph=create_graph)
    result = []
    for t in inputs:
        g = grads.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been "
                "used in the graph. Set allow_unused=True if this is desired.")
        if g is None:
            result.append(None)
        else:
            from paddle_tpu.core.sparse_grad import RowSparseGrad
            result.append(g if isinstance(g, (Tensor, RowSparseGrad))
                          else Tensor._wrap(g))
    return result


def _backward_impl(tensors, grad_tensors, retain_graph, accumulate_into_grad,
                   wanted, create_graph=False):
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    # Pending cotangents per (node, out_index); plus leaf grads keyed by id(tensor)
    node_cots = {}
    leaf_grads = {}
    tensor_by_id = {}

    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._grad_node is None:
            raise RuntimeError("backward() on a tensor with stop_gradient=True "
                               "and no grad history")
        if g is None:
            if t.numel() != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g_arr = jnp.ones(t._data.shape, t._data.dtype)
        elif create_graph and isinstance(g, Tensor):
            g_arr = g  # keep grad history through the seed cotangent
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._grad_node
        if node is None or t._retain_grads or (wanted and id(t) in wanted):
            _accumulate(leaf_grads, id(t), g_arr)
            tensor_by_id[id(t)] = t
        if node is not None:
            key = id(node)
            if key not in node_cots:
                node_cots[key] = [None] * node.n_outputs
                roots.append(node)
            slot = node_cots[key]
            cur = slot[t._out_index]
            slot[t._out_index] = g_arr if cur is None else _add(cur, g_arr)

    indeg, reachable = _topo_order(roots)
    # ready queue: nodes whose consumers (within reachable set) are all done.
    ready = [n for n in roots if indeg[id(n)] == 0]
    nodes_by_id = {id(n): n for n in roots}
    done = set()

    # BFS with dependency counting (Kahn) — same structure as RunBackward's
    # node_in_degree_map loop in the reference.
    # We must discover nodes lazily: a node becomes known when a cotangent
    # reaches it.
    while ready:
        node = ready.pop()
        if id(node) in done:
            continue
        done.add(id(node))
        cots = node_cots.pop(id(node), [None] * node.n_outputs)
        in_grads = _apply_node(node, cots, create_graph)
        if not retain_graph and not create_graph:
            node.vjp_fn = None  # free saved activations
            node.create_graph_apply = None  # also pins the op closure
        for t, g in zip(node.inputs, in_grads):
            parent = t._grad_node
            if g is not None and (parent is None or t._retain_grads
                                  or (wanted and id(t) in wanted)):
                _accumulate(leaf_grads, id(t), g)
                tensor_by_id[id(t)] = t
            if parent is not None:
                key = id(parent)
                if key not in done:
                    if key not in node_cots:
                        node_cots[key] = [None] * parent.n_outputs
                        nodes_by_id[key] = parent
                    if g is not None:
                        slot = node_cots[key]
                        cur = slot[t._out_index]
                        slot[t._out_index] = g if cur is None else _add(cur, g)
                    indeg[key] -= 1
                    if indeg[key] <= 0:
                        ready.append(parent)

    # Any remaining nodes with pending cotangents but unresolved indegree
    # (diamond patterns where some consumers were unreachable): flush them.
    while node_cots:
        progressed = False
        for key in list(node_cots):
            if key in done:
                node_cots.pop(key)
                continue
            node = nodes_by_id[key]
            done.add(key)
            cots = node_cots.pop(key)
            in_grads = _apply_node(node, cots, create_graph)
            if not retain_graph and not create_graph:
                node.vjp_fn = None
                node.create_graph_apply = None
            progressed = True
            for t, g in zip(node.inputs, in_grads):
                if g is None:
                    continue
                parent = t._grad_node
                if parent is None or t._retain_grads or (wanted and id(t) in wanted):
                    _accumulate(leaf_grads, id(t), g)
                    tensor_by_id[id(t)] = t
                if parent is not None and id(parent) not in done:
                    if id(parent) not in node_cots:
                        node_cots[id(parent)] = [None] * parent.n_outputs
                        nodes_by_id[id(parent)] = parent
                    slot = node_cots[id(parent)]
                    cur = slot[t._out_index]
                    slot[t._out_index] = g if cur is None else _add(cur, g)
            break
        if not progressed:
            break

    if accumulate_into_grad:
        from paddle_tpu.core.sparse_grad import RowSparseGrad
        for tid, g in leaf_grads.items():
            t = tensor_by_id[tid]
            if t.stop_gradient and t._grad_node is not None:
                continue
            if isinstance(g, RowSparseGrad) or \
                    isinstance(t._grad, RowSparseGrad):
                # SelectedRows-style grad: stored raw on .grad (the
                # reference's embedding(sparse=True) grad is a
                # SelectedRows, not a dense LoDTensor)
                prev = t._grad
                if prev is not None and isinstance(prev, Tensor):
                    prev = prev._data
                acc = g if prev is None else _add(prev, g)
                t._grad = acc if isinstance(acc, RowSparseGrad) \
                    else Tensor._wrap(acc)
                continue
            g_t = g if isinstance(g, Tensor) else Tensor._wrap(g)
            if t._grad is None:
                t._grad = g_t
            else:
                acc = _add(t._grad if create_graph else t._grad._data,
                           g_t if create_graph else g_t._data)
                t._grad = acc if isinstance(acc, Tensor) else Tensor._wrap(acc)
    return leaf_grads
