"""Tape traversal: the eager backward pass.

Parity target: the reference's RunBackward
(/root/reference/paddle/fluid/eager/backward.cc:104) — a topological queue over
GradNodes with per-tensor gradient accumulation (GradTensorHolder).  Here each
GradNode holds a jax.vjp pullback, so "running" a node is one pullback call.
"""

from __future__ import annotations

from collections import defaultdict
from typing import List, Optional

import jax.numpy as jnp

from paddle_tpu.core.tensor import GradNode, Tensor

__all__ = ["run_backward", "calc_gradients"]


def _topo_order(roots: List[GradNode]):
    """Reverse-topological order over the node graph (outputs first)."""
    indeg = defaultdict(int)  # node -> number of consumers discovered
    seen = set()
    stack = list(roots)
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        for t in node.inputs:
            parent = t._grad_node
            if parent is not None:
                indeg[id(parent)] += 1
                stack.append(parent)
    return indeg, seen


def _accumulate(store, key, value):
    cur = store.get(key)
    store[key] = value if cur is None else cur + value


def run_backward(tensors: List[Tensor], grad_tensors=None, retain_graph=False):
    """Standard .backward(): writes .grad on leaf tensors (and on tensors that
    called retain_grads())."""
    grads = _backward_impl(tensors, grad_tensors, retain_graph,
                           accumulate_into_grad=True, wanted=None)
    return grads


def calc_gradients(outputs, inputs, grad_outputs=None, retain_graph=False,
                   allow_unused=False):
    """paddle.grad parity: return grads of outputs wrt inputs, no .grad writes."""
    wanted = {id(t): t for t in inputs}
    grads = _backward_impl(outputs, grad_outputs, retain_graph,
                           accumulate_into_grad=False, wanted=wanted)
    result = []
    for t in inputs:
        g = grads.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been "
                "used in the graph. Set allow_unused=True if this is desired.")
        result.append(None if g is None else Tensor._wrap(g))
    return result


def _backward_impl(tensors, grad_tensors, retain_graph, accumulate_into_grad,
                   wanted):
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    # Pending cotangents per (node, out_index); plus leaf grads keyed by id(tensor)
    node_cots = {}
    leaf_grads = {}
    tensor_by_id = {}

    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._grad_node is None:
            raise RuntimeError("backward() on a tensor with stop_gradient=True "
                               "and no grad history")
        if g is None:
            if t.numel() != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g_arr = jnp.ones(t._data.shape, t._data.dtype)
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._grad_node
        if node is None or t._retain_grads or (wanted and id(t) in wanted):
            _accumulate(leaf_grads, id(t), g_arr)
            tensor_by_id[id(t)] = t
        if node is not None:
            key = id(node)
            if key not in node_cots:
                node_cots[key] = [None] * node.n_outputs
                roots.append(node)
            slot = node_cots[key]
            cur = slot[t._out_index]
            slot[t._out_index] = g_arr if cur is None else cur + g_arr

    indeg, reachable = _topo_order(roots)
    # ready queue: nodes whose consumers (within reachable set) are all done.
    ready = [n for n in roots if indeg[id(n)] == 0]
    nodes_by_id = {id(n): n for n in roots}
    done = set()

    # BFS with dependency counting (Kahn) — same structure as RunBackward's
    # node_in_degree_map loop in the reference.
    # We must discover nodes lazily: a node becomes known when a cotangent
    # reaches it.
    while ready:
        node = ready.pop()
        if id(node) in done:
            continue
        done.add(id(node))
        cots = node_cots.pop(id(node), [None] * node.n_outputs)
        in_grads = node.apply(cots)
        if not retain_graph:
            node.vjp_fn = None  # free saved activations
        for t, g in zip(node.inputs, in_grads):
            parent = t._grad_node
            if g is not None and (parent is None or t._retain_grads
                                  or (wanted and id(t) in wanted)):
                _accumulate(leaf_grads, id(t), g)
                tensor_by_id[id(t)] = t
            if parent is not None:
                key = id(parent)
                if key not in done:
                    if key not in node_cots:
                        node_cots[key] = [None] * parent.n_outputs
                        nodes_by_id[key] = parent
                    if g is not None:
                        slot = node_cots[key]
                        cur = slot[t._out_index]
                        slot[t._out_index] = g if cur is None else cur + g
                    indeg[key] -= 1
                    if indeg[key] <= 0:
                        ready.append(parent)

    # Any remaining nodes with pending cotangents but unresolved indegree
    # (diamond patterns where some consumers were unreachable): flush them.
    while node_cots:
        progressed = False
        for key in list(node_cots):
            if key in done:
                node_cots.pop(key)
                continue
            node = nodes_by_id[key]
            done.add(key)
            cots = node_cots.pop(key)
            in_grads = node.apply(cots)
            if not retain_graph:
                node.vjp_fn = None
            progressed = True
            for t, g in zip(node.inputs, in_grads):
                if g is None:
                    continue
                parent = t._grad_node
                if parent is None or t._retain_grads or (wanted and id(t) in wanted):
                    _accumulate(leaf_grads, id(t), g)
                    tensor_by_id[id(t)] = t
                if parent is not None and id(parent) not in done:
                    if id(parent) not in node_cots:
                        node_cots[id(parent)] = [None] * parent.n_outputs
                        nodes_by_id[id(parent)] = parent
                    slot = node_cots[id(parent)]
                    cur = slot[t._out_index]
                    slot[t._out_index] = g if cur is None else cur + g
            break
        if not progressed:
            break

    if accumulate_into_grad:
        for tid, g in leaf_grads.items():
            t = tensor_by_id[tid]
            if t.stop_gradient and t._grad_node is not None:
                continue
            if t._grad is None:
                t._grad = Tensor._wrap(g)
            else:
                t._grad = Tensor._wrap(t._grad._data + g)
    return leaf_grads
