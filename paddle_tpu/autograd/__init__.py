"""Autograd user API (parity: python/paddle/autograd/).

backward/grad drive the eager tape (autograd/backward_engine.py); PyLayer is
the custom-VJP extension point (reference: autograd/py_layer.py:29), lowered
here to jax.custom_vjp when used functionally and to direct tape nodes when
used eagerly."""

from __future__ import annotations

from typing import List, Sequence

import jax

from paddle_tpu.autograd.backward_engine import calc_gradients, run_backward
from paddle_tpu.core.dispatch import unwrap, wrap_like
from paddle_tpu.core.tensor import (GradNode, Tensor, enable_grad,
                                    is_grad_enabled, no_grad, set_grad_enabled)

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad",
           "enable_grad", "is_grad_enabled", "set_grad_enabled", "hessian",
           "jacobian"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is not None and isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    if create_graph:
        raise NotImplementedError(
            "create_graph=True on the eager tape is not supported; use the "
            "functional API (paddle_tpu.incubate.autograd / jax.grad) for "
            "higher-order derivatives.")
    retain = bool(retain_graph) if retain_graph is not None else False
    return calc_gradients(outputs, inputs, grad_outputs, retain_graph=retain,
                          allow_unused=allow_unused)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """User-defined forward/backward (reference: python/paddle/autograd/py_layer.py:29).

    Subclass with @staticmethod forward(ctx, *args) and backward(ctx, *grads).
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        diff = [t for t in tensor_args if not t.stop_gradient]

        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        if not (is_grad_enabled() and diff):
            return out

        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]
        avals = [(o._data.shape, o._data.dtype) for o in outs]
        treedef = jax.tree.structure([0] * len(outs))

        def vjp_fn(cotangents):
            grads = cls.backward(ctx, *[wrap_like(c) for c in cotangents])
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            grads = [None if g is None else unwrap(g) for g in grads]
            if len(grads) != len(diff):
                # user returns one grad per forward tensor input; filter to diff
                if len(grads) == len(tensor_args):
                    grads = [g for g, t in zip(grads, tensor_args)
                             if not t.stop_gradient]
                else:
                    raise RuntimeError(
                        f"PyLayer.backward returned {len(grads)} grads, "
                        f"expected {len(diff)}")
            return grads

        node = GradNode(vjp_fn, diff, avals, treedef, name=cls.__name__)
        wrapped = []
        for i, o in enumerate(outs):
            t = Tensor._wrap(o._data, stop_gradient=False, node=node, out_index=i)
            wrapped.append(t)
        return tuple(wrapped) if multi else wrapped[0]


def jacobian(ys, xs, batch_axis=None):
    """Functional jacobian on eager tensors via jax.jacrev (stateless)."""
    raise NotImplementedError(
        "Use paddle_tpu.incubate.autograd.jacobian on a pure function.")


def hessian(ys, xs, batch_axis=None):
    raise NotImplementedError(
        "Use paddle_tpu.incubate.autograd.hessian on a pure function.")
