"""Autograd user API (parity: python/paddle/autograd/).

backward/grad drive the eager tape (autograd/backward_engine.py); PyLayer is
the custom-VJP extension point (reference: autograd/py_layer.py:29), lowered
here to jax.custom_vjp when used functionally and to direct tape nodes when
used eagerly."""

from __future__ import annotations

from typing import List, Sequence

import jax

from paddle_tpu.autograd.backward_engine import calc_gradients, run_backward
from paddle_tpu.core.dispatch import unwrap, wrap_like
from paddle_tpu.core.tensor import (GradNode, Tensor, enable_grad,
                                    is_grad_enabled, no_grad, set_grad_enabled)

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad",
           "enable_grad", "is_grad_enabled", "set_grad_enabled", "hessian",
           "jacobian"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is not None and isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    retain = bool(retain_graph) if retain_graph is not None else bool(create_graph)
    return calc_gradients(outputs, inputs, grad_outputs, retain_graph=retain,
                          allow_unused=allow_unused, create_graph=create_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """User-defined forward/backward (reference: python/paddle/autograd/py_layer.py:29).

    Subclass with @staticmethod forward(ctx, *args) and backward(ctx, *grads).
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        diff = [t for t in tensor_args if not t.stop_gradient]

        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        if not (is_grad_enabled() and diff):
            return out

        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]
        avals = [(o._data.shape, o._data.dtype) for o in outs]
        treedef = jax.tree.structure([0] * len(outs))

        def vjp_fn(cotangents):
            grads = cls.backward(ctx, *[wrap_like(c) for c in cotangents])
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            grads = [None if g is None else unwrap(g) for g in grads]
            if len(grads) != len(diff):
                # user returns one grad per forward tensor input; filter to diff
                if len(grads) == len(tensor_args):
                    grads = [g for g, t in zip(grads, tensor_args)
                             if not t.stop_gradient]
                else:
                    raise RuntimeError(
                        f"PyLayer.backward returned {len(grads)} grads, "
                        f"expected {len(diff)}")
            return grads

        node = GradNode(vjp_fn, diff, avals, treedef, name=cls.__name__)

        def _cg_apply(cot_flat):
            """create_graph path: run user backward under the tape so the
            produced grads are differentiable."""
            import jax.numpy as jnp
            cots = []
            for c, (shape, dtype) in zip(cot_flat, avals):
                if c is None:
                    c = wrap_like(jnp.zeros(shape, dtype))
                elif not isinstance(c, Tensor):
                    c = wrap_like(c)
                cots.append(c)
            with enable_grad():
                grads = cls.backward(ctx, *cots)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            grads = list(grads)
            if len(grads) != len(diff):
                if len(grads) == len(tensor_args):
                    grads = [g for g, t in zip(grads, tensor_args)
                             if not t.stop_gradient]
                else:
                    raise RuntimeError(
                        f"PyLayer.backward returned {len(grads)} grads, "
                        f"expected {len(diff)}")
            return grads

        node.create_graph_apply = _cg_apply
        wrapped = []
        for i, o in enumerate(outs):
            t = Tensor._wrap(o._data, stop_gradient=False, node=node, out_index=i)
            wrapped.append(t)
        return tuple(wrapped) if multi else wrapped[0]


def _dense_jacobian(y: Tensor, x: Tensor, create_graph=False):
    """Rows of d(y_flat)/d(x) via one seeded backward per output element.

    Eager convenience API (reference: python/paddle/autograd/autograd.py
    Jacobian); O(numel(y)) pullback calls, each taped when create_graph.
    """
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.core.dispatch import wrap_like
    from paddle_tpu import ops as _ops

    y_n = max(1, int(np.prod(y.shape)))
    rows = []
    for i in range(y_n):
        seed = np.zeros((y_n,), np.float32)
        seed[i] = 1.0
        seed_t = wrap_like(jnp.asarray(seed.reshape(y.shape or ()),
                                       y._data.dtype))
        g = grad([y], [x], grad_outputs=[seed_t], retain_graph=True,
                 create_graph=create_graph, allow_unused=True)[0]
        if g is None:
            from paddle_tpu.ops.creation import zeros
            g = zeros(x.shape, dtype=x.dtype)
        rows.append(g)
    from paddle_tpu.ops.manipulation import stack, reshape
    out = stack(rows, axis=0)
    return reshape(out, list(y.shape) + list(x.shape))


def _batched_jacobian(y: Tensor, x: Tensor, create_graph=False):
    """Batch-diagonal Jacobian: y (B, M...), x (B, N...) -> (B, M..., N...).

    Valid under batch_axis semantics (batch rows independent): seeding output
    element m across ALL batch rows at once yields J[:, m, :] in one pullback.
    """
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.core.dispatch import wrap_like
    from paddle_tpu.ops.manipulation import stack, reshape

    B = y.shape[0]
    per = max(1, int(np.prod(y.shape[1:])))
    rows = []
    for m in range(per):
        seed = np.zeros((B, per), np.float32)
        seed[:, m] = 1.0
        seed_t = wrap_like(jnp.asarray(seed.reshape(y.shape), y._data.dtype))
        g = grad([y], [x], grad_outputs=[seed_t], retain_graph=True,
                 create_graph=create_graph, allow_unused=True)[0]
        if g is None:
            from paddle_tpu.ops.creation import zeros
            g = zeros(x.shape, dtype=x.dtype)
        rows.append(g)
    out = stack(rows, axis=1)  # (B, per, N...)
    return reshape(out, [B] + list(y.shape[1:]) + list(x.shape[1:]))


def jacobian(ys, xs, batch_axis=None):
    """Dense Jacobian of ys wrt xs.

    Reference: python/paddle/autograd/autograd.py (paddle.autograd.jacobian).
    batch_axis=None -> shape ys.shape + xs.shape; batch_axis=0 -> batched
    Jacobian of shape (B,) + ys.shape[1:] + xs.shape[1:] (batch rows treated
    as independent, as the reference's semantics require).
    """
    if batch_axis not in (None, 0):
        raise ValueError("jacobian: batch_axis must be None or 0")
    jac = _dense_jacobian if batch_axis is None else _batched_jacobian
    multi_y = not isinstance(ys, Tensor)
    multi_x = not isinstance(xs, Tensor)
    ys_l = list(ys) if multi_y else [ys]
    xs_l = list(xs) if multi_x else [xs]
    out = [[jac(y, x) for x in xs_l] for y in ys_l]
    if not multi_y and not multi_x:
        return out[0][0]
    if not multi_y:
        return out[0]
    if not multi_x:
        return [row[0] for row in out]
    return out


def hessian(ys, xs, batch_axis=None):
    """Dense Hessian of a scalar ys wrt xs.

    Single x: shape xs.shape + xs.shape.  List of xs: full block matrix
    H[i][j] = d2 ys / (dx_i dx_j), cross blocks included.
    """
    if not isinstance(ys, Tensor):
        raise ValueError("hessian expects a scalar Tensor output")
    if batch_axis is not None:
        raise ValueError("hessian: batch_axis is not supported for a scalar "
                         "output; take jacobian(grad, x, batch_axis=0)")
    multi_x = not isinstance(xs, Tensor)
    xs_l = list(xs) if multi_x else [xs]
    firsts = grad([ys], xs_l, create_graph=True, allow_unused=True)
    from paddle_tpu.ops.creation import zeros
    out = []
    for g1, xi in zip(firsts, xs_l):
        row = []
        for xj in xs_l:
            if g1 is None:
                row.append(zeros(list(xi.shape) + list(xj.shape),
                                 dtype=xi.dtype))
            else:
                row.append(_dense_jacobian(g1, xj))
        out.append(row)
    return out if multi_x else out[0][0]
