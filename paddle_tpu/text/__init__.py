"""paddle.text parity — text datasets + vocabulary utilities.

Reference: python/paddle/text/datasets/ (Imdb imdb.py, Imikolov
imikolov.py, UCIHousing uci_housing.py, ...).  No network egress here, so
every dataset either reads user-supplied files (same simple formats) or
generates a deterministic synthetic corpus with the right structure —
enough for the hapi examples and pipeline tests to run end to end.
"""

from __future__ import annotations

import os
import re
from collections import Counter
from typing import Iterable, List, Optional

import numpy as np

from paddle_tpu.io.dataset import Dataset

from paddle_tpu.text.tokenizer import FasterTokenizer  # noqa: F401

__all__ = ["Vocab", "FasterTokenizer", "Imdb", "Imikolov", "UCIHousing", "LMDataset",
           "viterbi_decode"]


class Vocab:
    """Token <-> id mapping (reference paddlenlp-style Vocab used by the
    text datasets; built from a counter or token iterator)."""

    def __init__(self, counter=None, min_freq: int = 1,
                 unk_token: str = "<unk>", pad_token: str = "<pad>"):
        self._tok2id = {}
        self._id2tok = []
        for sp in (pad_token, unk_token):
            if sp is not None:
                self._add(sp)
        self.unk_token = unk_token
        self.pad_token = pad_token
        if counter:
            for tok, freq in sorted(counter.items(),
                                    key=lambda kv: (-kv[1], kv[0])):
                if freq >= min_freq:
                    self._add(tok)

    def _add(self, tok):
        if tok not in self._tok2id:
            self._tok2id[tok] = len(self._id2tok)
            self._id2tok.append(tok)

    @classmethod
    def build_vocab(cls, iterator: Iterable[List[str]], min_freq=1,
                    **kw) -> "Vocab":
        c = Counter()
        for toks in iterator:
            c.update(toks)
        return cls(c, min_freq=min_freq, **kw)

    def to_indices(self, tokens: List[str]) -> List[int]:
        unk = self._tok2id.get(self.unk_token, 0)
        return [self._tok2id.get(t, unk) for t in tokens]

    def to_tokens(self, ids) -> List[str]:
        return [self._id2tok[int(i)] for i in ids]

    def __len__(self):
        return len(self._id2tok)

    def __contains__(self, tok):
        return tok in self._tok2id


_WORDS = ("the a on in of to and tpu chip mesh shard pipe moe adam norm "
          "token train loss grad step model layer head expert ring flash "
          "scan fuse tile core lane sub hbm vmem ici link host data").split()


def _synthetic_sentences(n, seed, lo=5, hi=12):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(rng.integers(lo, hi))
        # zipf-flavored draws so vocab frequencies look natural
        idx = np.minimum(rng.zipf(1.3, k) - 1, len(_WORDS) - 1)
        out.append([_WORDS[j] for j in idx])
    return out


class Imdb(Dataset):
    """Sentiment-classification dataset (reference imdb.py): (token_ids,
    label).  Reads an on-disk ``data_file`` with `label<TAB>text` lines,
    else a deterministic synthetic corpus (label = parity of sentence
    content so a model can learn it)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 1, seq_len: int = 16):
        self.seq_len = seq_len
        if data_file and os.path.exists(data_file):
            rows = []
            with open(data_file) as f:
                for ln in f:
                    lab, _, txt = ln.partition("\t")
                    rows.append((re.findall(r"\w+", txt.lower()),
                                 int(lab)))
            self._sents = [r[0] for r in rows]
            self._labels = [r[1] for r in rows]
        else:
            n = 800 if mode == "train" else 200
            self._sents = _synthetic_sentences(n, seed=5 if mode == "train"
                                               else 6)
            self._labels = [int(len(s) % 2) for s in self._sents]
        self.vocab = Vocab.build_vocab(self._sents, min_freq=cutoff)
        self.word_idx = self.vocab._tok2id  # reference attribute name

    def __len__(self):
        return len(self._sents)

    def __getitem__(self, idx):
        ids = self.vocab.to_indices(self._sents[idx])[:self.seq_len]
        pad = self.vocab._tok2id[self.vocab.pad_token]
        ids = ids + [pad] * (self.seq_len - len(ids))
        return np.asarray(ids, np.int64), np.int64(self._labels[idx])


class Imikolov(Dataset):
    """n-gram language-model dataset (reference imikolov.py): each item
    is an (n-1)-gram context plus the next word."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train", min_word_freq=1):
        if data_file and os.path.exists(data_file):
            with open(data_file) as f:
                sents = [re.findall(r"\w+", ln.lower()) for ln in f]
        else:
            sents = _synthetic_sentences(
                600 if mode == "train" else 150,
                seed=7 if mode == "train" else 8, lo=window_size + 1,
                hi=window_size + 8)
        self.vocab = Vocab.build_vocab(sents, min_freq=min_word_freq)
        self._grams = []
        for s in sents:
            ids = self.vocab.to_indices(s)
            for i in range(len(ids) - window_size + 1):
                self._grams.append(ids[i:i + window_size])

    def __len__(self):
        return len(self._grams)

    def __getitem__(self, idx):
        g = self._grams[idx]
        return np.asarray(g[:-1], np.int64), np.int64(g[-1])


class UCIHousing(Dataset):
    """Regression dataset (reference uci_housing.py): 13 features ->
    price.  Reads the standard whitespace-delimited file, else generates
    a fixed random linear-plus-noise problem."""

    FEATURES = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
            x, y = raw[:, :-1], raw[:, -1:]
        else:
            rng = np.random.default_rng(9 if mode == "train" else 10)
            n = 400 if mode == "train" else 100
            x = rng.standard_normal((n, self.FEATURES)).astype(np.float32)
            w = np.linspace(-1, 1, self.FEATURES).astype(np.float32)
            y = (x @ w[:, None] + 0.05
                 * rng.standard_normal((n, 1))).astype(np.float32)
        # feature normalization, reference behavior
        mu, sd = x.mean(0, keepdims=True), x.std(0, keepdims=True) + 1e-6
        self._x = (x - mu) / sd
        self._y = y

    def __len__(self):
        return len(self._x)

    def __getitem__(self, idx):
        return self._x[idx], self._y[idx]


class LMDataset(Dataset):
    """Next-token LM dataset over a flat token stream: (input_ids,
    labels) windows of ``seq_len`` — the shape TrainStep consumes.  Built
    from a text file, a token array, or the synthetic corpus."""

    def __init__(self, tokens=None, data_file: Optional[str] = None,
                 seq_len: int = 32, vocab: Optional[Vocab] = None,
                 mode: str = "train"):
        self.seq_len = seq_len
        if tokens is not None:
            stream = np.asarray(tokens, np.int64)
            self.vocab = vocab
        else:
            if data_file and os.path.exists(data_file):
                with open(data_file) as f:
                    sents = [re.findall(r"\w+", ln.lower()) for ln in f]
            else:
                sents = _synthetic_sentences(
                    500 if mode == "train" else 100,
                    seed=11 if mode == "train" else 12)
            self.vocab = vocab or Vocab.build_vocab(sents)
            stream = np.asarray(
                [i for s in sents for i in self.vocab.to_indices(s)],
                np.int64)
        n = (len(stream) - 1) // seq_len
        self._x = stream[:n * seq_len].reshape(n, seq_len)
        self._y = stream[1:n * seq_len + 1].reshape(n, seq_len)

    def __len__(self):
        return len(self._x)

    def __getitem__(self, idx):
        return self._x[idx], self._y[idx]


def viterbi_decode(potentials, transition_params, lengths=None):
    """paddle.text.viterbi_decode parity: batched hard Viterbi over
    emission ``potentials`` [B, T, N] with ``transition_params`` [N, N].
    Returns (scores [B], paths [B, T])."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.dispatch import unwrap, wrap_like

    pots = unwrap(potentials)
    trans = unwrap(transition_params)
    B, T, N = pots.shape

    def step(carry, emit):
        score = carry                                   # [B, N]
        cand = score[:, :, None] + trans[None]          # [B, N, N]
        best = jnp.max(cand, axis=1) + emit             # [B, N]
        back = jnp.argmax(cand, axis=1)                 # [B, N]
        return best, back

    score0 = pots[:, 0]
    best, backs = jax.lax.scan(step, score0,
                               jnp.moveaxis(pots[:, 1:], 1, 0))
    last = jnp.argmax(best, axis=-1)                    # [B]
    scores = jnp.max(best, axis=-1)

    def walk(carry, back):
        # carry = path[t+1]; back belongs to step t+1 and yields path[t]
        prev = jnp.take_along_axis(back, carry[:, None], 1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(walk, last, backs, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1), last[:, None]],
                            axis=1)
    return wrap_like(scores), wrap_like(paths.astype(jnp.int64))
