"""FasterTokenizer — native WordPiece tokenization (ctypes over
csrc/tokenizer).

Reference parity: ``faster_tokenizer``
(paddle/fluid/operators/string/faster_tokenizer_op.cc — BERT tokenize as
a graph op) + the strings kernel family (phi/kernels/strings/).
TPU-native stance: XLA programs never see strings, so tokenization is
host data-plane work — a native C++ WordPiece encoder feeding int ids
straight into the input pipeline, not an in-graph op.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

__all__ = ["FasterTokenizer"]


def _lib():
    from paddle_tpu.utils.cpp_extension import load_native
    lib = load_native("tokenizer", required_symbol="tok_encode")
    lib.tok_create.restype = ctypes.c_void_p
    lib.tok_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.tok_destroy.argtypes = [ctypes.c_void_p]
    lib.tok_id_count.restype = ctypes.c_int64
    lib.tok_id_count.argtypes = [ctypes.c_void_p]
    lib.tok_token_to_id.restype = ctypes.c_int64
    lib.tok_token_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tok_encode.restype = ctypes.c_int64
    lib.tok_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_int64),
                               ctypes.c_int64]
    return lib


class FasterTokenizer:
    """BERT-style WordPiece tokenizer backed by the native encoder.

    vocab: path to a one-token-per-line vocab file, OR a {token: id} dict
    / list of tokens (written to a temp file for the native side — ids
    must then be dense 0..n-1).

    Case folding is ASCII-only (the native side uses the C locale):
    non-ASCII text passes through unfolded — matching vocab entries must
    be cased as they appear, unlike BERT's full-unicode BasicTokenizer.
    """

    def __init__(self, vocab: Union[str, Dict[str, int], Sequence[str]],
                 do_lower_case: bool = True,
                 cls_token: str = "[CLS]", sep_token: str = "[SEP]",
                 pad_token: str = "[PAD]"):
        self._lib = _lib()
        if self._lib is None:
            raise RuntimeError("native tokenizer library unavailable")
        self._own_path = None
        if not isinstance(vocab, str):
            if isinstance(vocab, dict):
                items = sorted(vocab.items(), key=lambda kv: kv[1])
                if [i for _, i in items] != list(range(len(items))):
                    raise ValueError("vocab dict ids must be dense 0..n-1")
                tokens = [t for t, _ in items]
            else:
                tokens = list(vocab)
            import tempfile
            fd, path = tempfile.mkstemp(suffix=".vocab")
            with os.fdopen(fd, "w") as f:
                f.write("\n".join(tokens))
            self._own_path = vocab = path
        self._h = self._lib.tok_create(vocab.encode(),
                                       1 if do_lower_case else 0)
        if not self._h:
            raise FileNotFoundError(f"cannot read vocab file {vocab}")
        self.vocab_size = int(self._lib.tok_id_count(self._h))
        self.cls_id = self.token_to_id(cls_token)
        self.sep_id = self.token_to_id(sep_token)
        if self.cls_id < 0 or self.sep_id < 0:
            import warnings
            missing = [t for t, i in ((cls_token, self.cls_id),
                                      (sep_token, self.sep_id)) if i < 0]
            warnings.warn(
                f"special token(s) {missing} not in the vocab; sequences "
                "will be encoded WITHOUT [CLS]/[SEP] markers", stacklevel=2)
        _pad = self.token_to_id(pad_token)
        if _pad < 0:
            import warnings
            warnings.warn(
                f"pad token {pad_token!r} is not in the vocab; padding "
                "will use id 0, which is a REAL vocab token — pass the "
                "correct pad_token for this vocab", stacklevel=2)
        self.pad_id = max(_pad, 0)

    def token_to_id(self, token: str) -> int:
        return int(self._lib.tok_token_to_id(self._h, token.encode()))

    def tokenize_ids(self, text: str, max_len: int = 512) -> List[int]:
        """Raw WordPiece ids, no special tokens."""
        buf = (ctypes.c_int64 * max_len)()
        n = self._lib.tok_encode(self._h, text.encode("utf-8", "ignore"),
                                 buf, max_len)
        return list(buf[:n])

    def __call__(self, text: Union[str, Sequence[str]],
                 max_seq_len: int = 128,
                 pad_to_max_seq_len: bool = True):
        """Encode text(s) → {'input_ids', 'token_type_ids'} int64 arrays
        with [CLS]/[SEP] added (the faster_tokenizer_op output contract)."""
        texts = [text] if isinstance(text, str) else list(text)
        add_specials = self.cls_id >= 0 and self.sep_id >= 0
        if add_specials and max_seq_len < 3:
            raise ValueError(f"max_seq_len={max_seq_len} leaves no room "
                             "for [CLS]/[SEP] plus content")
        rows = []
        for s in texts:
            ids = self.tokenize_ids(s, max_len=max_seq_len)
            if add_specials:
                ids = [self.cls_id] + ids[:max_seq_len - 2] + [self.sep_id]
            rows.append(ids)
        width = max_seq_len if pad_to_max_seq_len else \
            max(len(r) for r in rows)
        out = np.full((len(rows), width), self.pad_id, np.int64)
        for i, r in enumerate(rows):
            out[i, :len(r)] = r
        return {"input_ids": out,
                "token_type_ids": np.zeros_like(out)}

    def close(self):
        if getattr(self, "_h", None):
            self._lib.tok_destroy(self._h)
            self._h = None
        if self._own_path:
            try:
                os.remove(self._own_path)
            except OSError:
                pass
            self._own_path = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
