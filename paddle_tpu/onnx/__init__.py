"""paddle_tpu.onnx — ONNX export of Layers.

Reference parity: ``paddle.onnx.export`` (python/paddle/onnx/export.py —
Program → ONNX via paddle2onnx).  TPU-native translation: the captured
program is a jaxpr (the tracing that replaces ProgramDesc), and export
walks its equations mapping XLA primitives onto ONNX ops.  The vendored
``onnx_mini.proto`` is a subset of the PUBLIC ONNX schema (the ``onnx``
pip package is not in this image); files written here are standard
``.onnx`` protobufs loadable by onnxruntime/netron.

Scope: serving-style exports — MLP / conv / classifier graphs (matmul,
conv, elementwise chains, reductions, reshapes).  Models with exotic
dot_general layouts (ring attention, MoE dispatch) should ship via the
first-class StableHLO path (jit.save); export raises a clear error
naming any unmapped primitive.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["export"]


def _pb():
    from paddle_tpu.onnx import onnx_mini_pb2
    return onnx_mini_pb2


# ONNX TensorProto data types (public enum values)
_DTYPES = {"float32": 1, "uint8": 2, "int8": 3, "int32": 6, "int64": 7,
           "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16}


def _onnx_dtype(np_dtype) -> int:
    name = str(np_dtype)
    if name not in _DTYPES:
        raise NotImplementedError(f"onnx export: dtype {name}")
    return _DTYPES[name]


class _Builder:
    def __init__(self, opset: int):
        self.pb = _pb()
        self.model = self.pb.ModelProto()
        self.model.ir_version = 8
        self.model.producer_name = "paddle_tpu"
        ops = self.model.opset_import.add()
        ops.domain = ""
        ops.version = opset
        self.graph = self.model.graph
        self.graph.name = "paddle_tpu_graph"
        self._n = 0
        self.names: Dict[int, str] = {}   # id(jaxpr var) -> onnx name

    def fresh(self, prefix="v") -> str:
        self._n += 1
        return f"{prefix}_{self._n}"

    def name_of(self, var) -> str:
        from jax._src.core import Literal
        if isinstance(var, Literal):
            return self.add_initializer(np.asarray(var.val))
        key = id(var)
        if key not in self.names:
            self.names[key] = self.fresh()
        return self.names[key]

    def add_initializer(self, arr: np.ndarray, name: Optional[str] = None
                        ) -> str:
        name = name or self.fresh("const")
        t = self.graph.initializer.add()
        t.name = name
        t.dims.extend(arr.shape)
        if str(arr.dtype) == "bfloat16":
            arr = arr.astype(np.float32)  # serve in fp32
        t.data_type = _onnx_dtype(arr.dtype)
        t.raw_data = np.ascontiguousarray(arr).tobytes()
        return name

    def node(self, op: str, inputs: Sequence[str], outputs: Sequence[str],
             **attrs):
        n = self.graph.node.add()
        n.op_type = op
        n.name = self.fresh(op.lower())
        n.input.extend(inputs)
        n.output.extend(outputs)
        for k, v in attrs.items():
            a = n.attribute.add()
            a.name = k
            if isinstance(v, int):
                a.type, a.i = 2, v
            elif isinstance(v, float):
                a.type, a.f = 1, v
            elif isinstance(v, str):
                a.type, a.s = 3, v.encode()
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, int) for x in v):
                a.type = 7
                a.ints.extend(v)
            else:
                raise NotImplementedError(f"attr {k}={v!r}")
        return n

    def value_info(self, holder, name: str, shape, np_dtype):
        vi = holder.add()
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = _onnx_dtype(np_dtype)
        for d in shape:
            dim = tt.shape.dim.add()
            dim.dim_value = int(d)


_ELEMWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "add_any": "Add",
    "lt": "Less", "le": "LessOrEqual", "gt": "Greater",
    "ge": "GreaterOrEqual", "eq": "Equal", "and": "And", "or": "Or",
    "xor": "Xor",
}
_UNARY = {
    "neg": "Neg", "exp": "Exp", "log": "Log", "tanh": "Tanh",
    "logistic": "Sigmoid", "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign",
    "erf": "Erf", "floor": "Floor", "ceil": "Ceil",
    "stop_gradient": "Identity", "copy": "Identity",
}


def _np_of(aval):
    return np.dtype(aval.dtype)


def _emit_eqn(b: _Builder, eqn):
    prim = eqn.primitive.name
    ins = [b.name_of(v) for v in eqn.invars]
    outs = [b.name_of(v) for v in eqn.outvars]
    p = eqn.params

    if prim in ("pjit", "jit", "closed_call", "custom_jvp_call",
                "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                "checkpoint", "custom_jvp_call_jaxpr"):
        inner = p.get("jaxpr") or p.get("call_jaxpr") or p.get("fun_jaxpr")
        if inner is None:
            raise NotImplementedError(f"onnx export: call prim {prim} "
                                      f"without inner jaxpr")
        closed = inner if hasattr(inner, "jaxpr") else None
        jaxpr = closed.jaxpr if closed is not None else inner
        consts = closed.consts if closed is not None else []
        # wire: constvars → initializers, invars/outvars → aliases
        for cv, cval in zip(jaxpr.constvars, consts):
            b.names[id(cv)] = b.add_initializer(np.asarray(cval))
        for iv, outer in zip(jaxpr.invars, ins):
            b.names[id(iv)] = outer
        for e in jaxpr.eqns:
            _emit_eqn(b, e)
        for ov, outer in zip(jaxpr.outvars, outs):
            b.node("Identity", [b.name_of(ov)], [outer])
        return

    if prim in _ELEMWISE:
        b.node(_ELEMWISE[prim], ins, outs)
    elif prim in _UNARY:
        b.node(_UNARY[prim], ins, outs)
    elif prim == "rsqrt":
        mid = b.fresh()
        b.node("Sqrt", ins, [mid])
        b.node("Reciprocal", [mid], outs)
    elif prim == "square":
        b.node("Mul", [ins[0], ins[0]], outs)
    elif prim == "not":
        b.node("Not", ins, outs)
    elif prim == "is_finite":
        inf_ = b.fresh()
        nan_ = b.fresh()
        bad = b.fresh()
        b.node("IsInf", ins, [inf_])
        b.node("IsNaN", ins, [nan_])
        b.node("Or", [inf_, nan_], [bad])
        b.node("Not", [bad], outs)
    elif prim == "ne":
        mid = b.fresh()
        b.node("Equal", ins, [mid])
        b.node("Not", [mid], outs)
    elif prim == "clamp":
        # lax.clamp(min, x, max) → ONNX Clip(x, min, max)
        b.node("Clip", [ins[1], ins[0], ins[2]], outs)
    elif prim == "gather":
        dn = p["dimension_numbers"]
        operand_aval = eqn.invars[0].aval
        slice_sizes = tuple(p["slice_sizes"])
        # the jnp.take(x, ids, axis=0) pattern → ONNX Gather(axis=0)
        if (tuple(dn.collapsed_slice_dims) == (0,)
                and tuple(dn.start_index_map) == (0,)
                and not getattr(dn, "operand_batching_dims", ())
                and slice_sizes == (1,) + tuple(operand_aval.shape[1:])):
            idx_aval = eqn.invars[1].aval
            # indices carry a trailing index-vector dim of size 1: drop it
            ishape = b.add_initializer(
                np.asarray(idx_aval.shape[:-1], np.int64))
            flat = b.fresh()
            b.node("Reshape", [ins[1], ishape], [flat])
            b.node("Gather", [ins[0], flat], outs, axis=0)
        else:
            raise NotImplementedError(
                "onnx export: general gather layouts are not mapped "
                "(only take-along-axis-0 / embedding lookup); use "
                "jit.save/StableHLO for this model")
    elif prim == "erfc":
        one = b.add_initializer(np.asarray(1.0, _np_of(eqn.invars[0].aval)))
        mid = b.fresh()
        b.node("Erf", ins, [mid])
        b.node("Sub", [one, mid], outs)
    elif prim == "reduce_mean":
        # axes stay an ATTRIBUTE until opset 18 (unlike ReduceSum at 13)
        b.node("ReduceMean", [ins[0]], outs, axes=list(p["axes"]),
               keepdims=0)
    elif prim == "integer_pow":
        y = int(p["y"])
        exp_name = b.add_initializer(
            np.asarray(y, _np_of(eqn.invars[0].aval)))
        b.node("Pow", [ins[0], exp_name], outs)
    elif prim == "convert_element_type":
        # bf16 graphs are folded to fp32 throughout (initializers + IO),
        # so a bf16 cast target must fold too or the graph type-checks
        # inconsistently in real ONNX consumers
        tgt = np.dtype(np.float32) if str(p["new_dtype"]) == "bfloat16" \
            else np.dtype(p["new_dtype"])
        b.node("Cast", ins, outs, to=_onnx_dtype(tgt))
    elif prim == "select_n":
        if len(ins) != 3:
            raise NotImplementedError("onnx export: select_n arity != 3")
        # jax select_n(pred, on_false, on_true); ONNX Where(cond, X, Y)
        # picks X where cond true
        b.node("Where", [ins[0], ins[2], ins[1]], outs)
    elif prim == "reshape":
        shape = b.add_initializer(
            np.asarray(eqn.outvars[0].aval.shape, np.int64))
        b.node("Reshape", [ins[0], shape], outs)
    elif prim == "squeeze":
        axes = b.add_initializer(np.asarray(p["dimensions"], np.int64))
        b.node("Squeeze", [ins[0], axes], outs)
    elif prim == "transpose":
        b.node("Transpose", ins, outs, perm=list(p["permutation"]))
    elif prim == "broadcast_in_dim":
        shape = list(p["shape"])
        bdims = list(p["broadcast_dimensions"])
        # step 1: reshape operand to rank(shape) with 1s off the bcast dims
        interim = [1] * len(shape)
        for src, dst in enumerate(bdims):
            interim[dst] = eqn.invars[0].aval.shape[src]
        rname = b.fresh()
        rshape = b.add_initializer(np.asarray(interim, np.int64))
        b.node("Reshape", [ins[0], rshape], [rname])
        eshape = b.add_initializer(np.asarray(shape, np.int64))
        b.node("Expand", [rname, eshape], outs)
    elif prim in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod"):
        axes = list(p["axes"])
        op = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
              "reduce_min": "ReduceMin", "reduce_prod": "ReduceProd"}[prim]
        if op == "ReduceSum":  # opset 13: axes are an input
            ax = b.add_initializer(np.asarray(axes, np.int64))
            b.node(op, [ins[0], ax], outs, keepdims=0)
        else:                  # axes still an attribute at opset 13
            b.node(op, [ins[0]], outs, axes=axes, keepdims=0)
    elif prim == "dot_general":
        ((lc, rc), (lb, rb)) = p["dimension_numbers"]
        lhs_aval, rhs_aval = (v.aval for v in eqn.invars)
        lr, rr = len(lhs_aval.shape), len(rhs_aval.shape)
        # numpy-style matmul: contract lhs last dim with rhs first
        # non-batch dim, identical leading batch dims
        if (tuple(lc), tuple(rc)) == ((lr - 1,), (rr - 2 if rr > 1 else 0,)) \
                and tuple(lb) == tuple(range(len(lb))) \
                and tuple(rb) == tuple(range(len(rb))):
            b.node("MatMul", ins, outs)
        else:
            # GENERAL layout (attention q@k^T, context@v, ...): transpose
            # each side to (batch..., free..., contract...) /
            # (batch..., contract..., free...), flatten the groups,
            # batched MatMul, reshape to the jax output layout
            # (batch..., lhs_free..., rhs_free...) — which is exactly
            # dot_general's result order, so no output transpose
            lshape, rshape = lhs_aval.shape, rhs_aval.shape
            lfree = [d for d in range(lr) if d not in lc and d not in lb]
            rfree = [d for d in range(rr) if d not in rc and d not in rb]
            lperm = list(lb) + lfree + list(lc)
            rperm = list(rb) + list(rc) + rfree
            bdims = [lshape[d] for d in lb]
            M = int(np.prod([lshape[d] for d in lfree], dtype=np.int64)) \
                if lfree else 1
            N = int(np.prod([rshape[d] for d in rfree], dtype=np.int64)) \
                if rfree else 1
            K = int(np.prod([lshape[d] for d in lc], dtype=np.int64)) \
                if lc else 1
            lt, rt = b.fresh(), b.fresh()
            b.node("Transpose", [ins[0]], [lt], perm=lperm)
            b.node("Transpose", [ins[1]], [rt], perm=rperm)
            l2, r2 = b.fresh(), b.fresh()
            b.node("Reshape", [lt, b.add_initializer(
                np.asarray(bdims + [M, K], np.int64))], [l2])
            b.node("Reshape", [rt, b.add_initializer(
                np.asarray(bdims + [K, N], np.int64))], [r2])
            mm = b.fresh()
            b.node("MatMul", [l2, r2], [mm])
            out_shape = b.add_initializer(
                np.asarray(eqn.outvars[0].aval.shape, np.int64))
            b.node("Reshape", [mm, out_shape], outs)
    elif prim == "split":
        sizes = list(p["sizes"])
        ax = b.add_initializer(np.asarray(sizes, np.int64),
                               b.fresh("splits"))
        b.node("Split", [ins[0], ax], outs, axis=int(p["axis"]))
    elif prim == "concatenate":
        b.node("Concat", ins, outs, axis=int(p["dimension"]))
    elif prim == "iota":
        # static shape: materialize as an initializer (arange broadcast
        # along `dimension`)
        shape = tuple(p["shape"])
        dim = int(p["dimension"])
        dt = np.dtype(p["dtype"])
        if str(dt) == "bfloat16":
            dt = np.dtype(np.float32)
        vec = np.arange(shape[dim], dtype=dt)
        arr = np.broadcast_to(
            vec.reshape([-1 if i == dim else 1
                         for i in range(len(shape))]), shape).copy()
        b.node("Identity", [b.add_initializer(arr)], outs)
    elif prim == "slice":
        starts = list(p["start_indices"])
        ends = list(p["limit_indices"])
        steps = list(p["strides"] or [1] * len(starts))
        axes = list(range(len(starts)))
        b.node("Slice",
               [ins[0],
                b.add_initializer(np.asarray(starts, np.int64)),
                b.add_initializer(np.asarray(ends, np.int64)),
                b.add_initializer(np.asarray(axes, np.int64)),
                b.add_initializer(np.asarray(steps, np.int64))], outs)
    elif prim == "expand_dims":
        axes = b.add_initializer(np.asarray(p["dimensions"], np.int64))
        b.node("Unsqueeze", [ins[0], axes], outs)
    elif prim == "conv_general_dilated":
        dn = p["dimension_numbers"]
        if (dn.lhs_spec[:2] != (0, 1)) or (dn.rhs_spec[:2] != (0, 1)):
            raise NotImplementedError("onnx export: conv layout != NCHW/OIHW")
        if any(d != 1 for d in p["lhs_dilation"]):
            # transposed conv lowers via lhs_dilation — a plain ONNX Conv
            # would silently compute the wrong thing
            raise NotImplementedError(
                "onnx export: lhs-dilated conv (Conv2DTranspose) is not "
                "mapped; use jit.save/StableHLO for this model")
        pads = list(p["padding"])
        onnx_pads = [pr[0] for pr in pads] + [pr[1] for pr in pads]
        b.node("Conv", ins, outs,
               strides=list(p["window_strides"]),
               pads=onnx_pads,
               dilations=list(p["rhs_dilation"]),
               group=int(p["feature_group_count"]))
    else:
        raise NotImplementedError(
            f"onnx export: unmapped primitive '{prim}'. Supported: "
            f"{sorted(list(_ELEMWISE) + list(_UNARY))} + matmul/conv/"
            "reduce/reshape/transpose/broadcast/cast/where. Use "
            "jit.save (StableHLO) for full-coverage serialization.")


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs) -> str:
    """Export ``layer`` to ``path + '.onnx'`` (reference signature:
    paddle.onnx.export(layer, path, input_spec, **configs)).

    input_spec: list of InputSpec (static shapes required)."""
    import jax
    from paddle_tpu.core.functional import functional_call, params_of
    from paddle_tpu.jit.save_load import InputSpec

    if input_spec is None:
        raise ValueError("onnx export needs input_spec=[InputSpec(...)]")
    avals = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            shape = [1 if d is None else int(d) for d in spec.shape]
            avals.append(jax.ShapeDtypeStruct(tuple(shape),
                                              np.dtype(spec.dtype)))
        else:
            arr = np.asarray(spec)
            avals.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))

    params = params_of(layer)

    def fn(ps, *xs):
        out = functional_call(layer, ps, *xs)
        return out._data if hasattr(out, "_data") else out

    closed = jax.make_jaxpr(fn)(params, *avals)
    jaxpr = closed.jaxpr

    b = _Builder(opset_version)
    # params arrive flattened in the jaxpr invars: first the pytree leaves
    # of `params`, then the data inputs
    leaves = jax.tree.leaves(params)
    n_param = len(leaves)
    for var, leaf in zip(jaxpr.invars[:n_param], leaves):
        b.names[id(var)] = b.add_initializer(np.asarray(leaf))
    for i, var in enumerate(jaxpr.invars[n_param:]):
        name = f"input_{i}"
        b.names[id(var)] = name
        dt = np.dtype(var.aval.dtype)
        if str(dt) == "bfloat16":
            dt = np.dtype(np.float32)
        b.value_info(b.graph.input, name, var.aval.shape, dt)
    for cv, cval in zip(jaxpr.constvars, closed.consts):
        b.names[id(cv)] = b.add_initializer(np.asarray(cval))

    for eqn in jaxpr.eqns:
        _emit_eqn(b, eqn)

    for i, var in enumerate(jaxpr.outvars):
        out_name = b.name_of(var)
        public = f"output_{i}"
        b.node("Identity", [out_name], [public])
        dt = np.dtype(var.aval.dtype)
        if str(dt) == "bfloat16":
            dt = np.dtype(np.float32)
        b.value_info(b.graph.output, public, var.aval.shape, dt)

    out_path = path if path.endswith(".onnx") else path + ".onnx"
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "wb") as f:
        f.write(b.model.SerializeToString())
    return out_path
