"""Common NN functional ops: linear, dropout, embedding, one_hot, pad,
interpolate, etc. (parity: python/paddle/nn/functional/common.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import functional as _func
from paddle_tpu.core import state as _state
from paddle_tpu.core.dispatch import dispatch, eager_op, unwrap
from paddle_tpu.ops.manipulation import pad  # re-export paddle.nn.functional.pad


@eager_op
def linear(x, weight, bias=None):
    # paddle stores Linear weight as [in, out] → plain matmul, MXU-friendly
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """RNG comes from the functional stream under jit (functional_call rngs)
    or the global eager key otherwise."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            # paddle 'downscale_in_infer': train masks unscaled, infer
            # multiplies by keep-prob
            return dispatch(lambda xv: (xv * (1.0 - p)).astype(xv.dtype), x,
                            op_name="dropout")
        return x
    if p == 1.0:
        from paddle_tpu.ops.creation import zeros_like
        return zeros_like(x)

    key = _func.next_functional_key("dropout")
    if key is None:
        key = _state.next_key()

    def _drop(xv):
        shape = list(xv.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, xv / (1.0 - p), jnp.zeros((), xv.dtype)
                             ).astype(xv.dtype)
        return jnp.where(keep, xv, jnp.zeros((), xv.dtype)).astype(xv.dtype)

    return dispatch(_drop, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = _func.next_functional_key("dropout") or _state.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def _drop(xv):
        keep = jax.random.bernoulli(key, 1.0 - p, xv.shape)
        a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, xv, alpha_p) + b).astype(xv.dtype)

    return dispatch(_drop, x, op_name="alpha_dropout")


def _embedding_pure(x, weight, padding_idx=None):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out


_embedding_dense = eager_op(_embedding_pure, name="embedding")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """``sparse=True`` (reference: ``nn.functional.embedding(sparse=True)``
    → SelectedRows grad, phi/kernels/selected_rows/) produces a
    ``RowSparseGrad`` for `weight` on the eager tape: rows-touched only,
    no dense [vocab, d] gradient is ever materialized.  Under jit, with
    grads disabled, or when `weight` is not a LEAF tensor (an upstream
    pullback could not consume a sparse cotangent) the dense path runs
    (XLA fuses the scatter-add)."""
    if sparse:
        from paddle_tpu.core.tensor import Tensor, is_grad_enabled
        from paddle_tpu.core import functional as _func
        if (isinstance(weight, Tensor) and not weight.stop_gradient
                and weight._grad_node is None
                and is_grad_enabled() and not _func.substitution_active()):
            from paddle_tpu.nn.functional.sparse_embed import (
                sparse_embedding_lookup)
            return sparse_embedding_lookup(x, weight, padding_idx)
    return _embedding_dense(x, weight, padding_idx=padding_idx)


@eager_op
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


@eager_op
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


@eager_op
def normalize(x, p=2, axis=1, epsilon=1e-12):
    norm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(norm, epsilon)


@eager_op
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


@eager_op
def bilinear(x1, x2, weight, bias=None):
    # weight: [out, in1, in2]
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@eager_op
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        b, c, h, w = x.shape
        oc = c // (r * r)
        x = jnp.reshape(x, (b, oc, r, r, h, w))
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return jnp.reshape(x, (b, oc, h * r, w * r))
    b, h, w, c = x.shape
    oc = c // (r * r)
    x = jnp.reshape(x, (b, h, w, r, r, oc))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return jnp.reshape(x, (b, h * r, w * r, oc))


@eager_op
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        b, c, h, w = x.shape
        x = jnp.reshape(x, (b, c, h // r, r, w // r, r))
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return jnp.reshape(x, (b, c * r * r, h // r, w // r))
    raise NotImplementedError("NHWC pixel_unshuffle")


@eager_op
def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NCHW":
        b, c, h, w = x.shape
        x = jnp.reshape(x, (b, groups, c // groups, h, w))
        x = jnp.swapaxes(x, 1, 2)
        return jnp.reshape(x, (b, c, h, w))
    b, h, w, c = x.shape
    x = jnp.reshape(x, (b, h, w, groups, c // groups))
    x = jnp.swapaxes(x, 3, 4)
    return jnp.reshape(x, (b, h, w, c))


@eager_op
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    b, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            sl = x[:, :, i * dh:i * dh + (oh - 1) * sh + 1:sh,
                   j * dw:j * dw + (ow - 1) * sw + 1:sw]
            patches.append(sl)
    out = jnp.stack(patches, axis=2)  # [b, c, kh*kw, oh, ow]
    return jnp.reshape(out, (b, c * kh * kw, oh * ow))


@eager_op
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW"):
    if data_format == "NCHW":
        spatial = x.shape[2:]
        chan_first = True
    else:
        spatial = x.shape[1:-1]
        chan_first = False
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    size = [int(unwrap(s)) for s in size]
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    if chan_first:
        out_shape = x.shape[:2] + tuple(size)
    else:
        out_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    return jax.image.resize(x, out_shape, method=jmode)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, data_format="NCHW", name=None):
    return interpolate(x, size=size, scale_factor=scale_factor, mode=mode,
                       align_corners=align_corners, data_format=data_format)


@eager_op
def affine_grid(theta, out_shape, align_corners=True):
    n, _, h, w = [int(s) for s in out_shape]
    if align_corners:
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
    else:
        ys = (jnp.arange(h) + 0.5) * 2 / h - 1
        xs = (jnp.arange(w) + 0.5) * 2 / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    grid = jnp.stack([gx, gy, ones], axis=-1)  # [h, w, 3]
    out = jnp.einsum("hwk,nik->nhwi", grid, theta)
    return out


@eager_op
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    b, ckk, L = x.shape
    c = ckk // (kh * kw)
    nh = (oh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    nw = (ow + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    x = jnp.reshape(x, (b, c, kh, kw, nh, nw))
    out = jnp.zeros((b, c, oh + 2 * ph, ow + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :, i * dh:i * dh + (nh - 1) * sh + 1:sh,
                         j * dw:j * dw + (nw - 1) * sw + 1:sw].add(x[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


# Public surface



# -- round-4 vision/common additions ----------------------------------------

@eager_op
def zeropad2d(x, padding, data_format="NCHW"):
    """Zero-pad H/W (reference zeropad2d; padding = int or
    [left, right, top, bottom])."""
    if isinstance(padding, int):
        padding = (padding,) * 4
    left, right, top, bottom = padding
    if data_format == "NCHW":
        cfg = [(0, 0), (0, 0), (top, bottom), (left, right)]
    else:
        cfg = [(0, 0), (top, bottom), (left, right), (0, 0)]
    return jnp.pad(x, cfg)


@eager_op
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """TSM temporal shift (reference temporal_shift: fold_div channels
    shift to t-1, the next fold to t+1, rest stay)."""
    if data_format != "NCHW":
        raise NotImplementedError("temporal_shift: NCHW only")
    nt, c, h, w = x.shape
    n = nt // seg_num
    fold = int(c * shift_ratio)
    xr = x.reshape(n, seg_num, c, h, w)
    back = jnp.concatenate(
        [xr[:, 1:, :fold], jnp.zeros_like(xr[:, :1, :fold])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, fold:2 * fold]),
         xr[:, :-1, fold:2 * fold]], axis=1)
    out = jnp.concatenate([back, fwd, xr[:, :, 2 * fold:]], axis=2)
    return out.reshape(nt, c, h, w)


@eager_op
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """Spatial sampling at normalized grid locations (reference
    grid_sample: x NCHW, grid [N, Hg, Wg, 2] with (x, y) in [-1, 1]).
    bilinear/nearest; zeros/border padding."""
    if mode not in ("bilinear", "nearest"):
        raise NotImplementedError(f"grid_sample mode {mode}")
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(f"grid_sample padding {padding_mode}")
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]

    def unnorm(g, size):
        if align_corners:
            return (g + 1.0) * 0.5 * (size - 1)
        return ((g + 1.0) * size - 1.0) * 0.5

    fx, fy = unnorm(gx, w), unnorm(gy, h)            # [N, Hg, Wg]

    def fetch(ix, iy):
        """x[n, :, iy, ix] with padding handling → [N, Hg, Wg, C]."""
        inside = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        cx = jnp.clip(ix, 0, w - 1)
        cy = jnp.clip(iy, 0, h - 1)
        batch = jnp.arange(n)[:, None, None]
        vals = x.transpose(0, 2, 3, 1)[batch, cy, cx]  # [N,Hg,Wg,C]
        if padding_mode == "zeros":
            vals = jnp.where(inside[..., None], vals, 0.0)
        return vals

    if mode == "nearest":
        out = fetch(jnp.round(fx).astype(jnp.int32),
                    jnp.round(fy).astype(jnp.int32))
        return out.transpose(0, 3, 1, 2).astype(x.dtype)

    x0 = jnp.floor(fx).astype(jnp.int32)
    y0 = jnp.floor(fy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx = (fx - x0)[..., None]
    wy = (fy - y0)[..., None]
    out = (fetch(x0, y0) * (1 - wx) * (1 - wy)
           + fetch(x1, y0) * wx * (1 - wy)
           + fetch(x0, y1) * (1 - wx) * wy
           + fetch(x1, y1) * wx * wy)
    return out.transpose(0, 3, 1, 2).astype(x.dtype)


__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
__all__.append("pad")
