"""Loss functionals (parity: python/paddle/nn/functional/loss.py).

cross_entropy keeps logits in fp32 for the softmax (TPU numerics), computes
log-softmax fused — this is the op the reference implements as
c_softmax_with_cross_entropy for TP; the sharded variant lives in
paddle_tpu/distributed/tp.py."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def _ce_route_counter():
    from paddle_tpu.observability import default_registry
    return default_registry().counter(
        "paddle_tpu_fused_ce_calls_total",
        "cross_entropy routing decisions by path (counted at trace time)",
        labelnames=("path",))


@eager_op
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    # Fused Pallas fast path (hard labels, no class weights): the vocab
    # axis streams through VMEM blockwise, so neither the fp32
    # log-softmax nor the one-hot backward ever materializes at
    # [batch, seq, vocab].  MUST route before the fp32 cast below — the
    # cast is itself the [B, S, V] fp32 intermediate being avoided.
    if (use_softmax and not soft_label and weight is None
            and label_smoothing == 0.0 and input.ndim >= 2
            and axis in (-1, input.ndim - 1)):
        lbl = label
        if lbl.ndim == input.ndim and lbl.shape[-1] == 1:
            lbl = jnp.squeeze(lbl, axis=-1)
        v = input.shape[-1]
        if lbl.ndim == input.ndim - 1 and \
                jnp.issubdtype(lbl.dtype, jnp.integer):
            from paddle_tpu.ops.pallas.cross_entropy import (
                fused_ce_eligible, fused_ce_enabled,
                fused_softmax_cross_entropy)
            t = int(lbl.size)
            if fused_ce_enabled() and fused_ce_eligible(t, v):
                _ce_route_counter().labels(path="fused").inc()
                valid = lbl != ignore_index
                safe = jnp.where(valid, lbl, 0)
                per = fused_softmax_cross_entropy(
                    input.reshape(-1, v), safe.reshape(-1))
                loss = jnp.where(valid, per.reshape(lbl.shape), 0.0)
                if reduction == "mean":
                    denom = jnp.maximum(
                        jnp.sum(valid.astype(jnp.float32)), 1.0)
                    return jnp.sum(loss) / denom
                return _reduce(loss, reduction)
            _ce_route_counter().labels(path="fallback").inc()
    x = input.astype(jnp.float32)
    if use_softmax:
        logp = jax.nn.log_softmax(x, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(x, 1e-30))
    n_classes = x.shape[axis]

    if soft_label:
        tgt = label.astype(jnp.float32)
        if label_smoothing > 0:
            tgt = (1 - label_smoothing) * tgt + label_smoothing / n_classes
        loss = -jnp.sum(tgt * logp, axis=axis)
        if weight is not None:
            w = jnp.sum(tgt * weight, axis=axis)
            loss = loss * w
            # weighted mean divides by the sum of weights (matching the
            # hard-label branch below), not the element count
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        return _reduce(loss, reduction)

    lbl = label
    if lbl.ndim == x.ndim and lbl.shape[axis] == 1:
        lbl = jnp.squeeze(lbl, axis=axis)
    valid = lbl != ignore_index
    safe_lbl = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe_lbl, axis), axis=axis)
    picked = jnp.squeeze(picked, axis=axis)
    if label_smoothing > 0:
        smooth = jnp.mean(logp, axis=axis)
        picked = (1 - label_smoothing) * picked + label_smoothing * smooth
    loss = -picked
    if weight is not None:
        w = jnp.take(weight, safe_lbl)
        loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


@eager_op
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False,
                               axis=-1):
    x = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(x, axis=axis)
    if soft_label:
        loss = -jnp.sum(label.astype(jnp.float32) * logp, axis=axis,
                        keepdims=True)
    else:
        lbl = label
        squeeze = lbl.ndim == x.ndim and lbl.shape[axis] == 1
        if squeeze:
            lbl = jnp.squeeze(lbl, axis=axis)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.squeeze(jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis), axis=axis)
        loss = jnp.where(valid, -picked, 0.0)[..., None]
    if return_softmax:
        return loss, jax.nn.softmax(x, axis=axis)
    return loss


@eager_op
def mse_loss(input, label, reduction="mean"):
    return _reduce(jnp.square(input - label), reduction)


@eager_op
def l1_loss(input, label, reduction="mean"):
    return _reduce(jnp.abs(input - label), reduction)


@eager_op
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = input - label
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return _reduce(loss, reduction)


@eager_op
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    valid = label != ignore_index
    safe = jnp.where(valid, label, 0)
    picked = jnp.take_along_axis(input, safe[..., None] if input.ndim == 2
                                 else jnp.expand_dims(safe, 1), axis=1)
    picked = jnp.squeeze(picked, axis=1)
    loss = -picked
    if weight is not None:
        w = jnp.take(weight, safe)
        loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.sum(jnp.where(valid, w, 0.0))
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(
            jnp.sum(valid.astype(jnp.float32)), 1.0)
    return _reduce(loss, reduction)


@eager_op
def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    x = jnp.clip(input.astype(jnp.float32), 1e-12, 1 - 1e-12)
    loss = -(label * jnp.log(x) + (1 - label) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@eager_op
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    x = logit.astype(jnp.float32)
    neg_abs = -jnp.abs(x)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * x + log_w * (jnp.log1p(jnp.exp(neg_abs)) +
                                          jnp.maximum(-x, 0))
    else:
        loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(neg_abs))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@eager_op
def kl_div(input, label, reduction="mean", log_target=False):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        safe = jnp.maximum(label, 1e-12)
        loss = label * (jnp.log(safe) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@eager_op
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.maximum(-label * (input - other) + margin, 0.0)
    return _reduce(loss, reduction)


@eager_op
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1, input, jnp.maximum(margin - input, 0.0))
    return _reduce(loss, reduction)


@eager_op
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
    return _reduce(loss, reduction)


@eager_op
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.sum(jnp.abs(a - b) ** p + epsilon, axis=-1) ** (1.0 / p)
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn2 = dist(positive, negative)
        dn = jnp.minimum(dn, dn2)
    loss = jnp.maximum(dp - dn + margin, 0.0)
    return _reduce(loss, reduction)


@eager_op
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    # log_probs: [T, B, C] (paddle layout) — use a scan over time with the
    # standard alpha recursion in log space; static shapes for XLA.
    T, B, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    lp = log_probs.astype(jnp.float32)

    # extended label sequence with blanks: [B, S]
    ext = jnp.full((B, S), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    neg_inf = jnp.asarray(-1e30, jnp.float32)

    # transition allowed from s-2 when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)), constant_values=-1)
    allow_skip = (ext != blank) & (ext != ext_prev2)

    def emit(t_lp, s_idx):
        return jnp.take_along_axis(t_lp, s_idx, axis=1)

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(emit(lp[0], ext[:, 0:1])[:, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(L > 0, emit(lp[0], ext[:, 1:2])[:, 0], neg_inf))

    def step(alpha, t_lp):
        a_prev = alpha
        a_shift1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)),
                           constant_values=-1e30)
        a_shift2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)),
                           constant_values=-1e30)
        a_shift2 = jnp.where(allow_skip, a_shift2, neg_inf)
        m = jnp.maximum(jnp.maximum(a_prev, a_shift1), a_shift2)
        m_safe = jnp.maximum(m, -1e29)
        # states with NO live incoming path have sum_exp == 0; log(0)
        # is -inf and its 1/0 cotangent turns the whole backward pass
        # NaN, so floor the sum and re-mask the result to the finite
        # sentinel (the floor keeps the log's gradient finite even for
        # the branch jnp.where does not select)
        sum_exp = (jnp.exp(a_prev - m_safe) + jnp.exp(a_shift1 - m_safe)
                   + jnp.exp(a_shift2 - m_safe))
        tot = jnp.where(
            m <= -1e29, neg_inf,
            m_safe + jnp.log(jnp.maximum(sum_exp, 1e-30)))
        new_alpha = tot + emit(t_lp, ext)
        return new_alpha, new_alpha

    _, alphas = jax.lax.scan(step, alpha0, lp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]

    # gather alpha at t = input_length-1, s = 2*label_length and 2*label_length-1
    t_idx = jnp.clip(input_lengths - 1, 0, T - 1)
    per_b = jnp.take_along_axis(
        alphas, t_idx[None, :, None], axis=0)[0]  # [B, S]
    s1 = jnp.clip(2 * label_lengths, 0, S - 1)
    s2 = jnp.clip(2 * label_lengths - 1, 0, S - 1)
    a1 = jnp.take_along_axis(per_b, s1[:, None], axis=1)[:, 0]
    a2 = jnp.take_along_axis(per_b, s2[:, None], axis=1)[:, 0]
    m = jnp.maximum(a1, a2)
    m_safe = jnp.maximum(m, -1e29)
    sum_exp = jnp.exp(a1 - m_safe) + jnp.exp(a2 - m_safe)
    ll = jnp.where(m <= -1e29, neg_inf,
                   m_safe + jnp.log(jnp.maximum(sum_exp, 1e-30)))
    loss = -ll
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(label_lengths, 1))
    return _reduce(loss, reduction)


@eager_op
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    p = jax.nn.sigmoid(logit.astype(jnp.float32))
    ce = jnp.maximum(logit, 0) - logit * label + jnp.log1p(
        jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@eager_op
def square_error_cost(input, label):
    return jnp.square(input - label)


# (the public __all__ is computed once at the end of the module)


@eager_op
def fused_linear_cross_entropy(hidden, weight, labels, chunk_size=8192,
                               reduction="mean", ignore_index=-100):
    """Fused lm-head + softmax cross-entropy over vocab chunks.

    Reference role: the fused softmax-with-cross-entropy kernels
    (phi/kernels/fusion, fused c_softmax_with_cross_entropy) — the lm-head
    logits [T, V] are never materialized in fp32: the forward scans vocab
    chunks with an online logsumexp, the backward recomputes each chunk's
    probabilities and accumulates dh / dW on the fly (the chunked-CE
    memory trick; trades one extra lm-head matmul for O(T*V) activation
    memory, which is what bounds single-chip batch size).

    hidden: [T, d] (flatten batch x seq first); weight: [d, V];
    labels: [T] int (ignore_index entries contribute no loss/grad).
    Differentiable wrt hidden and weight.
    """
    lbl = jnp.asarray(labels).astype(jnp.int32)
    mask = lbl != ignore_index
    safe = jnp.where(mask, lbl, 0)
    per_tok = _fused_ce(hidden, weight, safe, chunk_size)
    # zeroing outside the custom_vjp also zeroes the pad cotangents, so
    # ignored tokens contribute neither loss nor dh/dW
    per_tok = jnp.where(mask, per_tok, 0.0)
    if reduction == "mean":
        return per_tok.sum() / jnp.maximum(mask.sum(), 1)
    return _reduce(per_tok, reduction)


from functools import partial as _partial  # noqa: E402


@_partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ce(h, w, lbl, chunk_size):
    lse, gold = _fused_ce_scan(h, w, lbl, chunk_size)
    return lse - gold


def _padded_weight(w, chunk_size):
    """Pad the vocab axis up to a chunk multiple (no relayout — steps
    dynamic_slice their chunk out; padding columns are masked)."""
    v = w.shape[1]
    n = -(-v // chunk_size)
    pad = n * chunk_size - v
    wp = w if pad == 0 else jnp.pad(w, ((0, 0), (0, pad)),
                                    constant_values=0.0)
    return wp, n


def _take_chunk(wp, ci, chunk_size):
    return jax.lax.dynamic_slice(wp, (0, ci * chunk_size),
                                 (wp.shape[0], chunk_size))


def _fused_ce_scan(h, w, lbl, chunk_size):
    """Online logsumexp over vocab chunks; also gathers the gold logit."""
    hf = h.astype(jnp.float32)
    wp, n = _padded_weight(w, chunk_size)
    v = w.shape[1]

    def step(carry, ci):
        m, s, gold = carry
        wchunk = _take_chunk(wp, ci, chunk_size)
        logits = hf @ wchunk.astype(jnp.float32)       # [T, c]
        base = ci * chunk_size
        col = jnp.arange(chunk_size)[None, :] + base
        valid = col < v
        logits = jnp.where(valid, logits, -jnp.inf)
        cm = jnp.maximum(m, logits.max(axis=1))
        s = s * jnp.exp(m - cm) + jnp.exp(logits - cm[:, None]).sum(axis=1)
        local = lbl[:, None] - base
        hit = (local == jnp.arange(chunk_size)[None, :]) & valid
        gold = gold + jnp.where(hit, logits, 0.0).sum(axis=1)
        return (cm, s, gold), None

    t = hf.shape[0]
    init = (jnp.full((t,), -jnp.inf, jnp.float32),
            jnp.zeros((t,), jnp.float32), jnp.zeros((t,), jnp.float32))
    (m, s, gold), _ = jax.lax.scan(step, init, jnp.arange(n))
    return m + jnp.log(s), gold


def _fused_ce_fwd(h, w, lbl, chunk_size):
    lse, gold = _fused_ce_scan(h, w, lbl, chunk_size)
    return lse - gold, (h, w, lbl, lse)


def _fused_ce_bwd(chunk_size, res, g):
    h, w, lbl, lse = res
    hf = h.astype(jnp.float32)
    wp, n = _padded_weight(w, chunk_size)
    v = w.shape[1]
    gf = g.astype(jnp.float32)

    def step(carry, ci):
        dh, dw = carry
        wchunk = _take_chunk(wp, ci, chunk_size).astype(jnp.float32)
        logits = hf @ wchunk                           # [T, c]
        base = ci * chunk_size
        col = jnp.arange(chunk_size)[None, :] + base
        valid = col < v
        p = jnp.where(valid, jnp.exp(logits - lse[:, None]), 0.0)
        local = lbl[:, None] - base
        onehot = ((local == jnp.arange(chunk_size)[None, :]) & valid) \
            .astype(jnp.float32)
        delta = (p - onehot) * gf[:, None]             # [T, c]
        dh = dh + delta @ wchunk.T
        dw_chunk = hf.T @ delta                        # [d, c]
        dw = jax.lax.dynamic_update_slice(
            dw, dw_chunk, (0, ci * chunk_size))
        return (dh, dw), None

    dh0 = jnp.zeros_like(hf)
    dw0 = jnp.zeros(wp.shape, jnp.float32)
    (dh, dw), _ = jax.lax.scan(step, (dh0, dw0), jnp.arange(n))
    return dh.astype(h.dtype), dw[:, :v].astype(w.dtype), None


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


# recompute the public surface to include the fused loss above



# -- round-4 loss additions (reference python/paddle/nn/functional/loss.py) --

@eager_op
def huber_loss(input, label, delta=1.0, reduction="mean"):
    """Reference huber_loss: quadratic inside |d|<=delta, linear outside
    (smooth_l1 without the 1/delta normalization)."""
    d = input - label
    ad = jnp.abs(d)
    loss = jnp.where(ad <= delta, 0.5 * d * d,
                     delta * (ad - 0.5 * delta))
    return _reduce(loss, reduction)


@eager_op
def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):
    """Poisson negative log likelihood (reference poisson_nll_loss)."""
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(label) - label + \
            0.5 * jnp.log(2.0 * jnp.pi * label)
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


@eager_op
def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    """Gaussian negative log likelihood with predicted variance
    (reference gaussian_nll_loss)."""
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(input - label) / var)
    if full:
        loss = loss + 0.5 * jnp.log(2.0 * jnp.pi)
    return _reduce(loss, reduction)


@eager_op
def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    """Multi-class margin loss (reference multi_margin_loss):
    mean_j!=y max(0, margin - x_y + x_j)^p."""
    n, c = input.shape
    x_y = jnp.take_along_axis(input, label[:, None], axis=1)   # [N, 1]
    viol = jnp.maximum(0.0, margin - x_y + input) ** p         # [N, C]
    if weight is not None:
        viol = viol * jnp.take(weight, label)[:, None]
    mask = jnp.arange(c)[None, :] != label[:, None]
    loss = jnp.sum(jnp.where(mask, viol, 0.0), axis=1) / c
    return _reduce(loss, reduction)


@eager_op
def log_loss(input, label, epsilon=1e-4):
    """Binary log loss on probabilities (reference log_loss)."""
    return -label * jnp.log(input + epsilon) \
        - (1.0 - label) * jnp.log(1.0 - input + epsilon)


@eager_op
def dice_loss(input, label, epsilon=1e-5):
    """Dice loss over softmax probabilities (reference dice_loss:
    input [N, ..., C] probs, label [N, ..., 1] int)."""
    lbl = jnp.squeeze(label, axis=-1)
    onehot = jax.nn.one_hot(lbl, input.shape[-1], dtype=input.dtype)
    reduce_axes = tuple(range(1, input.ndim))
    inter = jnp.sum(input * onehot, axis=reduce_axes)
    union = jnp.sum(input, axis=reduce_axes) + \
        jnp.sum(onehot, axis=reduce_axes)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


@eager_op
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair loss (reference npair_loss): CE over anchor@positive.T
    similarities + L2 on the embeddings."""
    sim = anchor @ positive.T                              # [N, N]
    n = sim.shape[0]
    logp = jax.nn.log_softmax(sim, axis=1)
    same = labels[:, None] == labels[None, :]
    w = same.astype(sim.dtype)
    w = w / jnp.sum(w, axis=1, keepdims=True)
    ce = -jnp.mean(jnp.sum(w * logp, axis=1))
    # reference coefficient: Beta = 0.25 (npair_loss l2loss term)
    reg = l2_reg * 0.25 * (jnp.mean(jnp.sum(jnp.square(anchor), axis=1))
                           + jnp.mean(jnp.sum(jnp.square(positive), axis=1)))
    return ce + reg


@eager_op
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    """p-norm of x - y along the last axis (reference
    nn/functional/distance.py)."""
    import math
    # epsilon is added to the SIGNED difference before |.| (reference adds
    # it to sub = x - y + eps), so negative components match bit-for-bit
    d = jnp.abs((x - y) + epsilon)
    if isinstance(p, (int, float)) and math.isinf(p):
        out = jnp.max(d, axis=-1) if p > 0 else jnp.min(d, axis=-1)
    else:
        out = jnp.sum(d ** p, axis=-1) ** (1.0 / p)
    return out[..., None] if keepdim else out


@eager_op
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, return_softmax=False,
                         reduction="mean"):
    """ArcFace-family margin softmax (reference margin_cross_entropy:
    target cos(theta) -> cos(margin1*theta + margin2) - margin3, scaled).
    `logits` are cosine similarities in [-1, 1]."""
    onehot = jax.nn.one_hot(label, logits.shape[-1], dtype=logits.dtype)
    cos = jnp.clip(logits, -1.0, 1.0)
    theta = jnp.arccos(cos)
    target = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = jnp.where(onehot > 0, target, cos) * scale
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.sum(onehot * logp, axis=-1)
    loss = _reduce(loss, reduction)
    if return_softmax:
        return loss, jax.nn.softmax(adjusted, axis=-1)
    return loss


__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
