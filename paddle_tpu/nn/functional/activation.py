"""Activation functions (parity: python/paddle/nn/functional/activation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op

relu = eager_op(name="relu")(jax.nn.relu)
relu6 = eager_op(name="relu6")(jax.nn.relu6)
sigmoid = eager_op(name="sigmoid")(jax.nn.sigmoid)
tanh = eager_op(name="tanh")(jnp.tanh)
silu = eager_op(name="silu")(jax.nn.silu)
swish = silu
mish = eager_op(name="mish")(lambda x: x * jnp.tanh(jax.nn.softplus(x)))
hardswish = eager_op(name="hardswish")(jax.nn.hard_swish)
hardsigmoid = eager_op(name="hardsigmoid")(
    lambda x, slope=1.0 / 6, offset=0.5: jnp.clip(x * slope + offset, 0, 1))
hardtanh = eager_op(name="hardtanh")(
    lambda x, min=-1.0, max=1.0: jnp.clip(x, min, max))
elu = eager_op(name="elu")(lambda x, alpha=1.0: jax.nn.elu(x, alpha))
celu = eager_op(name="celu")(lambda x, alpha=1.0: jax.nn.celu(x, alpha))
selu = eager_op(name="selu")(
    lambda x, scale=1.0507009873554805, alpha=1.6732632423543772:
    scale * jnp.where(x > 0, x, alpha * jnp.expm1(x)))
leaky_relu = eager_op(name="leaky_relu")(
    lambda x, negative_slope=0.01: jax.nn.leaky_relu(x, negative_slope))
softplus = eager_op(name="softplus")(
    lambda x, beta=1.0, threshold=20.0:
    jnp.where(x * beta > threshold, x, jax.nn.softplus(x * beta) / beta))
softsign = eager_op(name="softsign")(jax.nn.soft_sign)
tanhshrink = eager_op(name="tanhshrink")(lambda x: x - jnp.tanh(x))
log_sigmoid = eager_op(name="log_sigmoid")(jax.nn.log_sigmoid)


@eager_op
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@eager_op
def softmax(x, axis=-1, dtype=None):
    from paddle_tpu.core.dtypes import to_jax
    if dtype is not None:
        x = x.astype(to_jax(dtype))
    return jax.nn.softmax(x, axis=axis)


@eager_op
def log_softmax(x, axis=-1, dtype=None):
    from paddle_tpu.core.dtypes import to_jax
    if dtype is not None:
        x = x.astype(to_jax(dtype))
    return jax.nn.log_softmax(x, axis=axis)


@eager_op
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@eager_op
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, jnp.zeros((), x.dtype))


@eager_op
def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, jnp.asarray(value, x.dtype))


@eager_op
def prelu(x, weight, data_format="NCHW"):
    w = weight
    if w.ndim == 1 and w.shape[0] > 1 and x.ndim > 1:
        ch_axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape = [1] * x.ndim
        shape[ch_axis] = w.shape[0]
        w = jnp.reshape(w, shape)
    return jnp.where(x >= 0, x, w * x)


@eager_op
def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False):
    # eval mode (and deterministic training fallback): use the mean slope
    slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


@eager_op
def maxout(x, groups, axis=1):
    axis = axis % x.ndim
    c = x.shape[axis]
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(jnp.reshape(x, new_shape), axis=axis + 1)


@eager_op
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@eager_op
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    # deterministic variant without key (eager path adds gumbel noise upstream)
    y = jax.nn.softmax(x / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.zeros_like(y)
        onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis,
                                    inplace=False) if hasattr(jnp, "put_along_axis") else \
            onehot.at[..., :].set(jnp.where(
                jnp.arange(y.shape[axis]) == idx, 1.0, 0.0))
        y = onehot + jax.lax.stop_gradient(-y) + y
    return y


# Public surface
__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and (hasattr(_v, "__wrapped_pure__")
                or getattr(_v, "__module__", None) == __name__)]
