"""Attention functionals.

Parity targets: python/paddle/nn/functional/flash_attention.py (reference
routes to _C_ops.flash_attn, a CUDA kernel) and scaled_dot_product_attention.
TPU-native: the hot path routes to a Pallas flash-attention kernel when on
TPU (paddle_tpu/ops/pallas/flash_attention.py); the reference XLA fallback
(below) is used on CPU and for odd shapes — XLA fuses it well regardless.

Layout convention is paddle's: [batch, seq, num_heads, head_dim].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op


def _sdpa_reference(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
                    scale=None, dropout_key=None):
    # GQA/MQA: this path materializes s×s scores anyway, so repeating KV
    # costs nothing extra (the Pallas path never repeats)
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # [b, s, h, d] → [b, h, s, d]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    # fp32 softmax accumulation (TPU numerics practice for bf16 inputs)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(causal, scores, -1e30)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -1e30)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros((), probs.dtype)).astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def _use_pallas(q_shape, head_dim):
    import jax as _j
    if _j.default_backend() != "tpu":
        return False
    # pallas kernel wants lane-aligned head_dim and block-aligned seq
    # (block sizes of >=128 and seq % block == 0).  Even at sequence
    # lengths where XLA's fused dense attention is FASTER in isolation
    # (below ~4k on v5e), flash is what lets the training step fit: the
    # dense path materializes the [b, h, s, s] score tensor per layer and
    # the remat policy keeps those dot outputs live (at the bench model's
    # shapes the dense variant fails to even compile on a 16 GB chip).
    # Backward-implementation and block-size choice are autotuned
    # (ops/pallas/autotune.py); at 8k+ flash also wins outright (6.4x).
    return head_dim % 128 == 0 and q_shape[1] >= 128 and \
        q_shape[1] % 128 == 0


@eager_op
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None):
    use_dropout = dropout_p > 0.0 and training
    hd = query.shape[-1]
    if (attn_mask is None and not use_dropout
            and query.shape[1] == key.shape[1]
            and hd in (32, 64)
            and query.shape[1] >= 1024
            and _use_pallas(query.shape[:-1] + (128,), 128)):
        # lane-alignment shim for BERT/ERNIE-class head_dim: zero-pad the
        # head dim to 128 and slice the output back — numerically EXACT
        # (zero pads contribute nothing to q@k^T or probs@v; the softmax
        # scale pins to the true head_dim) and autodiff slices the pad
        # grads away.  Costs extra MXU lanes but keeps the O(s) memory
        # of flash.  Gated to seq >= 1024: below that, XLA's dense
        # attention is FASTER on v5e (measured: ERNIE b64 s512 padded
        # flash 0.188 MFU vs dense 0.265 at b32) and the [b,h,s,s] probs
        # it saves are still affordable; at long seq flash is both the
        # memory story and the speed story.
        pad = [(0, 0)] * 3 + [(0, 128 - hd)]
        qp, kp, vp = (jnp.pad(t, pad) for t in (query, key, value))
        out = scaled_dot_product_attention(
            qp, kp, vp, attn_mask=None, dropout_p=0.0,
            is_causal=is_causal, training=training,
            scale=scale if scale is not None else hd ** -0.5)
        return out[..., :hd]
    if attn_mask is None and not use_dropout and \
            query.shape[1] == key.shape[1] and \
            _use_pallas(query.shape, query.shape[-1]):
        # no try/except: a lowering break in the flagship kernel must
        # surface, not silently fall back (round-1 lesson).
        # Backward implementation: blockwise-jax recompute, pinned from
        # IN-MODEL measurement on v5e (bench.py +
        # benchmarks/llama_seq_bench.py, full train step, both variants):
        #   b4/s2048: 0.514 vs 0.461   b2/s4096: 0.404 vs 0.361
        #   b1/s8192 (remat): 0.241 vs 0.218
        # — no crossover up to 8k: XLA fuses the recompute chain into the
        # surrounding step better than the separate dq + dkv Pallas
        # dispatches (two extra HBM passes over q/k/v/g).  The Pallas
        # backward kernels remain available (pallas_bwd=True /
        # PADDLE_TPU_FLASH_BWD=1, legacy alias PT_FLASH_PALLAS_BWD) and
        # win in ISOLATED microbenches (benchmarks/pallas_kernels_bench
        # .py) — a documented niche: standalone attention grads without
        # a surrounding fusable step.
        from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                           flash_bwd_env)
        pb = flash_bwd_env()
        return flash_attention(query, key, value, causal=is_causal,
                               scale=scale,
                               pallas_bwd=False if pb is None else pb)
    dk = None
    if use_dropout:
        from paddle_tpu.core import functional as _cf
        from paddle_tpu.core import state as _cs
        dk = _cf.next_functional_key("dropout")
        if dk is None:
            dk = _cs.next_key()
    return _sdpa_reference(query, key, value, attn_mask, dropout_p,
                           is_causal, scale, dropout_key=dk)


@eager_op
def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True):
    """paddle.nn.functional.flash_attention parity: returns (out, softmax)."""
    out = None
    if _use_pallas(query.shape, query.shape[-1]):
        try:
            from paddle_tpu.ops.pallas.flash_attention import flash_attention \
                as _fa
            out = _fa(query, key, value, causal=causal)
        except Exception:
            out = None
    if out is None:
        out = _sdpa_reference(query, key, value, None, dropout, causal)
    return out, None


@eager_op
def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True):
    """Variable-length packed attention (reference:
    nn/functional/flash_attention.py flash_attn_unpadded — FlashAttention's
    varlen kernel over cu_seqlens-packed sequences).

    q/k/v: [total_tokens, num_heads, head_dim] with sequences concatenated;
    cu_seqlens_*: [batch+1] int32 prefix offsets.  TPU-native realisation:
    segment-id block masking over the packed token axis — XLA fuses the
    mask into the attention matmuls, and cross-sequence pairs are masked
    exactly like the reference kernel skips them.  Memory is O(total^2)
    (dense scores) — fine for packed batches up to a few thousand tokens;
    larger packs should run the Pallas flash path with segment ids.
    Causal masking is bottom-right aligned (flash-attn >= 2.1 varlen
    semantics).  Returns (out, softmax).
    """
    tq, h, d = query.shape
    tk = key.shape[0]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    # segment id of each packed token: seg[i] = #offsets <= i  (tokens past
    # the last offset land in segment batch+1 == padding, matching nothing)
    pos_q = jnp.arange(tq)
    pos_k = jnp.arange(tk)
    seg_q = jnp.searchsorted(cu_seqlens_q.astype(jnp.int32), pos_q,
                             side="right")
    seg_k = jnp.searchsorted(cu_seqlens_k.astype(jnp.int32), pos_k,
                             side="right")
    # position within the sequence (for causal masking)
    start_q = cu_seqlens_q[jnp.clip(seg_q - 1, 0, None)]
    start_k = cu_seqlens_k[jnp.clip(seg_k - 1, 0, None)]
    rel_q = pos_q - start_q
    rel_k = pos_k - start_k

    mask = seg_q[:, None] == seg_k[None, :]
    if causal:
        # bottom-right alignment (flash-attn >= 2.1 varlen semantics):
        # when a sequence has fewer queries than keys (decode with cache),
        # the last query aligns with the last key.  The shift is per
        # SEQUENCE, gathered onto each query token via its segment id.
        seq_len_q = cu_seqlens_q[1:] - cu_seqlens_q[:-1]   # [batch]
        seq_len_k = cu_seqlens_k[1:] - cu_seqlens_k[:-1]
        nb = seq_len_q.shape[0]
        shift = (seq_len_k - seq_len_q)[jnp.clip(seg_q - 1, 0, nb - 1)]
        mask = mask & ((rel_q + shift)[:, None] >= rel_k[None, :])

    qf = query.astype(jnp.float32) * scale
    scores = jnp.einsum("qhd,khd->hqk", qf, key.astype(jnp.float32))
    scores = jnp.where(mask[None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout > 0.0 and training:
        from paddle_tpu.core import state as _cs
        keyr = _cs.next_key()
        keep = jax.random.bernoulli(keyr, 1.0 - dropout, probs.shape)
        probs = probs * keep / (1.0 - dropout)
    out = jnp.einsum("hqk,khd->qhd", probs, value.astype(jnp.float32))
    out = out.astype(query.dtype)
    return (out, probs if return_softmax else None)


def rotary_freqs(head_dim, max_position, base=10000.0, dtype=jnp.float32):
    """Precompute RoPE cos/sin tables, each [max_position, head_dim//2]."""
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                          / head_dim))
    t = jnp.arange(max_position, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


@eager_op
def apply_rotary_emb(x, cos, sin, position_offset=0):
    """Rotary position embedding, Llama/NeoX half-rotation convention.

    x: [batch, seq, heads, head_dim]; cos/sin: [max_pos, head_dim//2] tables
    from rotary_freqs.  position_offset shifts positions (decode w/ KV cache).
    Computed in fp32 then cast back (TPU bf16 numerics practice).
    """
    seq = x.shape[1]
    if isinstance(position_offset, int) and position_offset + seq > cos.shape[0]:
        raise ValueError(
            f"RoPE table overflow: positions [{position_offset}, "
            f"{position_offset + seq}) exceed table length {cos.shape[0]} "
            f"(max_position_embeddings)")
    if getattr(position_offset, "ndim", 0) == 1:
        # per-row offsets [B] (continuous-batching decode: every slot sits
        # at its own position) — gather per-(row, step) tables.  NOTE:
        # traced offsets can't be range-checked here; an out-of-table
        # position CLAMPS to the last row (jax gather semantics) instead
        # of raising like the scalar path — drivers must bound positions
        # against the table (ContinuousBatchingEngine validates max_len
        # at construction)
        pos = position_offset[:, None] + jnp.arange(seq)[None]   # [B, s]
        cos = cos[pos][:, :, None, :]                            # [B,s,1,h]
        sin = sin[pos][:, :, None, :]
    else:
        cos = jax.lax.dynamic_slice_in_dim(cos, position_offset, seq, 0)
        sin = jax.lax.dynamic_slice_in_dim(sin, position_offset, seq, 0)
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    half = x.shape[-1] // 2
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "rotary_freqs", "apply_rotary_emb"]
