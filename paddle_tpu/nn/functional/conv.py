"""Convolutions (parity: python/paddle/nn/functional/conv.py).

All lower to XLA conv_general_dilated — the MXU path for conv models
(PP-OCRv4-class networks).  Weight layout follows paddle: [out_c, in_c/groups,
*spatial]; data_format NCHW (default) or NHWC.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op


def _ntuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    if len(t) == 1:
        return t * n
    return t


def _padding(pad, n):
    if isinstance(pad, str):
        return pad.upper()  # SAME / VALID
    if isinstance(pad, int):
        return [(pad, pad)] * n
    pad = list(pad)
    if len(pad) == n and all(isinstance(p, int) for p in pad):
        return [(p, p) for p in pad]
    if len(pad) == 2 * n:
        return [(pad[2 * i], pad[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in pad]


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, transpose=False, output_padding=0, output_size=None):
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - n:]
        out_spec = lhs_spec
    else:
        lhs_spec = "N" + "DHW"[3 - n:] + "C"
        out_spec = lhs_spec
    rhs_spec = "OI" + "DHW"[3 - n:]
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, (lhs_spec, rhs_spec, out_spec))
    stride = _ntuple(stride, n)
    dilation = _ntuple(dilation, n)
    pad = _padding(padding, n)

    if not transpose:
        out = jax.lax.conv_general_dilated(
            x, weight, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
    else:
        # conv_transpose: lhs_dilation= stride implements fractional stride
        opad = _ntuple(output_padding, n)
        k = weight.shape[2:]
        if isinstance(pad, str):
            raise ValueError("string padding unsupported for conv_transpose")
        # transpose padding: p' = dilation*(k-1) - p
        tpad = [(dilation[i] * (k[i] - 1) - pad[i][0],
                 dilation[i] * (k[i] - 1) - pad[i][1] + opad[i])
                for i in range(n)]
        # weight [in, out/groups, *k] for paddle transpose convs → flip to
        # [out, in/groups, *k] with spatial reversal
        w = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
        w = jnp.swapaxes(w, 0, 1)
        if groups > 1:
            # regroup: weight was [in, out/groups, *k]
            ic = weight.shape[0]
            oc_pg = weight.shape[1]
            w = jnp.reshape(weight, (groups, ic // groups, oc_pg) + k)
            w = jnp.flip(w, axis=tuple(range(3, 3 + n)))
            w = jnp.swapaxes(w, 1, 2)  # [groups, out/groups, in/groups, *k]
            w = jnp.reshape(w, (oc_pg * groups, ic // groups) + k)
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1,) * n, padding=tpad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)

    if bias is not None:
        if data_format.startswith("NC"):
            bshape = (1, -1) + (1,) * n
        else:
            bshape = (1,) * (1 + n) + (-1,)
        out = out + jnp.reshape(bias, bshape)
    return out


@eager_op
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format)


@eager_op
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format)


@eager_op
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format)


@eager_op
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, transpose=True, output_padding=output_padding)


@eager_op
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, transpose=True, output_padding=output_padding)


@eager_op
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, transpose=True, output_padding=output_padding)


__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]
