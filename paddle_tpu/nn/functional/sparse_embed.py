"""Sparse-gradient embedding lookup — the eager tape node whose backward
emits a RowSparseGrad (reference: embedding(sparse=True) → SelectedRows,
paddle/phi/core/selected_rows.h + kernels/selected_rows/).
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.autograd import PyLayer
from paddle_tpu.core.dispatch import unwrap, wrap_like
from paddle_tpu.core.sparse_grad import RowSparseGrad

__all__ = ["sparse_embedding_lookup"]


class _SparseEmbedding(PyLayer):
    @staticmethod
    def forward(ctx, weight, ids, padding_idx):
        w = unwrap(weight)
        ctx.ids = ids
        ctx.padding_idx = padding_idx
        ctx.wshape = w.shape
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return wrap_like(out)

    @staticmethod
    def backward(ctx, g):
        gv = unwrap(g)
        rows = ctx.ids.reshape(-1)
        vals = gv.reshape(-1, gv.shape[-1])
        if ctx.padding_idx is not None:
            # the padding row receives no gradient (its fwd output was
            # masked to zero anyway)
            vals = jnp.where((rows != ctx.padding_idx)[:, None], vals, 0.0)
        return RowSparseGrad(rows, vals, ctx.wshape)


def sparse_embedding_lookup(x, weight, padding_idx=None):
    ids = unwrap(x)
    if not jnp.issubdtype(ids.dtype, jnp.integer):
        raise TypeError(f"embedding ids must be integer, got {ids.dtype}")
    return _SparseEmbedding.apply(weight, ids, padding_idx)
