"""paddle_tpu.nn.functional — the functional op surface for nn
(parity: python/paddle/nn/functional/)."""

from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.attention import *  # noqa: F401,F403
from paddle_tpu.nn.functional.common import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import *  # noqa: F401,F403
from paddle_tpu.nn.functional.fused import *  # noqa: F401,F403
from paddle_tpu.nn.functional.loss import *  # noqa: F401,F403
from paddle_tpu.nn.functional.norm import *  # noqa: F401,F403
from paddle_tpu.nn.functional.pooling import *  # noqa: F401,F403
