"""Fused transformer-block functionals (reference role: the
``fused_attention`` / ``fused_feedforward`` / ``fused_bias_act`` python
APIs over phi/kernels/fusion) — thin dispatch wrappers over the Pallas
megakernels in ``ops/pallas/fused_block.py``.

These are the user-facing entry points; the llama decoder block and
``nn.Transformer`` layers route through them automatically behind
``PADDLE_TPU_FUSED_BLOCK`` (see the module docstring there for the
VMEM-residency design and the knob semantics)."""

from __future__ import annotations

from paddle_tpu.core.dispatch import eager_op
from paddle_tpu.ops.pallas import fused_block as _FB

__all__ = ["fused_rmsnorm_qkv", "fused_mlp", "fused_ffn",
           "fused_decoder_block"]


@eager_op
def fused_rmsnorm_qkv(x, norm_weight, wq, wk, wv, epsilon=1e-5):
    """``q, k, v = (rmsnorm(x) * norm_weight) @ (wq | wk | wv)`` — the
    normalized activations never round-trip HBM (single Pallas pass on
    TPU, reference math elsewhere/ineligible).  Differentiable wrt all
    array inputs."""
    return _FB.fused_rmsnorm_qkv(x, norm_weight, wq, wk, wv,
                                 epsilon=epsilon)


@eager_op
def fused_mlp(x, w_gate, w_up, w_down, activation="silu"):
    """SwiGLU ``down(act(gate(x)) * up(x))`` with the hidden
    intermediate VMEM-resident."""
    return _FB.fused_mlp(x, w_gate, w_up, w_down, activation=activation)


@eager_op
def fused_decoder_block(x, norm1_weight, wq, wk, wv, rope_cos, rope_sin,
                        wo, norm2_weight, wg, wu, wd, num_heads,
                        num_kv_heads, epsilon=1e-5):
    """One whole llama decoder block (rmsnorm → QKV → RoPE → causal
    attention → o-proj+residual → rmsnorm → SwiGLU MLP+residual) as a
    single Pallas pass — the block-boundary activations never
    round-trip HBM (``PADDLE_TPU_FUSED_BLOCK=decoder`` routes eligible
    llama layers here automatically).  Differentiable via
    block-boundary remat; ineligible shapes take the unfused reference
    composition."""
    return _FB.fused_decoder_block(
        x, norm1_weight, wq, wk, wv, rope_cos, rope_sin, wo,
        norm2_weight, wg, wu, wd, num_heads=num_heads,
        num_kv_heads=num_kv_heads, epsilon=epsilon)


@eager_op
def fused_ffn(x, w1, w2, b1=None, b2=None, activation="relu"):
    """Classic feed-forward ``act(x @ w1 + b1) @ w2 + b2`` with the
    hidden intermediate VMEM-resident (non-gated :func:`fused_mlp`)."""
    return _FB.fused_ffn(x, w1, w2, b1=b1, b2=b2, activation=activation)
