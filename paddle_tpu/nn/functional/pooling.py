"""Pooling ops (parity: python/paddle/nn/functional/pooling.py), via
lax.reduce_window."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op


def _ntuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    return t * n if len(t) == 1 else t


def _pool(x, kernel, stride, padding, n, data_format, op, ceil_mode=False,
          exclusive=True):
    kernel = _ntuple(kernel, n)
    stride = _ntuple(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pad_cfg = padding.upper()
    else:
        p = _ntuple(padding, n)
        if len(p) == 2 * n:
            pad_cfg = [(p[2 * i], p[2 * i + 1]) for i in range(n)]
        else:
            pad_cfg = [(pi, pi) for pi in p]

    chan_first = data_format.startswith("NC")
    if chan_first:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
        if not isinstance(pad_cfg, str):
            pads = [(0, 0), (0, 0)] + list(pad_cfg)
    else:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        if not isinstance(pad_cfg, str):
            pads = [(0, 0)] + list(pad_cfg) + [(0, 0)]
    if isinstance(pad_cfg, str):
        pads = pad_cfg

    if op == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, dims, strides,
                                     pads)
    # avg
    summed = jax.lax.reduce_window(x.astype(jnp.float32), 0.0, jax.lax.add,
                                   dims, strides, pads)
    if exclusive and not isinstance(pads, str):
        ones = jnp.ones_like(x, dtype=jnp.float32)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides,
                                       pads)
        return (summed / counts).astype(x.dtype)
    denom = 1
    for k in kernel:
        denom *= k
    return (summed / denom).astype(x.dtype)


@eager_op
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL"):
    out = _pool(x, kernel_size, stride, padding, 1, data_format, "max",
                ceil_mode)
    return out


@eager_op
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW"):
    if not return_mask:
        return _pool(x, kernel_size, stride, padding, 2, data_format,
                     "max", ceil_mode)
    # variadic reduce_window carrying (value, flat-HW-index) pairs — the
    # indices are what max_unpool2d consumes (reference contract: index
    # into the flattened H*W plane per channel)
    if data_format != "NCHW":
        raise NotImplementedError("return_mask: NCHW only")
    if ceil_mode:
        raise NotImplementedError("return_mask with ceil_mode=True is "
                                  "not supported (floor-mode shapes only)")
    if isinstance(padding, str):
        raise NotImplementedError("return_mask with string padding is "
                                  "not supported; pass explicit ints")
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    n, c, h, w = x.shape
    idx = jnp.broadcast_to(
        (jnp.arange(h)[:, None] * w + jnp.arange(w)[None, :]),
        (n, c, h, w)).astype(jnp.int32)
    dims = (1, 1, *kernel_size)
    strides = (1, 1, *stride)
    pads = ((0, 0), (0, 0), (padding[0], padding[0]),
            (padding[1], padding[1]))
    neg = jnp.finfo(jnp.float32).min

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    out, mask = jax.lax.reduce_window(
        (x.astype(jnp.float32), idx), (neg, jnp.int32(-1)), reducer,
        dims, strides, pads)
    return out.astype(x.dtype), mask


@eager_op
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW"):
    """Scatter pooled values back to their argmax positions (reference
    max_unpool2d; `indices` are the flat H*W positions max_pool2d
    returns with return_mask=True)."""
    if data_format != "NCHW":
        raise NotImplementedError("max_unpool2d: NCHW only")
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    n, c, ph, pw = x.shape
    if output_size is None:
        oh = (ph - 1) * stride[0] + kernel_size[0] - 2 * (
            padding if isinstance(padding, int) else padding[0])
        ow = (pw - 1) * stride[1] + kernel_size[1] - 2 * (
            padding if isinstance(padding, int) else padding[1])
    else:
        oh, ow = output_size[-2], output_size[-1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    # scatter-ASSIGN (reference semantics): overlapping windows sharing an
    # argmax carry the same value, so duplicates must not sum
    out = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        indices.reshape(n, c, -1)].set(x.reshape(n, c, -1))
    return out.reshape(n, c, oh, ow)


@eager_op
def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW"):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max",
                 ceil_mode)


@eager_op
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    return _pool(x, kernel_size, stride, padding, 1, data_format, "avg",
                 ceil_mode, exclusive)


@eager_op
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg",
                 ceil_mode, exclusive)


@eager_op
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg",
                 ceil_mode, exclusive)


def _adaptive_out(in_size, out_size):
    # emit start/end per output index (static shapes)
    import numpy as np
    starts = (np.arange(out_size) * in_size) // out_size
    ends = -(-((np.arange(out_size) + 1) * in_size) // out_size)
    return starts, ends


def _adaptive_pool(x, output_size, n, data_format, op):
    chan_first = data_format.startswith("NC")
    spatial_off = 2 if chan_first else 1
    out_sizes = _ntuple(output_size, n)
    arr = x
    for d in range(n):
        axis = spatial_off + d
        in_size = arr.shape[axis]
        o = out_sizes[d]
        if in_size % o == 0:
            # uniform windows → reshape+reduce (fast path)
            k = in_size // o
            new_shape = arr.shape[:axis] + (o, k) + arr.shape[axis + 1:]
            r = jnp.reshape(arr, new_shape)
            arr = jnp.max(r, axis=axis + 1) if op == "max" else \
                jnp.mean(r, axis=axis + 1)
        else:
            starts, ends = _adaptive_out(in_size, o)
            slices = []
            for s, e in zip(starts, ends):
                window = jax.lax.slice_in_dim(arr, int(s), int(e), axis=axis)
                red = jnp.max(window, axis=axis, keepdims=True) if op == "max" \
                    else jnp.mean(window, axis=axis, keepdims=True)
                slices.append(red)
            arr = jnp.concatenate(slices, axis=axis)
    return arr


@eager_op
def adaptive_avg_pool1d(x, output_size):
    return _adaptive_pool(x, output_size, 1, "NCL", "avg")


@eager_op
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


@eager_op
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


@eager_op
def adaptive_max_pool1d(x, output_size, return_mask=False):
    return _adaptive_pool(x, output_size, 1, "NCL", "max")


@eager_op
def adaptive_max_pool2d(x, output_size, return_mask=False):
    return _adaptive_pool(x, output_size, 2, "NCHW", "max")


@eager_op
def adaptive_max_pool3d(x, output_size, return_mask=False):
    return _adaptive_pool(x, output_size, 3, "NCDHW", "max")


__all__ = [_n for _n in list(globals())
           if _n.endswith(("pool1d", "pool2d", "pool3d"))]
