"""Normalization functionals (parity: python/paddle/nn/functional/norm.py).

batch_norm here is the pure compute; running-stat updates happen in the
BatchNorm layer (eager) or are returned functionally.  All fuse well under
XLA; rms_norm is the LLM hot path (kept in fp32 accumulation for bf16
inputs — TPU numerics practice)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op


@eager_op
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        ndim = 1
    else:
        ndim = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - ndim, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) / jnp.sqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@eager_op
def rms_norm(x, weight=None, epsilon=1e-6, axis=-1):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
    out = (xf * jnp.reciprocal(jnp.sqrt(ms + epsilon))).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


@eager_op
def rms_norm_residual(x, weight, residual=None, epsilon=1e-5):
    """(y, h): h = x (+ residual), y = RMSNorm(h) * weight — ONE fused
    Pallas pass on TPU (ops/pallas/rmsnorm.py; 1.38x over the XLA chain
    on v5e at 8192x4096 bf16 in isolation), reference-math elsewhere.
    The returned ``h`` is the pre-norm sum the next residual branch
    consumes.

    NOTE: inside a larger jitted step, prefer the plain-jnp chain — a
    custom kernel is a fusion barrier, and measured in the bench model it
    COSTS ~2 MFU points (llama.py:156 keeps the jnp path for exactly that
    reason).  This op is for standalone/serving use and for callers whose
    surrounding code XLA cannot fuse anyway."""
    import jax as _j

    from paddle_tpu.ops.pallas.rmsnorm import fused_rmsnorm
    return fused_rmsnorm(x, weight, residual=residual, epsilon=epsilon,
                         interpret=_j.default_backend() != "tpu",
                         use_pallas=None if _j.default_backend() == "tpu"
                         else False)


@eager_op
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None):
    chan_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else x.ndim - 1
    shape = [1] * x.ndim
    shape[chan_axis] = x.shape[chan_axis]
    reduce_axes = tuple(i for i in range(x.ndim) if i != chan_axis)

    use_batch = training and not use_global_stats
    xf = x.astype(jnp.float32)
    if use_batch:
        mean = jnp.mean(xf, axis=reduce_axes)
        var = jnp.var(xf, axis=reduce_axes)
    else:
        mean = running_mean
        var = running_var
    out = (xf - jnp.reshape(mean, shape)) / jnp.sqrt(
        jnp.reshape(var, shape) + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * jnp.reshape(weight, shape)
    if bias is not None:
        out = out + jnp.reshape(bias, shape)
    return out


def batch_norm_stats(x, data_format="NCHW"):
    """Pure helper: batch mean/var along non-channel axes (for layer-side
    running stat updates)."""
    from paddle_tpu.core.dispatch import dispatch

    def _stats(xv):
        chan_axis = 1 if data_format.startswith("NC") and xv.ndim > 1 \
            else xv.ndim - 1
        axes = tuple(i for i in range(xv.ndim) if i != chan_axis)
        xf = xv.astype(jnp.float32)
        return jnp.mean(xf, axis=axes), jnp.var(xf, axis=axes)

    return dispatch(_stats, x, op_name="batch_norm_stats")


@eager_op
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW"):
    # per-sample, per-channel normalization over spatial dims
    if data_format.startswith("NC"):
        axes = tuple(range(2, x.ndim))
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        axes = tuple(range(1, x.ndim - 1))
        shape = (1,) * (x.ndim - 1) + (-1,)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = ((xf - mean) / jnp.sqrt(var + eps)).astype(x.dtype)
    if weight is not None:
        out = out * jnp.reshape(weight, shape)
    if bias is not None:
        out = out + jnp.reshape(bias, shape)
    return out


@eager_op
def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW"):
    if data_format == "NCHW" or x.ndim == 2:
        b, c = x.shape[:2]
        spatial = x.shape[2:]
        xg = jnp.reshape(x, (b, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, xg.ndim))
        xf = xg.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        out = ((xf - mean) / jnp.sqrt(var + epsilon)).astype(x.dtype)
        out = jnp.reshape(out, x.shape)
        shape = (1, c) + (1,) * len(spatial)
    else:  # NHWC
        b = x.shape[0]
        c = x.shape[-1]
        spatial = x.shape[1:-1]
        xg = jnp.reshape(x, (b,) + spatial + (num_groups, c // num_groups))
        axes = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
        xf = xg.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        out = ((xf - mean) / jnp.sqrt(var + epsilon)).astype(x.dtype)
        out = jnp.reshape(out, x.shape)
        shape = (1,) * (x.ndim - 1) + (c,)
    if weight is not None:
        out = out * jnp.reshape(weight, shape)
    if bias is not None:
        out = out + jnp.reshape(bias, shape)
    return out


@eager_op
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    chan_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[chan_axis]
    pads = [(0, 0)] * x.ndim
    pads[chan_axis] = (half, size - 1 - half)
    sq = jnp.pad(sq, pads)
    acc = jnp.zeros_like(x, dtype=jnp.float32)
    for i in range(size):
        sl = [slice(None)] * x.ndim
        sl[chan_axis] = slice(i, i + c)
        acc = acc + sq[tuple(sl)].astype(jnp.float32)
    div = jnp.power(k + alpha * acc / size, beta).astype(x.dtype)
    return x / div


__all__ = ["layer_norm", "rms_norm", "rms_norm_residual", "batch_norm",
           "batch_norm_stats", "instance_norm", "group_norm",
           "local_response_norm"]
