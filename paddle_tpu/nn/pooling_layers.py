"""Pooling layers (parity: python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer import Layer

__all__ = ["MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
           "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _Pool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format=None, name=None, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        kwargs = {}
        if self.data_format is not None:
            kwargs["data_format"] = self.data_format
        return type(self)._fn(x, self.kernel_size, stride=self.stride,
                              padding=self.padding, ceil_mode=self.ceil_mode,
                              **kwargs)


class MaxPool1D(_Pool):
    _fn = staticmethod(F.max_pool1d)


class MaxPool2D(_Pool):
    _fn = staticmethod(F.max_pool2d)


class MaxPool3D(_Pool):
    _fn = staticmethod(F.max_pool3d)


class AvgPool1D(_Pool):
    _fn = staticmethod(F.avg_pool1d)


class AvgPool2D(_Pool):
    _fn = staticmethod(F.avg_pool2d)


class AvgPool3D(_Pool):
    _fn = staticmethod(F.avg_pool3d)


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, data_format=None, return_mask=False,
                 name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return type(self)._fn(x, self.output_size)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool1d)


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool2d)


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool3d)


class AdaptiveMaxPool1D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool1d)


class AdaptiveMaxPool2D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool2d)


class AdaptiveMaxPool3D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool3d)
